"""Replica voting over state fingerprints + the shadow-step audit.

The detection side of the integrity plane (doc/robustness.md
"Integrity plane").  Every ``integrity_every`` rounds the trainer's
live state — params and (when present) updater state — is digested
per (leaf, device) with :mod:`.fingerprint` and the digests are voted:

* **intra-process**: every local device holding a replica of the same
  logical slice must agree bitwise;
* **cross-process**: the per-rank digest blocks are allgathered (u32
  words — no float transport, nothing to truncate) and every replica
  of the same (leaf, slice) group must agree bitwise.

Under ``det_reduce`` the train step is bitwise deterministic, so any
disagreement IS corruption — there is no tolerance knob.  A strict
majority names the corrupt minority replica and the verdict is a typed
:class:`IntegrityError{rank, tensor, kind}`; the CLI turns that into
elastic quarantine (the named rank is evicted, survivors reload the
last *fingerprint-verified* checkpoint, so state poisoned by a corrupt
rank's gradient contributions after the flip is discarded too).

The **shadow-step audit** guards compute rather than state: the
sampled round's grad program is re-traced into an independent second
executable and both are executed on identical probe inputs; loss and
every gradient leaf must match bitwise.  A deterministic miscompile
that lowers the two traces differently (the PR-9 GSPMD concat class),
or a flaky core that computes the same program differently twice,
surfaces as ``kind="shadow"``.  (Two executables that miscompile
*identically* are outside the threat model — that failure needs
cross-hardware voting, which the state fingerprints provide at the
next round boundary once the wrong values land in params.)

The vote is computed from the full allgathered matrix on EVERY rank,
so all ranks reach the identical verdict without an extra collective —
the corrupt rank learns its own name and self-quarantines while the
survivors evict it.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import events as obs_events
from ..obs.registry import registry as obs_registry
from .fingerprint import Digest, digest_device_array, digest_array


class IntegrityError(RuntimeError):
    """Silent-data-corruption verdict.

    ``rank`` is the corrupt process index when the vote named one
    (None = ambiguous or local-only), ``tensor`` the first disagreeing
    leaf, ``kind`` one of ``state`` (fingerprint vote), ``shadow``
    (grad-program re-execution mismatch), ``canary`` (serve golden
    probe mismatch)."""

    def __init__(self, message: str, *, kind: str = "state",
                 rank: Optional[int] = None,
                 tensor: Optional[str] = None) -> None:
        super().__init__(message)
        self.kind = kind
        self.rank = rank
        self.tensor = tensor


def _slice_key(index) -> tuple:
    return tuple(
        (s.start, s.stop, s.step) if isinstance(s, slice) else s
        for s in index
    )


def _leaves(trainer):
    """(name, array) over params + updater state, in the sorted order
    every rank reproduces independently (the allgather contract)."""
    for key in sorted(trainer.params):
        for tag in sorted(trainer.params[key]):
            yield f"{key}/{tag}", trainer.params[key][tag]
    if trainer.save_ustate and trainer.ustates:
        for key in sorted(trainer.ustates):
            for tag in sorted(trainer.ustates[key]):
                slots = trainer.ustates[key][tag]
                for slot in sorted(slots):
                    yield f"ust:{key}/{tag}@{slot}", slots[slot]


def local_fingerprints(trainer) -> Tuple[List[Digest], List[tuple]]:
    """Digest every (leaf, local device) shard.  Returns (rows, keys)
    where ``keys[i] = (leaf_name, slice_key)`` — replicated leaves get
    the full-extent slice, so one uniform group-by covers both the
    replicated and the ZeRO-sharded layouts."""
    rows: List[Digest] = []
    keys: List[tuple] = []
    for name, arr in _leaves(trainer):
        shards = getattr(arr, "addressable_shards", None)
        if not shards:
            a = np.asarray(arr)
            rows.append(digest_array(a))
            keys.append((name, _slice_key(tuple(
                slice(0, s, None) for s in a.shape))))
            continue
        for s in sorted(shards, key=lambda s: s.device.id):
            rows.append(digest_device_array(
                s.data, index=s.index, shape=arr.shape))
            keys.append((name, _slice_key(s.index)))
    return rows, keys


def _peer_keys(trainer) -> List[tuple]:
    """Recompute every process's (leaf, slice) key sequence from the
    shardings' global device->slice maps — deterministic and identical
    on every rank, so the allgathered digest block needs no key
    transport."""
    import jax

    out: List[tuple] = []
    per_leaf = []
    for name, arr in _leaves(trainer):
        sh = getattr(arr, "sharding", None)
        shape = tuple(int(d) for d in np.shape(arr))
        per_leaf.append((name, sh, shape))
    for p in range(jax.process_count()):
        for name, sh, shape in per_leaf:
            if sh is None:
                out.append((name, _slice_key(tuple(
                    slice(0, s, None) for s in shape))))
                continue
            imap = sh.devices_indices_map(shape)
            for d in sorted((d for d in imap if d.process_index == p),
                            key=lambda d: d.id):
                out.append((name, _slice_key(imap[d])))
    return out


def vote(groups: Dict[tuple, List[Tuple[int, Digest]]]) -> List[dict]:
    """Majority vote within every (leaf, slice) replica group.

    ``groups[key]`` is ``[(rank, digest), ...]``.  Returns findings:
    one dict per disagreeing group with the named corrupt ``rank``
    (the strict-minority holder) or ``rank=None`` when the group is
    too small or too split to name one (2-way ties, 2-replica groups).
    Single-replica groups are unvotable and always clean."""
    findings: List[dict] = []
    for (name, _sk), members in sorted(groups.items()):
        if len(members) < 2:
            continue
        counts = collections.Counter(d for _r, d in members)
        if len(counts) == 1:
            continue
        top, top_n = counts.most_common(1)[0]
        if top_n * 2 > len(members):
            bad = sorted({r for r, d in members if d != top})
            findings.append({
                "tensor": name,
                "ranks": bad,
                "rank": bad[0] if len(bad) == 1 else None,
                "replicas": len(members),
            })
        else:
            findings.append({
                "tensor": name,
                "ranks": sorted({r for r, _d in members}),
                "rank": None,
                "replicas": len(members),
            })
    return findings


def check_state(trainer) -> dict:
    """One fingerprint sweep + vote.  Returns the verdict dict
    ``{"clean": bool, "findings": [...], "replicas": int,
    "elapsed_s": float}``; identical on every rank (the vote runs on
    the full allgathered matrix)."""
    import jax

    t0 = time.perf_counter()
    rows, keys = local_fingerprints(trainer)
    my_rank = jax.process_index()
    groups: Dict[tuple, List[Tuple[int, Digest]]] = {}
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        block = np.asarray(rows, np.uint32).reshape(-1)
        all_blocks = np.asarray(
            multihost_utils.process_allgather(block)
        ).reshape(jax.process_count(), -1, 2)
        all_keys = _peer_keys(trainer)
        if len(all_keys) != all_blocks.shape[0] * all_blocks.shape[1]:
            raise IntegrityError(
                "fingerprint/key count mismatch across processes "
                f"({len(all_keys)} keys vs {all_blocks.shape} digests) "
                "— ranks disagree on the state tree itself",
                kind="state")
        i = 0
        for p in range(all_blocks.shape[0]):
            for j in range(all_blocks.shape[1]):
                d = (int(all_blocks[p, j, 0]), int(all_blocks[p, j, 1]))
                groups.setdefault(all_keys[i], []).append((p, d))
                i += 1
    else:
        for k, d in zip(keys, rows):
            groups.setdefault(k, []).append((my_rank, d))
    findings = vote(groups)
    return {
        "clean": not findings,
        "findings": findings,
        "replicas": max((len(g) for g in groups.values()), default=1),
        "elapsed_s": time.perf_counter() - t0,
    }


class IntegrityPlane:
    """Round-boundary integrity driver: cadence, metrics, events, and
    the typed verdict.  One instance per LearnTask; survives trainer
    rebuilds (the trainer is passed per call)."""

    def __init__(self, every: int = 0, shadow: int = 0) -> None:
        self.every = int(every)
        self.shadow = int(shadow)
        #: newest round whose post-round state passed the vote — the
        #: quarantine rollback bound (survivors must NOT resume from a
        #: checkpoint the corrupt rank's gradients already poisoned)
        self.last_clean_round: Optional[int] = None
        self.checks = 0
        self.last_elapsed_s = 0.0

    def due(self, round_: int) -> bool:
        return self.every > 0 and (round_ + 1) % self.every == 0

    # ------------------------------------------------------------------
    def _count(self, kind: str, verdict: str) -> None:
        obs_registry().counter(
            "integrity_checks_total",
            "Integrity-plane checks by kind and verdict.",
            labelnames=("kind", "verdict"),
        ).labels(kind=kind, verdict=verdict).inc()
        if verdict != "clean":
            obs_registry().counter(
                "integrity_failures_total",
                "Integrity-plane corruption verdicts.",
                labelnames=("kind",),
            ).labels(kind=kind).inc()

    def _fail(self, kind: str, round_: int, *, rank=None, tensor=None,
              detail: str = "") -> IntegrityError:
        self._count(kind, "corrupt")
        obs_registry().gauge(
            "integrity_corrupt_rank",
            "Process index named corrupt by the last vote (-1 none).",
        ).set(-1 if rank is None else rank)
        obs_events.emit("integrity.detect", kind=kind, round=round_,
                        rank=rank, tensor=tensor, detail=detail)
        return IntegrityError(
            f"integrity {kind} check failed at round {round_}: "
            f"{detail or 'replica digests disagree'}"
            + (f" (corrupt rank {rank})" if rank is not None else "")
            + (f" tensor {tensor}" if tensor else ""),
            kind=kind, rank=rank, tensor=tensor)

    # ------------------------------------------------------------------
    def check_round(self, trainer, round_: int) -> Optional[dict]:
        """Run the due checks for ``round_``; raises
        :class:`IntegrityError` on a corruption verdict, updates
        ``last_clean_round`` and emits ``integrity.clean`` otherwise."""
        if not self.due(round_):
            return None
        self.checks += 1
        verdict = check_state(trainer)
        self.last_elapsed_s = verdict["elapsed_s"]
        if not verdict["clean"]:
            f = verdict["findings"][0]
            raise self._fail(
                "state", round_, rank=f["rank"], tensor=f["tensor"],
                detail=(f"{len(verdict['findings'])} tensor(s) disagree, "
                        f"first {f['tensor']} ranks {f['ranks']} "
                        f"({f['replicas']} replicas)"))
        self._count("state", "clean")
        if self.shadow:
            mismatch = trainer.shadow_step(round_)
            if mismatch is not None:
                raise self._fail("shadow", round_,
                                 tensor=mismatch.get("tensor"),
                                 detail=mismatch.get("detail", ""))
            self._count("shadow", "clean")
        self.last_clean_round = round_
        obs_registry().gauge(
            "integrity_corrupt_rank",
            "Process index named corrupt by the last vote (-1 none).",
        ).set(-1)
        obs_events.emit("integrity.clean", round=round_,
                        elapsed_s=round(verdict["elapsed_s"], 6),
                        replicas=verdict["replicas"])
        return verdict

    def snapshot(self) -> dict:
        """Telemetry block for the round record."""
        return {
            "every": self.every,
            "checks": self.checks,
            "last_clean_round": self.last_clean_round,
            "last_elapsed_s": round(self.last_elapsed_s, 6),
        }
