"""Order-independent per-tensor digests for corruption detection.

The digest must satisfy three properties at once:

1. **Sensitivity**: any single flipped bit changes it.  ``s1`` is a
   plain modular word sum, so a one-bit flip shifts it by exactly
   ``±2^b mod 2^32 != 0`` — no single-bit flip can cancel.
2. **Layout invariance**: the same logical tensor sharded over ANY mesh
   (1-process, 4-process zero=1, 8-way zero=3) digests to the same
   value.  Modular sums commute, and ``s2`` weights each word by its
   *global* flat index — a property of the logical tensor, not of the
   shard that happens to hold it — so per-shard partial digests combine
   by plain modular addition regardless of how the mesh carved it up.
3. **Cheapness**: the reduction is jitted and runs on the device that
   holds the shard; only two u32 words cross the host boundary per
   (leaf, device).

Definition (little-endian canonical element encoding, C order):

* ``word[i]`` = the ``i``-th machine word of the tensor, widened to
  u32: the u32 bit pattern for 4-byte dtypes, the u16 pattern for
  2-byte dtypes, the byte for 1-byte dtypes, and an (lo, hi) u32 pair
  for 8-byte dtypes (words-per-element ``wpe = max(1, itemsize//4)``
  for >=4-byte dtypes).
* global word index ``g(i) = element_global_flat_index * wpe + k``.
* ``s1 = sum_i word[i] mod 2^32``
* ``s2 = sum_i word[i] * (g(i) + 1 mod 2^32) mod 2^32``

All arithmetic is u32 wraparound, which the jitted path gets for free
from XLA's two's-complement ops and the numpy oracle reproduces via
u64 intermediates reduced mod 2^32 (identical by ring homomorphism).
Tensors above 2^32 words alias their index weights; ``s1`` keeps full
single-flip sensitivity regardless.
"""

from __future__ import annotations

import functools
from typing import Iterable, Sequence, Tuple

import numpy as np

Digest = Tuple[int, int]

_M32 = np.uint64(0xFFFFFFFF)


def _as_words(a: np.ndarray) -> Tuple[np.ndarray, int]:
    """Flat u64-widened machine words of ``a`` plus words-per-element."""
    a = np.ascontiguousarray(a)
    itemsize = a.dtype.itemsize
    if itemsize == 4:
        w, wpe = a.view(np.uint32), 1
    elif itemsize == 2:
        w, wpe = a.view(np.uint16), 1
    elif itemsize == 1:
        w, wpe = a.view(np.uint8), 1
    elif itemsize == 8:
        w, wpe = a.view(np.uint32), 2  # little-endian (lo, hi) pairs
    else:
        raise TypeError(f"digest: unsupported itemsize {itemsize} "
                        f"(dtype {a.dtype})")
    return w.reshape(-1).astype(np.uint64), wpe


def _global_word_index(shape: Sequence[int], index, wpe: int) -> np.ndarray:
    """u64 global word indices for the local block ``index`` (a tuple of
    slices into an array of logical ``shape``), C order."""
    shape = tuple(int(s) for s in shape)
    strides = np.ones(len(shape), np.uint64)
    for d in range(len(shape) - 2, -1, -1):
        strides[d] = strides[d + 1] * np.uint64(shape[d + 1])
    gi = np.zeros((), np.uint64)
    for d, sl in enumerate(index):
        start = np.uint64(sl.start or 0)
        stop = sl.stop if sl.stop is not None else shape[d]
        n = int(stop) - int(sl.start or 0)
        offs = (start + np.arange(n, dtype=np.uint64)) * strides[d]
        gi = gi[..., None] + offs.reshape((1,) * d + (n,))
    if wpe == 1:
        return gi.reshape(-1)
    gi = gi.reshape(-1, 1) * np.uint64(wpe) + np.arange(wpe, dtype=np.uint64)
    return gi.reshape(-1)


def digest_array(a: np.ndarray, index=None, shape=None) -> Digest:
    """Numpy oracle: digest of ``a``, or of the local block ``a`` sitting
    at slice ``index`` of a logical tensor of ``shape``."""
    a = np.asarray(a)
    words, wpe = _as_words(a)
    if index is None:
        gi = np.arange(words.size, dtype=np.uint64)
    else:
        gi = _global_word_index(shape if shape is not None else a.shape,
                                index, wpe)
        if gi.size != words.size:
            raise ValueError(
                f"digest: block {a.shape} does not match slice {index} "
                f"of {shape}")
    s1 = int(words.sum() & _M32)
    s2 = int((words * ((gi & _M32) + np.uint64(1) & _M32) & _M32).sum()
             & _M32)
    return (s1, s2)


def combine_digests(parts: Iterable[Digest]) -> Digest:
    """Combine per-shard partial digests of ONE tensor (each computed
    with its own global offsets) into the full-tensor digest."""
    s1 = s2 = 0
    for p1, p2 in parts:
        s1 = (s1 + p1) & 0xFFFFFFFF
        s2 = (s2 + p2) & 0xFFFFFFFF
    return (s1, s2)


# ----------------------------------------------------------------------
# jitted on-device digest


@functools.lru_cache(maxsize=None)
def _digest_program(shape: tuple, dtype_str: str, logical_shape: tuple):
    """Compiled per-(local shape, dtype, logical shape) digest kernel;
    slice ``starts`` ride as a traced vector so every shard of a leaf —
    and every round — reuses one executable."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    dtype = np.dtype(dtype_str)
    itemsize = dtype.itemsize
    wpe = 2 if itemsize == 8 else 1
    strides = np.ones(max(len(logical_shape), 1), np.uint32)
    for d in range(len(logical_shape) - 2, -1, -1):
        strides[d] = strides[d + 1] * np.uint32(logical_shape[d + 1])

    def fn(x, starts):
        if itemsize == 4:
            w = lax.bitcast_convert_type(x, jnp.uint32)
        elif itemsize == 2:
            w = lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
        elif itemsize == 1:
            w = lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.uint32)
        else:  # itemsize 8 -> trailing (lo, hi) u32 axis
            w = lax.bitcast_convert_type(x, jnp.uint32)
        # global element index of every local element (u32 wraparound
        # matches the oracle's u64-mod-2^32 by ring homomorphism)
        gi = jnp.zeros(shape, jnp.uint32)
        for d in range(len(shape)):
            offs = starts[d] + lax.iota(jnp.uint32, shape[d])
            gi = gi + jnp.expand_dims(
                offs * jnp.uint32(strides[d]),
                axis=tuple(i for i in range(len(shape)) if i != d))
        if wpe == 2:
            gi = gi[..., None] * jnp.uint32(2) + lax.iota(
                jnp.uint32, 2)
        w = w.reshape(-1)
        gi = gi.reshape(-1)
        s1 = jnp.sum(w, dtype=jnp.uint32)
        s2 = jnp.sum(w * (gi + jnp.uint32(1)), dtype=jnp.uint32)
        return jnp.stack([s1, s2])

    return jax.jit(fn)


def digest_device_array(x, index=None, shape=None) -> Digest:
    """Digest a single-device jax array (one shard's ``.data``) on the
    device that holds it.  ``index``/``shape`` place the block inside
    its logical tensor (omit for a full replica)."""
    lshape = tuple(int(s) for s in (shape if shape is not None else x.shape))
    starts = np.zeros(max(len(x.shape), 1), np.uint32)
    if index is not None:
        for d, sl in enumerate(index):
            starts[d] = np.uint32(sl.start or 0)
    prog = _digest_program(tuple(int(s) for s in x.shape),
                           np.dtype(x.dtype).str, lshape)
    out = np.asarray(prog(x, starts[:max(len(x.shape), 1)]))
    return (int(out[0]), int(out[1]))


def digest_global(arr) -> Digest:
    """Full-tensor digest of a (possibly sharded) jax array, combined
    from one addressable replica of every distinct slice.  Requires all
    slices addressable (single-process meshes / gathered arrays); the
    cross-process path votes on per-shard digests instead."""
    sh = getattr(arr, "sharding", None)
    shards = getattr(arr, "addressable_shards", None)
    if sh is None or not shards:
        return digest_array(np.asarray(arr))
    seen = {}
    for s in sorted(shards, key=lambda s: s.device.id):
        key = tuple((sl.start, sl.stop, sl.step) for sl in s.index)
        if key not in seen:
            seen[key] = digest_device_array(
                s.data, index=s.index, shape=arr.shape)
    return combine_digests(seen.values())
