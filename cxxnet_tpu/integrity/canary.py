"""Serve golden canary: a manifest-committed probe batch whose score
CRC must stay stable for the lifetime of a loaded model.

The serving analog of the trainer's state fingerprints: the engine
cannot vote with replicas it does not know about, but it CAN hold its
own compute to a golden answer.  At model load the engine scores the
probe batch committed in the checkpoint manifest (``probe`` block:
deterministic seed + row count, plus the CRC the trainer recorded at
save time) and records the CRC of the scores; from then on a periodic
re-score must reproduce that CRC bit-for-bit — the model bytes and the
predict program are frozen between reloads, so ANY drift is memory or
compute corruption, and ``/healthz`` degrades with the
``integrity_failed`` reason token (the fleet supervisor ejects the
replica from rotation, without killing it, and readmits it when a
later canary comes back clean — see serve/fleet.py).

The trainer-recorded golden is only binding when the engine scores
through the same program class (same backend, no quantized sibling
preferred): a legitimate pipeline difference (int8 weights, another
backend's FMA contraction) re-bases the golden at load with an
``integrity.golden_rebased`` event instead of a false alarm.
"""

from __future__ import annotations

import zlib
from typing import Optional, Tuple

import numpy as np


def probe_batch(seed: int, rows: int, shape: Tuple[int, ...]) -> np.ndarray:
    """The deterministic probe: ``rows`` samples of per-example
    ``shape``, uniform [0, 1) f32 from a fixed PCG — reproducible from
    the (seed, rows, shape) triple alone, which is all the manifest
    commits."""
    rng = np.random.RandomState(int(seed) & 0x7FFFFFFF)
    return rng.random_sample((int(rows),) + tuple(shape)).astype(np.float32)


def scores_crc(scores: np.ndarray) -> int:
    """CRC32 over the canonical encoding of the score tensor (shape
    header + little-endian f32 bytes): bit-exact, shape-sensitive."""
    a = np.ascontiguousarray(np.asarray(scores, np.float32))
    head = ("x".join(str(int(d)) for d in a.shape) + ":").encode()
    return zlib.crc32(a.tobytes(), zlib.crc32(head)) & 0xFFFFFFFF


def make_probe_block(seed: int, rows: int, shape: Tuple[int, ...],
                     crc: Optional[int], backend: str) -> dict:
    """The manifest ``probe`` block (written by the trainer at save
    when ``integrity_probe = 1``)."""
    block = {
        "seed": int(seed),
        "rows": int(rows),
        "shape": [int(d) for d in shape],
        "backend": backend,
    }
    if crc is not None:
        block["crc32"] = int(crc) & 0xFFFFFFFF
    return block


def block_matches_pipeline(block: dict, *, backend: str,
                           quant: bool) -> bool:
    """Is the trainer-recorded golden binding for THIS engine's scoring
    pipeline?  Different backend or a quantized sibling legitimately
    changes the scores — rebase instead of alarm."""
    return (not quant) and block.get("backend") == backend \
        and block.get("crc32") is not None
