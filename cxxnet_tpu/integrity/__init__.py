"""Integrity plane: silent-data-corruption detection for live state.

PR 15 made every byte that reaches DISK crash-consistent and
CRC-audited; this package guards the bytes that live in device/host
memory and the compute that produces them, between checkpoints
(doc/robustness.md "Integrity plane"):

* :mod:`.fingerprint` — an order-independent per-tensor digest that is
  bitwise-identical across mesh layouts, with a jitted on-device
  reduction and a pure-numpy oracle.
* :mod:`.plane` — replica voting over allgathered fingerprints
  (majority names the corrupt minority rank → :class:`IntegrityError`
  → elastic quarantine), plus the shadow-step audit that re-executes a
  sampled grad program through an independently traced executable.
* :mod:`.canary` — the serve golden canary: a manifest-committed probe
  batch whose score CRC must stay stable for the lifetime of a loaded
  model (mismatch degrades ``/healthz`` with ``integrity_failed``).
"""

from .fingerprint import combine_digests, digest_array, digest_device_array
from .plane import IntegrityError, IntegrityPlane

__all__ = [
    "IntegrityError",
    "IntegrityPlane",
    "combine_digests",
    "digest_array",
    "digest_device_array",
]
