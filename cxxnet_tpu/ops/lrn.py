"""Cross-channel local response normalization as a fused Pallas kernel.

Semantics (parity: ``/root/reference/src/layer/lrn_layer-inl.hpp`` —
``out = x * (knorm + alpha/n * sum_win(x^2))^-beta`` with the window of
``n`` channels ``[c-n/2, c-n/2+n)`` clipped at the edges, the ``chpool``
expression).

Why a kernel: XLA lowers the channel-window sum to ``reduce_window`` over
the minor (lane) dimension, which materializes a windowed intermediate and
runs on the VPU unfused.  The Pallas version keeps one ``(rows, C)`` block
in VMEM, computes the window as ``n`` static shifted adds, and fuses the
power/multiply — one HBM round trip for forward and one for backward
(which recomputes the norm instead of saving it: LRN sits on big
activations, so memory beats FLOPs here; same trade as
``jax.checkpoint``).

Backward derivation: with ``s_c = Σ_{d∈W} x²_{c+d}``, ``norm = k + a·s``,
``a = alpha/n``, ``out_c = x_c · norm_c^{-β}``:

``dx_j = g_j·norm_j^{-β} − 2aβ·x_j·Σ_{d∈W} (g·x·norm^{-β-1})_{j-d}``

i.e. the same shifted-add window, reversed.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

_BLOCK_ROWS = 256


def _window_offsets(nsize: int) -> Tuple[int, int]:
    """Window [c-half, c-half+nsize) → offsets -half .. nsize-1-half."""
    half = nsize // 2
    return -half, nsize - 1 - half


def _shifted_sum(v: jnp.ndarray, lo: int, hi: int) -> jnp.ndarray:
    """Σ_d v[:, c+d] for d in [lo, hi], zero-padded at the edges.

    Static shifts only — lowers to lane rotations/selects on the VPU.
    """
    c = v.shape[-1]
    zero = jnp.zeros_like(v)
    acc = None
    for d in range(lo, hi + 1):
        if d == 0:
            sh = v
        elif d > 0:
            sh = jnp.concatenate([v[:, d:], zero[:, :d]], axis=-1)
        else:
            sh = jnp.concatenate([zero[:, d:], v[:, :c + d]], axis=-1)
        acc = sh if acc is None else acc + sh
    return acc


def _fwd_kernel(x_ref, o_ref, *, nsize, alpha, beta, knorm):
    x = x_ref[:].astype(jnp.float32)
    lo, hi = _window_offsets(nsize)
    s = _shifted_sum(x * x, lo, hi)
    norm = knorm + (alpha / nsize) * s
    o_ref[:] = (x * norm ** (-beta)).astype(o_ref.dtype)


def _bwd_kernel(x_ref, g_ref, dx_ref, *, nsize, alpha, beta, knorm):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    a = alpha / nsize
    lo, hi = _window_offsets(nsize)
    s = _shifted_sum(x * x, lo, hi)
    norm = knorm + a * s
    t = g * x * norm ** (-beta - 1.0)
    back = _shifted_sum(t, -hi, -lo)  # reversed window
    dx_ref[:] = (g * norm ** (-beta) - 2.0 * a * beta * x * back).astype(
        dx_ref.dtype
    )


def _as_rows(x: jnp.ndarray) -> Tuple[jnp.ndarray, Tuple[int, ...], int]:
    """NHWC (or (N,C)) → (M, C) padded to a block-row multiple."""
    shape = x.shape
    c = shape[-1]
    m = int(np.prod(shape[:-1]))
    x2 = x.reshape(m, c)
    pad = (-m) % _BLOCK_ROWS
    if pad:
        x2 = jnp.concatenate(
            [x2, jnp.zeros((pad, c), x2.dtype)], axis=0
        )
    return x2, shape, m


def _call(kernel, out_dtype, args, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x2 = args[0]
    m, c = x2.shape
    grid = (m // _BLOCK_ROWS,)
    spec = pl.BlockSpec(
        (_BLOCK_ROWS, c), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, c), out_dtype),
        grid=grid,
        in_specs=[spec] * len(args),
        out_specs=spec,
        interpret=interpret,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn(x, nsize: int = 3, alpha: float = 0.001, beta: float = 0.75,
        knorm: float = 1.0, interpret: bool = False):
    """Fused LRN over the channel (minor) dim of an NHWC/(N,C) array."""
    x2, shape, m = _as_rows(x)
    kern = functools.partial(
        _fwd_kernel, nsize=nsize, alpha=alpha, beta=beta, knorm=knorm
    )
    out = _call(kern, x.dtype, (x2,), interpret)
    return out[:m].reshape(shape)


def _lrn_fwd(x, nsize, alpha, beta, knorm, interpret):
    return lrn(x, nsize, alpha, beta, knorm, interpret), x


def _lrn_bwd(nsize, alpha, beta, knorm, interpret, x, g):
    x2, shape, m = _as_rows(x)
    g2, _, _ = _as_rows(g)
    kern = functools.partial(
        _bwd_kernel, nsize=nsize, alpha=alpha, beta=beta, knorm=knorm
    )
    dx = _call(kern, x.dtype, (x2, g2), interpret)
    return (dx[:m].reshape(shape),)


lrn.defvjp(_lrn_fwd, _lrn_bwd)


def lrn_matmul(x, nsize: int = 3, alpha: float = 0.001, beta: float = 0.75,
               knorm: float = 1.0):
    """LRN whose channel-window sum is a banded C×C matmul — MXU work.

    Scope: targets the *small-C* LRN layers the zoo actually has
    (GoogLeNet/AlexNet, C ≤ 192), where reduce_window's shifted adds
    are VPU-bound and the dense band is tiny.  The band costs O(C²)
    FLOPs and a C×C operand per call vs reduce_window's O(C·nsize) —
    at C ≥ 1024 the matmul form is a large FLOP regression; keep the
    default `lrn_impl = xla` there.

    The window sum ``win[c] = sum_{c-half <= j < c-half+nsize} x²[j]``
    is ``x² @ B`` with ``B[j, c] = 1`` on the band (same clipped-edge
    semantics as ``lrn_xla``'s reduce_window padding).  Flattened to
    ``(N·H·W, C) @ (C, C)`` this is exactly MXU-shaped, and autodiff's
    backward is another banded GEMM (``@ Bᵀ``) — no reduce_window, no
    shifted-add chain on the VPU.  f32 accumulation in the GEMM (one
    rounding) vs the shifted-add chain's per-add rounding: same-or-better
    numerics.
    """
    c = x.shape[-1]
    half = nsize // 2
    j = jnp.arange(c)
    # band rows j, cols c: win[c] sums j in [c - half, c + nsize-1-half]
    d = j[:, None] - j[None, :]
    band = ((d >= -half) & (d <= nsize - 1 - half)).astype(x.dtype)
    sq = x * x
    win = jnp.matmul(
        sq.reshape(-1, c), band, preferred_element_type=jnp.float32
    ).astype(x.dtype).reshape(x.shape)
    norm = knorm + (alpha / nsize) * win
    return x * norm ** (-beta)


def lrn_xla(x, nsize: int = 3, alpha: float = 0.001, beta: float = 0.75,
            knorm: float = 1.0):
    """Stock-XLA reference implementation (reduce_window over channels).

    The golden model for the Pallas kernel's pairtest and the fallback
    for backends without Pallas support.
    """
    from jax import lax

    half = nsize // 2
    sq = x * x
    win = lax.reduce_window(
        sq,
        sq.dtype.type(0.0),
        lax.add,
        window_dimensions=(1,) * (x.ndim - 1) + (nsize,),
        window_strides=(1,) * x.ndim,
        padding=((0, 0),) * (x.ndim - 1) + ((half, nsize - 1 - half),),
    )
    norm = knorm + (alpha / nsize) * win
    return x * norm ** (-beta)
