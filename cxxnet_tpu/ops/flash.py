"""Fused flash attention as Pallas TPU kernels.

``ops/attention.mha`` is the golden model: it materializes the full
``(B, H, T, T)`` score matrix in HBM, which is both the memory ceiling
for long sequences (8k tokens at b8/h16 is ~32 GB of scores in f32) and
an extra HBM round-trip per step.  This kernel runs the standard
flash-attention recurrence — blockwise scores with an online
(log-sum-exp) softmax — entirely in VMEM: scores never touch HBM, and
memory is O(T) instead of O(T^2).

The backward pass is the flash recomputation scheme: the forward saves
only the per-row LSE (``m + log l``); two backward kernels re-derive the
probability blocks from (q, k, lse) and accumulate

* ``dq_i  = sum_j  [p_ij * (do_i . v_j - delta_i)] k_j * scale``
* ``dk_j  = sum_i  [p_ij * (do_i . v_j - delta_i)] q_i * scale``
* ``dv_j  = sum_i  p_ij^T do_i``

with ``delta_i = sum_d dO_id O_id`` computed once in XLA.

Layout contract matches ``ops/attention``: ``q, k, v`` are
``(B, T, H, Dh)``; internally heads fold into the grid's batch dim and
blocks are ``(block, Dh)`` tiles.  Causal masking predicates whole
skipped blocks (``pl.when``), so the causal kernel does ~half the FLOPs.
All accumulation is f32 regardless of input dtype (bf16 in, bf16 out,
f32 recurrence — the same discipline as the XLA path's
``preferred_element_type``).

``interpret=True`` runs the identical kernels on CPU for golden tests
(the PairTest discipline, SURVEY §4.1).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ._compat import pallas_tpu_compiler_params

NEG_INF = -1e30


def _dims(seq):
    return dict(dimension_semantics=seq)


def _mask(tq: int, tk: int, q_off, k_off):
    from jax import lax

    qi = q_off + lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
    ki = k_off + lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
    return qi >= ki


def _live(qo_ref, ko_ref, iq, ik, bq, bk, causal, dyn):
    """Causal block-liveness: can this (iq, ik) block contribute at all?
    Static offsets fold at trace time (the plain flash path); dynamic
    offsets read the SMEM scalars — ``pl.when`` accepts traced
    predicates, so a fully-future ring hop skips all compute."""
    if not causal:
        return True
    if dyn:
        return (qo_ref[0, 0] + iq * bq + bq - 1
                >= ko_ref[0, 0] + ik * bk)
    return iq * bq + bq - 1 >= ik * bk


def _fwd_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc, m, l, *, bq, bk, causal, dyn, scale):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, NEG_INF)
        l[:] = jnp.zeros_like(l)

    live = _live(qo_ref, ko_ref, iq, ik, bq, bk, causal, dyn)

    @pl.when(live)
    def _block():
        qb = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = jnp.where(
                _mask(bq, bk, qo_ref[0, 0] + iq * bq,
                      ko_ref[0, 0] + ik * bk),
                s, NEG_INF,
            )
        m_prev = m[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            # a query row fully masked within a live block leaves m_new at
            # NEG_INF, making exp(s - m_new) = 1 for every masked entry;
            # zero such rows so `out` alone is valid even under the
            # non-block-aligned offsets the public flash_mha_lse allows
            p = jnp.where(m_new > NEG_INF * 0.5, p, 0.0)
        l[:, :1] = l[:, :1] * corr + p.sum(axis=-1, keepdims=True)
        m[:, :1] = m_new
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc[:] = acc[:] * corr + pv

    @pl.when(ik == nk - 1)
    def _done():
        lf = jnp.maximum(l[:, :1], 1e-30)
        o_ref[0] = (acc[:] / lf).astype(o_ref.dtype)
        lse_ref[0] = m[:, :1] + jnp.log(lf)


def _dq_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               dl_ref, dq_ref, acc, *, bq, bk, causal, dyn, scale):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    live = _live(qo_ref, ko_ref, iq, ik, bq, bk, causal, dyn)

    @pl.when(live)
    def _block():
        qb = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(s - lse_ref[0])
        if causal:
            p = jnp.where(
                _mask(bq, bk, qo_ref[0, 0] + iq * bq,
                      ko_ref[0, 0] + ik * bk),
                p, 0.0,
            )
        dob = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            dob, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dl_ref[0])
        acc[:] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ik == nk - 1)
    def _done():
        dq_ref[0] = acc[:].astype(dq_ref.dtype)


def _dkv_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                dl_ref, dk_ref, dv_ref, kacc, vacc,
                *, bq, bk, causal, dyn, scale):
    from jax.experimental import pallas as pl

    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        kacc[:] = jnp.zeros_like(kacc)
        vacc[:] = jnp.zeros_like(vacc)

    live = _live(qo_ref, ko_ref, iq, ik, bq, bk, causal, dyn)

    @pl.when(live)
    def _block():
        qb = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(s - lse_ref[0])
        if causal:
            p = jnp.where(
                _mask(bq, bk, qo_ref[0, 0] + iq * bq,
                      ko_ref[0, 0] + ik * bk),
                p, 0.0,
            )
        dob = do_ref[0].astype(jnp.float32)
        vacc[:] += jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            dob, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dl_ref[0])
        kacc[:] += jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(iq == nq - 1)
    def _done():
        dk_ref[0] = kacc[:].astype(dk_ref.dtype)
        dv_ref[0] = vacc[:].astype(dv_ref.dtype)


def _pick_block(t: int, want: int) -> int:
    b = min(want, t)
    while t % b:
        b //= 2
    return max(b, 1)


def _offs(q_off, k_off):
    """Normalize offsets to the (1,1) int32 SMEM operands the kernels
    read; None → zeros (the plain static path)."""
    z = jnp.zeros((1, 1), jnp.int32)
    qo = z if q_off is None else jnp.asarray(q_off, jnp.int32).reshape(1, 1)
    ko = z if k_off is None else jnp.asarray(k_off, jnp.int32).reshape(1, 1)
    return qo, ko


def _smem_spec():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _flash_fwd_raw(q, k, v, causal, bq, bk, interpret,
                   q_off=None, k_off=None):
    """(BH, T, D) folded layout -> (out, lse).  lse is (BH, T, 1) f32 —
    the lane-1 layout keeps T in sublanes so the kernel writes it
    without a relayout.  ``q_off``/``k_off`` are dynamic global
    position offsets for the causal mask (ring hops); None keeps the
    static-offset fast path (block-level causal skip)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    dyn = q_off is not None or k_off is not None
    qo, ko = _offs(q_off, k_off)
    bh, t, d = q.shape
    tk = k.shape[1]
    nq, nk = t // bq, tk // bk
    scale = 1.0 / math.sqrt(d)
    kern = functools.partial(
        _fwd_kernel, bq=bq, bk=bk, causal=causal, dyn=dyn, scale=scale
    )
    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[_smem_spec(), _smem_spec(), qspec, kspec, kspec],
        out_specs=[
            qspec,
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            **_dims(("parallel", "parallel", "arbitrary"))
        ),
        interpret=interpret,
    )(qo, ko, q, k, v)


def _flash_bwd_raw(q, k, v, do, lse, delta, causal, bq, bk, interpret,
                   q_off=None, k_off=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    dyn = q_off is not None or k_off is not None
    qo, ko = _offs(q_off, k_off)
    bh, t, d = q.shape
    tk = k.shape[1]
    nq, nk = t // bq, tk // bk
    scale = 1.0 / math.sqrt(d)

    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM)
    rspec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bq=bq, bk=bk, causal=causal,
                          dyn=dyn, scale=scale),
        grid=(bh, nq, nk),
        in_specs=[_smem_spec(), _smem_spec(),
                  qspec, kspec, kspec, qspec, rspec, rspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pallas_tpu_compiler_params(
            **_dims(("parallel", "parallel", "arbitrary"))
        ),
        interpret=interpret,
    )(qo, ko, q, k, v, do, lse, delta)

    # k/v grid: kv block is the resident operand, q sweeps innermost
    qspec2 = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kspec2 = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0),
                          memory_space=pltpu.VMEM)
    rspec2 = pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0),
                          memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq, bk=bk, causal=causal,
                          dyn=dyn, scale=scale),
        grid=(bh, nk, nq),
        in_specs=[_smem_spec(), _smem_spec(),
                  qspec2, kspec2, kspec2, qspec2, rspec2, rspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            **_dims(("parallel", "parallel", "arbitrary"))
        ),
        interpret=interpret,
    )(qo, ko, q, k, v, do, lse, delta)
    return dq, dk, dv


def _fold(x):
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unfold(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_mha(q, k, v, causal: bool = False, block_q: int = 512,
              block_k: int = 512, interpret: bool = False):
    """Flash attention on ``(B, T, H, Dh)`` tensors — drop-in for
    ``attention.mha``.  ``_pick_block`` halves the block until it
    divides T; callers (the layer's ``auto`` dispatch) should route T
    whose largest dividing block is tiny back to ``mha`` — a block-1
    kernel is valid but pathologically slow."""
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    b, t, h, d = q.shape
    bq = _pick_block(t, block_q)
    bk = _pick_block(k.shape[1], block_k)
    out, lse = _flash_fwd_raw(
        _fold(q), _fold(k), _fold(v), causal, bq, bk, interpret
    )
    return _unfold(out, b, h), (q, k, v, _unfold(out, b, h), lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    b, t, h, d = q.shape
    bq = _pick_block(t, block_q)
    bk = _pick_block(k.shape[1], block_k)
    gf = _fold(g)
    of = _fold(out)
    delta = (gf.astype(jnp.float32) * of.astype(jnp.float32)).sum(
        -1, keepdims=True
    )
    dq, dk, dv = _flash_bwd_raw(
        _fold(q), _fold(k), _fold(v), gf, lse, delta, causal, bq, bk,
        interpret,
    )
    return _unfold(dq, b, h), _unfold(dk, b, h), _unfold(dv, b, h)


flash_mha.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def flash_mha_lse(q, k, v, q_off, k_off, causal: bool = True,
                  block_q: int = 512, block_k: int = 512,
                  interpret: bool = False):
    """Flash attention returning ``(out, lse)`` with dynamic position
    offsets — the ring-attention building block.

    ``lse`` is the per-row log-sum-exp ``(B, T, H)`` of the (masked)
    scores; ring hops merge partial results as
    ``lse' = logaddexp(lse_a, lse_b)``, ``o' = (o_a e^{lse_a-lse'} +
    o_b e^{lse_b-lse'})``.  ``q_off``/``k_off`` are traced scalars: the
    global positions of this call's first query/key row, consumed by
    the causal mask (a hop whose keys all sit after the queries yields
    lse ~ -1e30 and washes out of the merge).

    The VJP accepts cotangents for BOTH outputs: ``dL/dlse`` folds into
    the backward kernels as ``ds = p * (dp - (delta - dlse))`` — the
    same two kernels serve both flash entry points.
    """
    out, lse, _ = _flash_lse_fwd_impl(
        q, k, v, q_off, k_off, causal, block_q, block_k, interpret
    )
    return out, lse


def _flash_lse_fwd_impl(q, k, v, q_off, k_off, causal, block_q, block_k,
                        interpret):
    b, t, h, d = q.shape
    bq = _pick_block(t, block_q)
    bk = _pick_block(k.shape[1], block_k)
    out, lse = _flash_fwd_raw(
        _fold(q), _fold(k), _fold(v), causal, bq, bk, interpret,
        q_off=q_off, k_off=k_off,
    )
    # lse (BH, T, 1) -> (B, T, H)
    lse_o = lse[:, :, 0].reshape(b, h, t).transpose(0, 2, 1)
    return _unfold(out, b, h), lse_o, (out, lse)


def _flash_lse_fwd(q, k, v, q_off, k_off, causal, block_q, block_k,
                   interpret):
    out_u, lse_o, (out_f, lse_f) = _flash_lse_fwd_impl(
        q, k, v, q_off, k_off, causal, block_q, block_k, interpret
    )
    return (out_u, lse_o), (q, k, v, q_off, k_off, out_f, lse_f)


def _flash_lse_bwd(causal, block_q, block_k, interpret, res, cts):
    g, g_lse = cts
    q, k, v, q_off, k_off, out_f, lse_f = res
    b, t, h, d = q.shape
    bq = _pick_block(t, block_q)
    bk = _pick_block(k.shape[1], block_k)
    gf = _fold(g)
    # dL/dlse_i adds p_ij * dlse_i to ds_ij; the kernels compute
    # ds = p * (dp - dl) so dl = delta - dlse absorbs it
    dlse = jnp.zeros((b * h, t, 1), jnp.float32) if g_lse is None else (
        g_lse.transpose(0, 2, 1).reshape(b * h, t, 1).astype(jnp.float32)
    )
    delta = (gf.astype(jnp.float32) * out_f.astype(jnp.float32)).sum(
        -1, keepdims=True
    )
    dq, dk, dv = _flash_bwd_raw(
        _fold(q), _fold(k), _fold(v), gf, lse_f, delta - dlse,
        causal, bq, bk, interpret, q_off=q_off, k_off=k_off,
    )
    return (_unfold(dq, b, h), _unfold(dk, b, h), _unfold(dv, b, h),
            None, None)


flash_mha_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)
