"""GPipe-style pipeline parallelism over a mesh axis.

New TPU-first scope (the reference has no pipeline parallelism, SURVEY
§2.8).  The scaling-book recipe: stage ``s`` of ``S`` (one per device on
the pipeline mesh axis) owns the parameters of layers ``[s*L/S,
(s+1)*L/S)``; microbatches march through the stages, activations hop to
the next device with ``lax.ppermute`` each tick, and the whole schedule
is one ``lax.scan`` of ``T + S - 1`` ticks inside the SPMD program —
bubble fraction ``(S-1)/(T+S-1)``.

The primitive operates on a *homogeneous block stack*: ``block_fn(params,
x) -> y`` applied ``L`` times with stacked params (leading dim ``L``).
Stage-local sub-stacks run under ``lax.scan`` so each tick does its
``L/S`` blocks.  The trainer-facing wrapper below shards the stacked
params over the pipeline axis; everything differentiates with ``jax.grad``
(the backward schedule is the transposed pipeline, derived by autodiff).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def _stage_apply(block_fn: Callable, stage_params, x):
    """Run this stage's L/S blocks sequentially on one activation."""

    def body(h, p):
        return block_fn(p, h), None

    y, _ = lax.scan(body, x, stage_params)
    return y


def gpipe(
    block_fn: Callable,
    stage_params,
    x_mb: jnp.ndarray,
    *,
    axis_name: str,
) -> jnp.ndarray:
    """Pipelined application of the full block stack.

    Call under ``shard_map``: ``stage_params`` is this device's
    ``(L/S, ...)`` parameter sub-stack (the global ``(L, ...)`` stack
    sharded on ``axis_name``); ``x_mb`` is ``(T, mb, ...)`` microbatches,
    replicated.  Stage 0 feeds microbatches in, activations hop stages on
    a ``ppermute`` ring each tick, the last stage stores results, and a
    final ``psum`` replicates the output buffer (other stages contribute
    zeros).  ``T + S - 1`` ticks total — bubble ``(S-1)/(T+S-1)``."""
    s = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    t = x_mb.shape[0]
    n_tick = t + s - 1
    perm = [(i, (i + 1) % s) for i in range(s)]

    out0 = jnp.zeros_like(x_mb)
    reg0 = jnp.zeros_like(x_mb[0])

    def tick(carry, k):
        reg, out = carry
        mb_idx = jnp.clip(k, 0, t - 1)
        reg = jnp.where(idx == 0, x_mb[mb_idx], reg)
        y = _stage_apply(block_fn, stage_params, reg)
        done_idx = jnp.clip(k - (s - 1), 0, t - 1)
        store = jnp.logical_and(idx == s - 1, k >= s - 1)
        out = out.at[done_idx].set(jnp.where(store, y, out[done_idx]))
        reg = lax.ppermute(y, axis_name, perm)
        return (reg, out), None

    (_, out), _ = lax.scan(tick, (reg0, out0), jnp.arange(n_tick))
    return lax.psum(out, axis_name)


def pipeline_apply(
    block_fn: Callable,
    params_stacked,
    x: jnp.ndarray,
    mesh,
    *,
    n_microbatch: int,
    stage_axis: str = "model",
    data_axis: str = "data",
):
    """Trainer-facing wrapper: global ``(L, ...)`` param stack, global
    ``(B, ...)`` batch → pipelined ``block_fn^L`` application.

    The batch splits into ``n_microbatch`` microbatches; params shard
    over ``stage_axis``; output layout matches the input batch.
    """
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map_nocheck

    b = x.shape[0]
    if b % n_microbatch != 0:
        raise ValueError(
            f"batch {b} must divide into {n_microbatch} microbatches"
        )
    n_stage = mesh.shape[stage_axis]
    l = jax.tree_util.tree_leaves(params_stacked)[0].shape[0]
    if l % n_stage != 0:
        raise ValueError(f"{l} blocks must divide over {n_stage} stages")
    mb = b // n_microbatch
    x_mb = x.reshape((n_microbatch, mb) + x.shape[1:])

    # keep each data replica on its own microbatch rows (no redundant
    # recompute across the data axis); replicate only when indivisible
    n_data = mesh.shape.get(data_axis, 1) if hasattr(mesh.shape, "get") \
        else dict(mesh.shape)[data_axis]
    if data_axis in mesh.axis_names and mb % n_data == 0 and n_data > 1:
        row_spec = P(None, data_axis)
    else:
        row_spec = P()

    pspec = jax.tree_util.tree_map(
        lambda v: P(stage_axis, *([None] * (v.ndim - 1))), params_stacked
    )
    out = shard_map_nocheck(
        functools.partial(gpipe, block_fn, axis_name=stage_axis),
        mesh, (pspec, row_spec), row_spec,
    )(params_stacked, x_mb)
    return out.reshape((b,) + out.shape[2:])
