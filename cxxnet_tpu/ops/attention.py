"""Multi-head attention: plain, and ring (sequence-parallel) variants.

The reference framework predates attention entirely (SURVEY §5: no
sequence axis anywhere), so this op is new TPU-first scope: long-context
support via **ring attention** — the sequence is sharded over a mesh
axis, each device holds a query block, and key/value blocks rotate
around the ring with ``lax.ppermute`` while a numerically-stable
streaming softmax (log-sum-exp merging, the flash-attention recurrence)
accumulates the output.  Compute on each hop overlaps the neighbour
exchange; memory per device is O(T/n) instead of O(T), and the ICI ring
is exactly the topology TPU slices provide.

Layouts: ``q, k, v`` are ``(B, T, H, Dh)`` (batch, time, heads, head
dim).  ``mha`` is the single-device golden model; ``ring_attention`` is
the per-shard computation to run under ``shard_map`` with the time axis
sharded on ``axis_name``.
"""

from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _scores(q: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """(B,Tq,H,D),(B,Tk,H,D) -> (B,H,Tq,Tk) scaled dot product (f32)."""
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    return s * (1.0 / jnp.sqrt(jnp.float32(d)))


def _causal_mask(tq: int, tk: int, q_off, k_off) -> jnp.ndarray:
    """True where query position >= key position (may attend)."""
    qi = q_off + lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
    ki = k_off + lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
    return qi >= ki


def mha(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
) -> jnp.ndarray:
    """Plain softmax attention — the golden model for the ring variant."""
    s = _scores(q, k)
    if causal:
        mask = _causal_mask(q.shape[1], k.shape[1], 0, 0)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(v.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = False,
) -> jnp.ndarray:
    """Blockwise ring attention over a sequence-sharded mesh axis.

    Call under ``shard_map`` with q/k/v time-sharded on ``axis_name``;
    each of the ``n`` devices sees ``(B, T/n, H, Dh)`` blocks.  The kv
    block makes ``n`` hops around the ring; the output never leaves its
    device.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    qf = q.astype(jnp.float32)

    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    m0 = jnp.full((b, h, tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(o, m, l, kb, vb, hop):
        """Streaming-softmax merge of the kv block that arrived from
        device (idx - hop) % n."""
        src = (idx - hop) % n
        s = _scores(qf, kb.astype(jnp.float32))
        if causal:
            mask = _causal_mask(tq, tk, idx * tq, src * tk)
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p, vb.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        return o_new, m_new, l_new

    # hop 0 merges the resident kv block; n-1 rotations follow (not n —
    # the final block must not be rotated onward, that hop is wasted ICI)
    o, m, l = merge(o0, m0, l0, k, v, 0)

    def step(carry, hop):
        o, m, l, kb, vb = carry
        kb, vb = lax.ppermute((kb, vb), axis_name, perm)
        o, m, l = merge(o, m, l, kb, vb, hop)
        return (o, m, l, kb, vb), None

    if n > 1:
        (o, m, l, _, _), _ = lax.scan(
            step, (o, m, l, k, v), jnp.arange(1, n)
        )
    l = jnp.maximum(l, 1e-30)  # fully-masked rows (strict causal pad)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(v.dtype)


def ring_self_attention(
    x_q: jnp.ndarray,
    x_k: jnp.ndarray,
    x_v: jnp.ndarray,
    mesh,
    seq_axis: str = "model",
    *,
    causal: bool = False,
) -> jnp.ndarray:
    """shard_map wrapper: global (B,T,H,Dh) arrays, T sharded on
    ``seq_axis`` (batch on ``data``); returns the same global layout."""
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map_nocheck

    spec = P("data", seq_axis, None, None)
    fn = shard_map_nocheck(
        functools.partial(ring_attention, axis_name=seq_axis, causal=causal),
        mesh, (spec, spec, spec), spec,
    )
    return fn(x_q, x_k, x_v)


def a2a_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = False,
    attn_fn=None,
) -> jnp.ndarray:
    """Ulysses-style all-to-all sequence parallelism.

    Call under ``shard_map`` with q/k/v time-sharded on ``axis_name``
    ((B, T/n, H, Dh) blocks).  Two ``lax.all_to_all`` re-shardings swap
    the sequence sharding for a head sharding: each device then runs
    *full-sequence* attention over H/n heads, so the math inside is
    exactly ``mha`` (no streaming softmax needed).  Communication is two
    all-to-alls of the activations vs the ring's n ppermute hops of
    k/v — better when heads divide the axis and T is large; the ring
    wins when H < n or memory for the full T scores is tight.
    """
    n = lax.psum(1, axis_name)
    del n  # static under shard_map; kept for symmetry/documentation

    def swap(x, fwd: bool):
        # fwd: (B, T/n, H, Dh) -> (B, T, H/n, Dh); tiled all_to_all
        # splits split_axis n ways and concatenates along concat_axis
        return lax.all_to_all(
            x, axis_name,
            split_axis=2 if fwd else 1,
            concat_axis=1 if fwd else 2,
            tiled=True,
        )

    local = attn_fn if attn_fn is not None else mha
    o = local(swap(q, True), swap(k, True), swap(v, True), causal=causal)
    return swap(o, False)


def a2a_self_attention(
    x_q: jnp.ndarray,
    x_k: jnp.ndarray,
    x_v: jnp.ndarray,
    mesh,
    seq_axis: str = "model",
    *,
    causal: bool = False,
    attn_fn=None,
) -> jnp.ndarray:
    """shard_map wrapper mirroring ``ring_self_attention`` — same global
    (B,T,H,Dh) contract, all-to-all schedule inside.  ``attn_fn`` swaps
    the per-device full-sequence attention (e.g. the Pallas flash kernel
    under ``attn_impl = pallas``)."""
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map_nocheck

    spec = P("data", seq_axis, None, None)
    fn = shard_map_nocheck(
        functools.partial(a2a_attention, axis_name=seq_axis, causal=causal,
                          attn_fn=attn_fn),
        mesh, (spec, spec, spec), spec,
    )
    return fn(x_q, x_k, x_v)


def ring_attention_flash(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ring attention whose per-hop block math runs the fused flash
    kernel (``ops/flash.flash_mha_lse``) instead of XLA einsums.

    Same schedule as :func:`ring_attention` — kv blocks rotate around
    the ``axis_name`` ring — but each hop computes its ``(o, lse)``
    pair entirely in VMEM and partial results merge in log space:
    ``lse' = logaddexp``, outputs reweighted by ``exp(lse - lse')``.
    The causal mask uses dynamic global offsets (this device's query
    block start vs the hop's key block start); a hop that is entirely
    in the future yields ``lse ~ -1e30`` and washes out of the merge.
    """
    from .flash import flash_mha_lse

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(o, lse, kb, vb, hop_i):
        """o carried f32 across hops (the repo's accumulate-in-f32
        discipline); cast once at the final return."""
        src = (idx - hop_i) % n
        o_h, lse_h = flash_mha_lse(
            q, kb, vb, idx * tq, src * tk, causal, 512, 512, interpret
        )
        lse_new = jnp.logaddexp(lse, lse_h)
        w_old = jnp.exp(lse - lse_new)[:, :, :, None]
        w_new = jnp.exp(lse_h - lse_new)[:, :, :, None]
        o2 = o * w_old + o_h.astype(jnp.float32) * w_new
        return o2, lse_new

    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    lse0 = jnp.full((b, tq, h), NEG_INF, jnp.float32)
    o, lse = hop(o0, lse0, k, v, 0)

    def step(carry, hop_i):
        o, lse, kb, vb = carry
        kb, vb = lax.ppermute((kb, vb), axis_name, perm)
        o, lse = hop(o, lse, kb, vb, hop_i)
        return (o, lse, kb, vb), None

    if n > 1:
        (o, lse, _, _), _ = lax.scan(
            step, (o, lse, k, v), jnp.arange(1, n)
        )
    return o.astype(v.dtype)


def ring_self_attention_flash(
    x_q: jnp.ndarray,
    x_k: jnp.ndarray,
    x_v: jnp.ndarray,
    mesh,
    seq_axis: str = "model",
    *,
    causal: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """shard_map wrapper mirroring ``ring_self_attention`` with the
    flash per-hop kernel."""
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map_nocheck

    spec = P("data", seq_axis, None, None)
    fn = shard_map_nocheck(
        functools.partial(ring_attention_flash, axis_name=seq_axis,
                          causal=causal, interpret=interpret),
        mesh, (spec, spec, spec), spec,
    )
    return fn(x_q, x_k, x_v)
