"""Reduced-precision inference primitives: int8 weights, folded rescale.

The cuDNN/TPP lesson (arXiv:1410.0759, arXiv:2104.05755): the remaining
per-chip inference headroom is in reduced-precision, layout-aware
primitives — the MXU contracts an int8-originated operand at full rate
while HBM moves 4x fewer weight bytes.  This module holds the scheme's
math, shared by the exporter (``nnet/quant.py``), the quantized forward
dispatch (``nnet/net.py``) and the tests:

* **per-output-channel symmetric scales** — each output channel ``o`` of
  a conv (HWIO, axis 3) or fullc (``(nout, nin)``, axis 0) kernel gets
  ``scale[o] = max(|w[..., o]|) / 127``; codes are
  ``round(w / scale)`` clipped to ``[-127, 127]`` (symmetric: -128 is
  never emitted, so negation stays exact and the zero-point is 0);
* **dequant-free application** — because the scale is constant along
  every contracted axis, it commutes out of the contraction:
  ``x @ (q * s) == (x @ q) * s``.  The compiled program therefore feeds
  the RAW codes (cast to the activation dtype — int8 values are exact
  in bf16's 8-bit mantissa) to ``lax.dot_general`` /
  ``lax.conv_general_dilated`` with ``preferred_element_type=float32``
  and folds the per-channel rescale into the following bias add; the
  weight tensor at rest — in host RAM, HBM and the jit argument — stays
  int8;
* **bf16 fallback** — a layer whose quantization error blows the
  accuracy budget stores its kernel as bfloat16 instead (2x, not 4x);
  the plain layer ``apply`` path handles it via its usual
  ``astype(x.dtype)``.

Param-dict convention (the ``params`` pytree the trainer carries): a
quantized layer's entry holds ``wmat_q8`` (int8 codes, original kernel
layout), ``wscale`` (f32, shape ``(nout,)``) and the untouched f32
``bias``; an unquantized (or bf16-fallback) entry keeps the usual
``wmat``.  ``is_quantized`` keys on ``wmat_q8``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "QKEY", "SKEY", "QMAX",
    "quantize_weight", "dequantize_weight", "quant_error",
    "is_quantized", "effective_wmat",
    "fc_apply_q", "conv_apply_q",
    "weight_bytes", "scheme_of",
]

QKEY = "wmat_q8"   # int8 codes (kernel layout preserved)
SKEY = "wscale"    # f32 per-output-channel scales, shape (nout,)
QMAX = 127.0       # symmetric range: [-127, 127], zero-point 0


def _scale_shape(ndim: int, out_axis: int) -> Tuple[int, ...]:
    shape = [1] * ndim
    shape[out_axis] = -1
    return tuple(shape)


def quantize_weight(w, out_axis: int) -> Tuple[np.ndarray, np.ndarray]:
    """``(codes int8, scales f32)`` for one kernel, per-output-channel
    symmetric.  ``out_axis`` is the output-channel axis (3 for HWIO
    conv kernels, 0 for ``(nout, nin)`` fullc kernels).  All-zero
    channels get scale 1 (codes are all 0 — any scale round-trips)."""
    w = np.asarray(w, np.float32)
    out_axis = out_axis % w.ndim
    reduce_axes = tuple(a for a in range(w.ndim) if a != out_axis)
    absmax = np.abs(w).max(axis=reduce_axes)
    scale = np.where(absmax > 0, absmax / QMAX, 1.0).astype(np.float32)
    sb = scale.reshape(_scale_shape(w.ndim, out_axis))
    q = np.clip(np.rint(w / sb), -QMAX, QMAX).astype(np.int8)
    return q, scale


def dequantize_weight(q, scale, out_axis: int, dtype=np.float32):
    """Codes + scales back to a dense kernel (NOT the serving path —
    the compiled programs never materialize this at rest; it exists for
    round-trip tests, error ranking and the fused-group assembly)."""
    q = jnp.asarray(q)
    sb = jnp.asarray(scale).reshape(_scale_shape(q.ndim, out_axis % q.ndim))
    return (q.astype(jnp.float32) * sb).astype(dtype)


def quant_error(w, out_axis: int) -> float:
    """Relative L2 quantization error of one kernel — the exporter's
    per-layer fallback ranking (worst error reverts to bf16 first)."""
    w = np.asarray(w, np.float32)
    q, s = quantize_weight(w, out_axis)
    dq = np.asarray(dequantize_weight(q, s, out_axis))
    denom = float(np.linalg.norm(w))
    if denom == 0.0:
        return 0.0
    return float(np.linalg.norm(w - dq) / denom)


def is_quantized(lparams) -> bool:
    return bool(lparams) and QKEY in lparams


def effective_wmat(lparams, dtype):
    """The layer's kernel in ``dtype`` whatever its storage: dequantized
    codes for an int8 entry, the usual ``astype`` otherwise.  The fused
    group paths (sibling-1x1, branch-embed) assemble block kernels from
    this — the dequant happens IN-program (weights at rest stay int8);
    only the group GEMM itself runs unfolded."""
    if is_quantized(lparams):
        return dequantize_weight(lparams[QKEY], lparams[SKEY],
                                 out_axis=-1, dtype=dtype)
    return lparams["wmat"].astype(dtype)


def _rescale_bias(y, lparams, out_dtype):
    """Fold the per-channel rescale (+ bias) into the contraction's f32
    output, then hand downstream layers their expected dtype."""
    y = y * lparams[SKEY].astype(jnp.float32)
    if "bias" in lparams:
        y = y + lparams["bias"].astype(jnp.float32)
    return y.astype(out_dtype)


def fc_apply_q(lparams, x, kernels=None):
    """Quantized ``fullc``: ``y = (x @ q.T) * scale + bias``.

    ``q`` is ``(nout, nin)`` int8; the cast to the activation dtype is
    exact (|codes| <= 127 fit bf16's mantissa) and fuses into the GEMM's
    operand read — the weight argument of the compiled program is the
    int8 array.  ``kernels`` (a ``ops.kernels.BoundKernels``) may route
    the whole chain into the fused Pallas epilogue kernel — bit-equal to
    this stock lowering (tests/test_kernels.py)."""
    q = lparams[QKEY]
    if (kernels is not None and x.ndim == 2
            and kernels.active("int8_gemm", x=x, q=q)):
        from .kernels import int8_gemm as _kq

        return _kq.int8_gemm_rescale(
            x, q, lparams[SKEY], lparams.get("bias"),
            interpret=kernels.interpret)
    y = jax.lax.dot_general(
        x, q.astype(x.dtype),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return _rescale_bias(y, lparams, x.dtype)


def conv_apply_q(lparams, x, stride: int, pad_y: int, pad_x: int,
                 groups: int = 1, kernels=None):
    """Quantized conv: direct NHWC/HWIO ``conv_general_dilated`` on the
    raw codes, f32 accumulate, per-output-channel rescale folded into
    the bias add (scales are per-O, so they commute out of the HWI
    contraction — exact).  A 1x1/pad-0/ungrouped conv IS the fullc GEMM
    over flattened pixels, so ``kernels`` may route it into the fused
    int8 epilogue kernel; K×K convs stay on the stock lowering."""
    q = lparams[QKEY]
    if (kernels is not None and groups == 1 and pad_y == 0 and pad_x == 0
            and q.shape[:2] == (1, 1)
            and kernels.active("int8_gemm", x=x, q=q)):
        from .kernels import int8_gemm as _kq

        if stride > 1:
            # exact for a 1x1/pad-0 conv: output (i, j) reads only
            # x[i*stride, j*stride]
            x = x[:, ::stride, ::stride, :]
        n, h, w, cin = x.shape
        y = _kq.int8_gemm_rescale(
            x.reshape(-1, cin),
            jnp.transpose(q.reshape(cin, -1)),  # HWIO (1,1,I,O) -> (O, I)
            lparams[SKEY], lparams.get("bias"),
            interpret=kernels.interpret)
        return y.reshape(n, h, w, -1)
    y = jax.lax.conv_general_dilated(
        x, q.astype(x.dtype),
        window_strides=(stride, stride),
        padding=((pad_y, pad_y), (pad_x, pad_x)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
        preferred_element_type=jnp.float32,
    )
    return _rescale_bias(y, lparams, x.dtype)


def weight_bytes(params) -> Tuple[int, int]:
    """``(actual, f32_equiv)`` bytes of a params pytree: what the
    weights cost at rest as stored vs what the same tensors would cost
    dense f32 — the serve engine's ``serve_weight_bytes`` gauges and
    the QUANT lane's >= 3.5x assertion.  Scales are billed to
    ``actual`` only (they do not exist in the f32 model)."""
    actual = 0
    f32_equiv = 0
    for tags in (params or {}).values():
        for tag, w in tags.items():
            size = int(np.prod(np.shape(w)) or 1)
            nbytes = getattr(w, "nbytes", None)
            if nbytes is None:
                nbytes = int(np.asarray(w).nbytes)
            actual += int(nbytes)
            if tag != SKEY:
                f32_equiv += 4 * size
    return actual, f32_equiv


def scheme_of(trainer) -> str:
    """The trainer's quant scheme for cache keys / identity surfaces:
    ``"int8"`` / ``"bf16"`` when quantized, ``""`` for the plain f32
    model (the absent-key spelling every pre-quant cache key used)."""
    return getattr(trainer, "quant_scheme", "") or ""
