"""On-chip kernel library: registry, capability probes, verdict gating.

The TPP/cuDNN lesson (arXiv 2104.05755, arXiv 1410.0759): a SMALL
library of well-chosen fused primitives beats op-by-op lowering — but
only where measured.  This package holds the repo's Pallas block
kernels and the discipline that decides when they run:

* ``conv_block``   — fused conv+bias(+relu) GEMM for the sibling-1x1
  groups ``nnet/net.py`` already assembles (``conv_block.py``);
* ``int8_gemm``    — quantized GEMM with the per-channel rescale (+bias,
  optional relu) inside the kernel epilogue (``int8_gemm.py``);
* ``zero_update``  — the fused shard-local sgd update step for
  ``_apply_updates`` (``update_step.py``).

Every kernel registers a **capability probe** (backend/dtype/shape —
"can this launch at all") and an **interpret-mode reference**: the
identical kernel body run under ``interpret=True`` on CPU, pinned
bit-equal to the stock XLA lowering by tests/test_kernels.py.  Whether
a capable kernel actually RUNS is the selector's call:

``kernel_lib = auto | off | <name[,name...]>``

* ``off`` (also ``0``/empty) — stock lowering everywhere;
* an explicit name list — those kernels pinned ON wherever their probe
  passes (on non-TPU backends they execute in interpret mode: exact,
  slow — the parity/test spelling);
* ``auto`` (the default, also ``-1``) — follow the RECORDED per-backend
  verdicts in ``verdicts.json``, the same way ``conv_branch_embed=-1``
  follows its measured CPU reject: a kernel runs only where a committed
  ``promote`` verdict from the bisect A/B (``tools/kernel_ab.py``)
  says it pays.  CPU rejects are recorded (Pallas on CPU is emulation);
  TPU verdicts stay queued in ``tools/tpu_queue.sh`` — until a window
  drains the queue and commits a promote, ``auto`` means stock, so
  adopting a kernel is always a measured decision, never faith.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, NamedTuple, Optional

__all__ = [
    "KERNELS", "KernelSpec", "KernelSelector", "BoundKernels",
    "parse_mode", "verdicts_path", "load_verdicts", "record_verdict",
    "reload_verdicts",
]


class KernelSpec(NamedTuple):
    name: str
    doc: str
    probe: Callable[..., Optional[str]]  # None = capable, str = reason


def _specs() -> Dict[str, KernelSpec]:
    from . import conv_block, int8_gemm, update_step

    return {
        "conv_block": KernelSpec(
            "conv_block",
            "fused conv+bias(+relu) GEMM for sibling-1x1 groups",
            conv_block.probe),
        "int8_gemm": KernelSpec(
            "int8_gemm",
            "int8 GEMM, per-channel rescale in the kernel epilogue",
            int8_gemm.probe),
        "zero_update": KernelSpec(
            "zero_update",
            "fused shard-local sgd update step",
            update_step.probe),
    }


KERNELS: Dict[str, KernelSpec] = _specs()

# ----------------------------------------------------------------------
# recorded per-backend verdicts (the committed promotion state)
_VERDICTS_LOCK = threading.Lock()
_VERDICTS: Optional[dict] = None


def verdicts_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "verdicts.json")


def load_verdicts() -> dict:
    """``{kernel: {backend: {"verdict": promote|reject, ...}}}`` from
    the committed file; cached (``reload_verdicts`` drops the cache —
    tests and ``kernel_ab --record`` use it)."""
    global _VERDICTS
    with _VERDICTS_LOCK:
        if _VERDICTS is None:
            try:
                with open(verdicts_path(), "r", encoding="utf-8") as f:
                    _VERDICTS = json.load(f)
            except (OSError, ValueError):
                _VERDICTS = {}
        return _VERDICTS


def reload_verdicts() -> None:
    global _VERDICTS
    with _VERDICTS_LOCK:
        _VERDICTS = None


def record_verdict(kernel: str, backend: str, verdict: str,
                   path: str = "", **extra) -> dict:
    """Append/overwrite one (kernel, backend) verdict in the committed
    file (``tools/kernel_ab.py --record``).  Returns the full doc."""
    if kernel not in KERNELS:
        raise ValueError(f"unknown kernel {kernel!r}")
    if verdict not in ("promote", "reject"):
        raise ValueError(f"verdict must be promote/reject, got {verdict!r}")
    path = path or verdicts_path()
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    doc.setdefault(kernel, {})[backend] = {"verdict": verdict, **extra}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    if os.path.abspath(path) == verdicts_path():
        reload_verdicts()
    return doc


# ----------------------------------------------------------------------
# the conf-keyed selector
def parse_mode(val: str) -> str:
    """Validate a ``kernel_lib`` conf value; returns the canonical
    spelling (``auto`` / ``off`` / comma name list).  Raises on unknown
    kernel names — a conf typo must fail at build, not silently serve
    the stock path."""
    v = (val or "").strip()
    if v in ("auto", "-1"):
        return "auto"
    if v in ("off", "0", "", "none"):
        return "off"
    names = [s.strip() for s in v.split(",") if s.strip()]
    bad = [s for s in names if s not in KERNELS]
    if bad or not names:
        raise ValueError(
            f"kernel_lib={val!r}: expected auto, off, or a comma list "
            f"of {sorted(KERNELS)}"
            + (f" (unknown: {bad})" if bad else ""))
    return ",".join(sorted(set(names)))


class KernelSelector:
    """Decides, per (kernel, backend), whether the Pallas path runs."""

    def __init__(self, mode: str = "auto",
                 verdicts: Optional[dict] = None) -> None:
        self.mode = parse_mode(mode)
        self._verdicts = verdicts

    def _verdict(self, name: str, backend: str) -> str:
        v = (self._verdicts if self._verdicts is not None
             else load_verdicts())
        return ((v.get(name) or {}).get(backend) or {}).get("verdict", "")

    def active(self, name: str, backend: str) -> bool:
        if name not in KERNELS:
            raise ValueError(f"unknown kernel {name!r}")
        backend = backend or "cpu"
        if self.mode == "off":
            return False
        if self.mode == "auto":
            # follow the recorded promotion state: no verdict = stock
            # (promotion requires the measured A/B, never default-on)
            return self._verdict(name, backend) == "promote"
        return name in self.mode.split(",")

    def fingerprint(self, backend: str) -> str:
        """Cache-key component (``serve/cache.py``): the names this
        selector activates on ``backend``, '' when none — the stock
        program's key is unchanged from the pre-kernel era."""
        names = [n for n in sorted(KERNELS) if self.active(n, backend)]
        return "+".join(names)

    def bind(self, backend: Optional[str]) -> "BoundKernels":
        return BoundKernels(self, backend or "cpu")


class BoundKernels:
    """A selector fixed to one backend — what dispatch sites consume.
    ``interpret`` is True off-TPU: the identical kernel body runs under
    the Pallas interpreter (exact, slow — the parity spelling)."""

    __slots__ = ("selector", "backend", "interpret")

    def __init__(self, selector: KernelSelector, backend: str) -> None:
        self.selector = selector
        self.backend = backend
        self.interpret = backend != "tpu"

    def active(self, name: str, **probe_kw) -> bool:
        """Selected AND capable; publishes the decision as the
        ``kernel_selected{name,backend}`` gauge."""
        on = self.selector.active(name, self.backend)
        if on and probe_kw:
            on = KERNELS[name].probe(self.backend, **probe_kw) is None
        from ...obs import device as obs_device

        obs_device.mark_kernel_selected(name, self.backend, on)
        return on

    def fingerprint(self) -> str:
        return self.selector.fingerprint(self.backend)
