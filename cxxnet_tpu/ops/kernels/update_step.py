"""Fused shard-local sgd update-step kernel for ``_apply_updates``.

The ZeRO update path (``nnet/trainer.py _apply_updates``) applies the
per-tensor updater rules as separate XLA elementwise ops — momentum
read, clip, wd-fold, momentum write, weight write — each a full HBM
round-trip over the (shard-local) tensor.  The sgd rule

    m' = mom * m - lr * (clip(g) + wd * w);  w' = w + m'

is one fused read-modify-write: this kernel streams each (w, g, m)
tile through VMEM exactly once and writes both outputs from registers.
The math is purely elementwise, so the shard-local contract
(doc/parallel.md: each replica updates only its 1/N slice) holds
untouched — the kernel never sees, and never needs, the other shards.

Parity contract: the kernel body replays ``updater.SGDUpdater.apply``
(including the ``clip_gradient != 0`` NaN-zeroing clip quirk,
sgd_updater-inl.hpp:72-84) op for op — interpret mode on CPU is
bit-equal to the stock rule (tests/test_kernels.py pins it, NaNs
included).  lr/momentum arrive as traced (1,1) SMEM scalars (they are
schedule functions of the traced epoch); wd/clip are trace-time
constants, exactly as in the stock closure.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .._compat import pallas_tpu_compiler_params
from .conv_block import _pick_block

_LANES = 128


def _sgd_kernel(lr_ref, mom_ref, w_ref, g_ref, m_ref, wo_ref, mo_ref,
                *, wd, clip):
    lr = lr_ref[0, 0]
    mom = mom_ref[0, 0]
    g = g_ref[:]
    if clip != 0.0:
        # the reference's built-in NaN guard (_nan_clip): zero NaNs,
        # then clamp — only when clip_gradient is set
        g = jnp.where(jnp.isnan(g), 0.0, g)
        g = jnp.clip(g, -clip, clip)
    m = mom * m_ref[:] - lr * (g + wd * w_ref[:])
    wo_ref[:] = w_ref[:] + m
    mo_ref[:] = m


def sgd_update(w, g, m, lr, mom, *, wd: float = 0.0, clip: float = 0.0,
               interpret: bool = False, br: int = 0):
    """One fused sgd step over an arbitrary-shape tensor.

    Returns ``(new_w, new_m)`` with ``w``'s shape/dtype.  ``lr``/``mom``
    are (traced) scalars already cast to ``w.dtype`` (the stock rule's
    spelling); ``wd``/``clip`` are static floats.  The tensor is
    flattened and padded to a ``(rows, 128)`` lane layout; ``br`` tiles
    the rows (0 = whole tensor in one block).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape = w.shape
    n = int(w.size)
    rows = max(1, -(-n // _LANES))
    total = rows * _LANES

    def lanes(a):
        f = a.reshape(-1)
        if total > n:
            f = jnp.pad(f, (0, total - n))
        return f.reshape(rows, _LANES)

    br = _pick_block(rows, br) if br else rows
    sc = lambda v: jnp.asarray(v, w.dtype).reshape(1, 1)  # noqa: E731
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    vspec = pl.BlockSpec((br, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    out = jax.ShapeDtypeStruct((rows, _LANES), w.dtype)
    w2, m2 = pl.pallas_call(
        functools.partial(_sgd_kernel, wd=float(wd), clip=float(clip)),
        grid=(rows // br,),
        in_specs=[smem, smem, vspec, vspec, vspec],
        out_specs=[vspec, vspec],
        out_shape=[out, out],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(sc(lr), sc(mom), lanes(w), lanes(g), lanes(m))
    return (w2.reshape(-1)[:n].reshape(shape),
            m2.reshape(-1)[:n].reshape(shape))


def probe(backend: str, w=None, updater=None, **_kw):
    """None when launchable, else the reject reason.  Only the sgd rule
    is fused (elementwise, single-state); lars/lamb need layer-global
    norms and adam/nag/rmsprop/adagrad stay on the stock path until
    they earn their own measured verdicts."""
    if updater is not None and getattr(updater, "type_name", "") != "sgd":
        return (f"updater {getattr(updater, 'type_name', '?')!r} not "
                "fused (sgd only)")
    if w is not None and w.dtype != jnp.float32:
        return f"master params must be f32, got {w.dtype}"
    return None
