"""Fused conv+bias(+relu) block kernel for the sibling-1x1 groups.

The stock lowering of a fused 1x1 sibling group (``nnet/net.py
_apply_fused_1x1``) is three XLA ops per group: one
``conv_general_dilated`` over the scatter-assembled block kernel, a
``slice_in_dim`` per member, and a bias add per member.  A 1x1 conv IS a
GEMM — output pixel ``(n,y,x)`` is ``x_row @ W`` — so this kernel runs
the whole group as ONE Pallas GEMM with the bias add (and optionally
the following relu) in the epilogue: the MXU tile is written back to
VMEM exactly once, already biased, instead of round-tripping through
HBM between the conv and the elementwise ops.  Strides subsample the
input on the host side first (exact for a 1x1/pad-0 conv: output pixel
``(i,j)`` reads only ``x[i*s, j*s]``).

Parity contract (tests/test_kernels.py): with the default full-array
blocks the kernel's contraction is ONE ``dot_general`` over the same K
axis as the stock conv's GEMM lowering — interpret mode on CPU is
bit-equal to the stock path.  Explicit ``bm``/``bn`` tile the GEMM for
the MXU (the on-chip shape); the per-element contraction is still one
full-K dot, and the A/B driver (tools/kernel_ab.py) gates promotion on
measured parity + throughput per backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .._compat import pallas_tpu_compiler_params


def _pick_block(t: int, want: int) -> int:
    b = min(want, t)
    while t % b:
        b //= 2
    return max(b, 1)


def _gemm_bias_kernel(x_ref, w_ref, b_ref, o_ref, *, relu, has_bias):
    # one full-K dot per output tile: same contraction (and, without
    # preferred_element_type, the same accumulation dtype) as the stock
    # conv's GEMM — the epilogue is the only difference
    y = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())))
    if has_bias:
        y = y + b_ref[:]
    if relu:
        y = jnp.maximum(y, jnp.zeros((), y.dtype))
    o_ref[:] = y.astype(o_ref.dtype)


def fused_block_gemm(x2d, w2d, bias=None, *, relu: bool = False,
                     interpret: bool = False, bm: int = 0, bn: int = 0):
    """``relu?(x2d @ w2d + bias)`` as one Pallas program.

    ``x2d`` is ``(M, K)``, ``w2d`` ``(K, O)``, ``bias`` ``(O,)`` or
    None.  ``bm``/``bn`` tile M/O (0 = whole axis — the bit-parity
    default); K always stays whole so every output element is a single
    full-K contraction.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x2d.shape
    k2, o = w2d.shape
    if k != k2:
        raise ValueError(f"fused_block_gemm: K mismatch {k} vs {k2}")
    has_bias = bias is not None
    b2 = (bias.reshape(1, o).astype(x2d.dtype) if has_bias
          else jnp.zeros((1, 1), x2d.dtype))
    bm = _pick_block(m, bm) if bm else m
    bn = _pick_block(o, bn) if bn else o
    kern = functools.partial(_gemm_bias_kernel, relu=relu,
                             has_bias=has_bias)
    bspec = (pl.BlockSpec((1, bn), lambda i, j: (0, j),
                          memory_space=pltpu.VMEM) if has_bias
             else pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                               memory_space=pltpu.VMEM))
    return pl.pallas_call(
        kern,
        grid=(m // bm, o // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, bn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            bspec,
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, o), x2d.dtype),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(x2d, w2d, b2)


def conv1x1_block(x, wk, bias=None, *, stride: int = 1,
                  relu: bool = False, interpret: bool = False,
                  bm: int = 0, bn: int = 0):
    """The group's 1x1 conv as the fused GEMM: ``x`` NHWC, ``wk``
    ``(1,1,C,O)`` (or already ``(C,O)``), ``bias`` the concatenated
    ``(O,)`` member biases.  Returns NHWC with ``O`` channels."""
    if stride > 1:
        x = x[:, ::stride, ::stride, :]
    n, h, w, c = x.shape
    w2d = wk.reshape(wk.shape[-2], wk.shape[-1])
    y = fused_block_gemm(x.reshape(-1, c), w2d, bias, relu=relu,
                         interpret=interpret, bm=bm, bn=bn)
    return y.reshape(n, h, w, -1)


def probe(backend: str, x=None, wk=None, **_kw):
    """Capability probe: None when launchable, else the reject reason.
    Shape arguments are optional — a conf-time probe only has the
    backend; a trace-time probe has the real operands."""
    if x is not None:
        if x.ndim != 4:
            return f"input must be NHWC, got ndim={x.ndim}"
        if x.dtype not in (jnp.float32, jnp.bfloat16):
            return f"unsupported activation dtype {x.dtype}"
    if wk is not None and wk.ndim == 4 and wk.shape[:2] != (1, 1):
        return f"kernel must be 1x1, got {wk.shape[:2]}"
    return None
