"""int8 GEMM with the per-channel rescale inside the kernel epilogue.

The PR-10 quant scheme (``ops/quant.py``) feeds RAW int8 codes to the
contraction and folds the per-output-channel rescale into the f32 bias
add OUTSIDE it — correct because the scale commutes out of the
contraction, but spelled as separate XLA ops the fusion of which is the
compiler's mood.  This kernel pins the whole chain —
cast(int8)→MXU→rescale→bias→activation — into ONE Pallas program: the
f32 accumulator tile is rescaled, biased and (optionally) relu'd while
still in VMEM, and only the finished activation-dtype tile is written
back.

Bit contract (the acceptance bar): with default full-array blocks the
kernel replays the stock ``fc_apply_q`` ops in the identical order —
``dot_general(x, q.astype(x.dtype), preferred_element_type=f32)``,
``* scale``, ``+ bias``, ``astype(x.dtype)`` — so interpret mode on CPU
is BIT-EQUAL to the PR-10 dequant-free reference
(tests/test_kernels.py pins it with ``np.array_equal``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .._compat import pallas_tpu_compiler_params
from .conv_block import _pick_block


def _int8_kernel(x_ref, q_ref, s_ref, b_ref, o_ref, *, relu, has_bias):
    # identical op chain to ops/quant.fc_apply_q + _rescale_bias: the
    # int8 codes are cast to the activation dtype (exact: |codes| <= 127
    # fit bf16's mantissa), contracted with f32 accumulation, and the
    # epilogue rescales in f32
    y = jax.lax.dot_general(
        x_ref[:], q_ref[:].astype(x_ref.dtype),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    y = y * s_ref[:].astype(jnp.float32)
    if has_bias:
        y = y + b_ref[:].astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[:] = y.astype(o_ref.dtype)


def int8_gemm_rescale(x2d, q, scale, bias=None, *, relu: bool = False,
                      interpret: bool = False, bm: int = 0, bn: int = 0):
    """``relu?((x2d @ q.T) * scale + bias).astype(x.dtype)`` fused.

    ``x2d`` is ``(M, K)`` f32/bf16, ``q`` ``(O, K)`` int8 (the fullc
    layout — the int8 array itself is the program operand; weights at
    rest stay 1 byte/element), ``scale`` ``(O,)`` f32, ``bias`` ``(O,)``
    or None.  ``bm``/``bn`` tile M/O (0 = whole axis, the bit-parity
    default); K stays whole so each output element is one full-K
    contraction in f32.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x2d.shape
    o, k2 = q.shape
    if k != k2:
        raise ValueError(f"int8_gemm_rescale: K mismatch {k} vs {k2}")
    has_bias = bias is not None
    s2 = scale.reshape(1, o)
    b2 = (bias.reshape(1, o) if has_bias
          else jnp.zeros((1, 1), jnp.float32))
    bm = _pick_block(m, bm) if bm else m
    bn = _pick_block(o, bn) if bn else o
    kern = functools.partial(_int8_kernel, relu=relu, has_bias=has_bias)
    row = lambda i, j: (0, j)  # noqa: E731 - (1, bn) per-channel rows
    bspec = (pl.BlockSpec((1, bn), row, memory_space=pltpu.VMEM)
             if has_bias
             else pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                               memory_space=pltpu.VMEM))
    return pl.pallas_call(
        kern,
        grid=(m // bm, o // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, k), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), row, memory_space=pltpu.VMEM),
            bspec,
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, o), x2d.dtype),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(x2d, q, s2, b2)


def probe(backend: str, x=None, q=None, **_kw):
    """None when launchable, else the reject reason."""
    if x is not None and x.dtype not in (jnp.float32, jnp.bfloat16):
        return f"unsupported activation dtype {x.dtype}"
    if q is not None and q.dtype != jnp.int8:
        return f"codes must be int8, got {q.dtype}"
    return None
