"""Version-compat shims shared by the ops kernels."""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect

_PARAMS = inspect.signature(_shard_map).parameters
# replication checking was renamed check_rep -> check_vma in jax 0.9; the
# ring/pipeline kernels disable it (ppermute under scan confuses it)
_CHECK_KW = "check_vma" if "check_vma" in _PARAMS else "check_rep"


def shard_map_nocheck(f, mesh, in_specs, out_specs):
    """shard_map with replication checking off, any jax version."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: False},
    )


def pallas_tpu_compiler_params(**kw):
    """Pallas-TPU compiler params under either spelling: the class was
    ``TPUCompilerParams`` through jax 0.4.x and renamed
    ``CompilerParams`` in 0.5."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:  # pragma: no cover - depends on installed jax
        cls = pltpu.TPUCompilerParams
    return cls(**kw)
