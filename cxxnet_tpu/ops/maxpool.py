"""Fused ceil-shape max pooling as Pallas kernels.

Semantics parity: the reference pooling layer
(``/root/reference/src/layer/pooling_layer-inl.hpp``) — ceil output
shapes with partial edge windows, and the mshadow ``unpool`` backward
(every input position equal to its window's max receives that window's
gradient).  Identical math to the XLA expression in
``layers/conv._maxpool_eq``; that path remains the golden model and the
non-TPU fallback.

Why a kernel: the XLA lowering of the k*k shifted-slice tree (forward)
and the compare + interior-pad-expand chain (backward) materializes
intermediates in HBM between fusions — measured ~17 ms/step across
GoogLeNet b128's 13 pools even after the unpool-VJP rewrite
(doc/performance.md).  Here each grid cell holds one batch row's whole
spatial plane in VMEM and runs the entire tree register-resident: one
HBM read of x (+ y, g for backward) and one write.

Grid: ``(N,)`` — one image per cell; the largest GoogLeNet plane
(112x112x64 bf16 + padded copy + output) is ~5 MB, well inside the
~16 MB VMEM budget.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _geometry(h: int, w: int, kh: int, kw: int, s: int, py: int, px: int):
    """Mirrors layers/conv._pool_geometry (kept import-cycle-free)."""
    def ceil_shape(n, k, p):
        if p == 0:
            return min(n - k + s - 1, n - 1) // s + 1
        out = (n + 2 * p - k + s - 1) // s + 1
        if (out - 1) * s >= n + p:
            out -= 1
        return out

    oh, ow = ceil_shape(h, kh, py), ceil_shape(w, kw, px)
    prh = max(0, (oh - 1) * s + kh - h - py)
    prw = max(0, (ow - 1) * s + kw - w - px)
    return (py, prh), (px, prw), oh, ow


def _pad_plane(xb, pads_h, pads_w, val):
    return jnp.pad(
        xb, ((0, 0), pads_h, pads_w, (0, 0)),
        constant_values=xb.dtype.type(val),
    )


def _fwd_kernel(x_ref, o_ref, *, kh, kw, s, py, px):
    xb = x_ref[:]
    (plh, prh), (plw, prw), oh, ow = _geometry(
        xb.shape[1], xb.shape[2], kh, kw, s, py, px
    )
    xp = _pad_plane(xb, (plh, prh), (plw, prw), -jnp.inf)
    acc = None
    for dy in range(kh):
        for dx in range(kw):
            sl = xp[:, dy:dy + (oh - 1) * s + 1:s,
                    dx:dx + (ow - 1) * s + 1:s, :]
            acc = sl if acc is None else lax.max(acc, sl)
    o_ref[:] = acc


def _bwd_kernel(x_ref, y_ref, g_ref, dx_ref, *, kh, kw, s, py, px):
    xb = x_ref[:]
    # equality compare in f32: Mosaic on v5e rejects bf16 cmpf, and the
    # bf16->f32 cast is exact so the tie set is unchanged
    y = y_ref[:].astype(jnp.float32)
    g = g_ref[:]
    h, w = xb.shape[1], xb.shape[2]
    (plh, prh), (plw, prw), oh, ow = _geometry(h, w, kh, kw, s, py, px)
    xp = _pad_plane(xb, (plh, prh), (plw, prw), -jnp.inf).astype(jnp.float32)
    hp, wp = xp.shape[1], xp.shape[2]
    zero = jnp.zeros((), g.dtype)
    total = None
    for dy in range(kh):
        for dx in range(kw):
            xw = xp[:, dy:dy + (oh - 1) * s + 1:s,
                    dx:dx + (ow - 1) * s + 1:s, :]
            contrib = jnp.where(xw == y, g, zero)
            exp = lax.pad(
                contrib, zero,
                ((0, 0, 0),
                 (dy, hp - (dy + (oh - 1) * s + 1), s - 1),
                 (dx, wp - (dx + (ow - 1) * s + 1), s - 1),
                 (0, 0, 0)),
            )
            total = exp if total is None else total + exp
    dx_ref[:] = total[:, plh:plh + h, plw:plw + w, :]


def _call(kernel, x_shape, out_shape, dtype, args, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = x_shape[0]

    def spec(shape):
        return pl.BlockSpec(
            (1,) + tuple(shape[1:]),
            lambda i: (i,) + (0,) * (len(shape) - 1),
            memory_space=pltpu.VMEM,
        )

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(out_shape, dtype),
        grid=(n,),
        in_specs=[spec(a.shape) for a in args],
        out_specs=spec(out_shape),
        interpret=interpret,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def maxpool_fused(x, kh: int, kw: int, s: int, py: int = 0, px: int = 0,
                  interpret: bool = False):
    """Pallas max pool over NHWC with the unpool-equality backward."""
    _, _, oh, ow = _geometry(x.shape[1], x.shape[2], kh, kw, s, py, px)
    kern = functools.partial(_fwd_kernel, kh=kh, kw=kw, s=s, py=py, px=px)
    out_shape = (x.shape[0], oh, ow, x.shape[3])
    return _call(kern, x.shape, out_shape, x.dtype, (x,), interpret)


def _mp_fwd(x, kh, kw, s, py, px, interpret):
    y = maxpool_fused(x, kh, kw, s, py, px, interpret)
    return y, (x, y)


def _mp_bwd(kh, kw, s, py, px, interpret, res, g):
    x, y = res
    kern = functools.partial(_bwd_kernel, kh=kh, kw=kw, s=s, py=py, px=px)
    dx = _call(
        kern, x.shape, x.shape, x.dtype, (x, y, g.astype(x.dtype)),
        interpret,
    )
    return (dx,)


maxpool_fused.defvjp(_mp_fwd, _mp_bwd)

def _bwd_s1_kernel(x_ref, y_ref, g_ref, dx_ref, *, k, pl_, pr_):
    """One-pass stride-1 backward: pad y/g in VMEM so every output
    window covering input position (i, j) is a plain shifted slice,
    then sum the 9 (k*k) equality-gated gradient reads.  Measured 1.5x
    the XLA pad-and-add form in isolation at GoogLeNet's 28x28x256
    inception pool (doc/performance.md)."""
    x = x_ref[:].astype(jnp.float32)
    h, w = x.shape[1], x.shape[2]
    # output (i', j') covers inputs i' .. i'+k-1 (left pad pl_); input
    # (i, j) is covered by outputs i-k+1+pl_ .. i+pl_ — pad y/g so those
    # reads become slices at offsets 0..k-1
    yp = jnp.pad(y_ref[:].astype(jnp.float32),
                 ((0, 0), (k - 1 - pl_, pl_), (k - 1 - pl_, pl_), (0, 0)),
                 constant_values=jnp.inf)
    gp = jnp.pad(g_ref[:].astype(jnp.float32),
                 ((0, 0), (k - 1 - pl_, pl_), (k - 1 - pl_, pl_), (0, 0)))
    acc = None
    for dy in range(k):
        for dx in range(k):
            ys = yp[:, dy:dy + h, dx:dx + w, :]
            gs = gp[:, dy:dy + h, dx:dx + w, :]
            c = jnp.where(x == ys, gs, 0.0)
            acc = c if acc is None else acc + c
    dx_ref[:] = acc.astype(dx_ref.dtype)


def maxpool_bwd_s1(x, y, g, k: int, pad: int, interpret: bool = False):
    """Stride-1 unpool-equality backward as a single fused pass.

    Semantics identical to the XLA form in ``conv._maxpool_eq_bwd``
    restricted to ``stride == 1`` (where the ceil-shape output equals
    the input size and no interior padding exists); the pairtest golden
    is that path.
    """
    (pl_, pr_), _, oh, ow = _geometry(
        x.shape[1], x.shape[2], k, k, 1, pad, pad
    )
    assert (oh, ow) == (x.shape[1], x.shape[2]), "stride-1 same-size only"
    kern = functools.partial(_bwd_s1_kernel, k=k, pl_=pl_, pr_=pr_)
    return _call(kern, x.shape, x.shape, x.dtype, (x, y, g), interpret)

