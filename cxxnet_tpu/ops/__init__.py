"""Hand-tuned TPU kernels (Pallas).

The reference's analog is user-extensible mshadow expressions — e.g. the
custom ``Plan`` structs in
``/root/reference/src/layer/insanity_pooling_layer-inl.hpp:13-215`` that
extend the tensor compiler where stock expressions fall short.  Here the
stock compiler is XLA; where its lowering of an op is not TPU-shaped, the
op gets a Pallas kernel with a custom VJP.  Every kernel has an
``interpret=True`` path so the same code runs (slowly) on CPU for golden
tests against the pure-XLA implementation (the PairTest discipline,
SURVEY §4.1).
"""

from .attention import (  # noqa: F401
    a2a_self_attention,
    mha,
    ring_attention,
    ring_attention_flash,
    ring_self_attention,
    ring_self_attention_flash,
)
from .flash import flash_mha, flash_mha_lse  # noqa: F401
from .maxpool import maxpool_bwd_s1, maxpool_fused  # noqa: F401
from .lrn import lrn, lrn_xla  # noqa: F401
from .pipeline import gpipe, pipeline_apply  # noqa: F401
from . import quant  # noqa: F401
