"""MNIST idx-format iterator.

Parity: ``/root/reference/src/io/iter_mnist-inl.hpp`` — loads the idx
images/labels into RAM, scales pixels by 1/256, optional one-shot shuffle
(``shuffle``, ``seed_data``), ``input_flat`` chooses flat vs image nodes,
``index_offset``; the final partial batch is dropped.
"""

from __future__ import annotations

import struct

import numpy as np

from .data import DataBatch, DataIter


def read_idx_images(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        magic, count, rows, cols = struct.unpack(">iiii", f.read(16))
        buf = f.read(count * rows * cols)
    return np.frombuffer(buf, np.uint8).reshape(count, rows, cols)


def read_idx_labels(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        magic, count = struct.unpack(">ii", f.read(8))
        buf = f.read(count)
    return np.frombuffer(buf, np.uint8)


def write_idx_images(path: str, imgs: np.ndarray) -> None:
    """idx3 writer (for tools/tests; the reference ships data externally)."""
    n, r, c = imgs.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">iiii", 0x803, n, r, c))
        f.write(imgs.astype(np.uint8).tobytes())


def write_idx_labels(path: str, labels: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack(">ii", 0x801, len(labels)))
        f.write(labels.astype(np.uint8).tobytes())


class MNISTIterator(DataIter):
    def supports_dist_shard(self) -> bool:
        return True

    def __init__(self) -> None:
        self.batch_size = 0
        self.input_flat = 1
        self.shuffle = 0
        self.index_offset = 0
        self.silent = 0
        self.path_img = ""
        self.path_label = ""
        self.seed = 0
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self.dist_shard = "interleave"  # or "block": contiguous batches
        self._loc = 0
        self._img: np.ndarray | None = None
        self._label: np.ndarray | None = None
        self._inst: np.ndarray | None = None

    def set_param(self, name, val):
        if name == "batch_size":
            self.batch_size = int(val)
        elif name == "input_flat":
            self.input_flat = int(val)
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "index_offset":
            self.index_offset = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "path_img":
            self.path_img = val
        elif name == "path_label":
            self.path_label = val
        elif name == "seed_data":
            self.seed = int(val)
        elif name == "dist_num_worker":
            self.dist_num_worker = int(val)
        elif name == "dist_worker_rank":
            self.dist_worker_rank = int(val)
        elif name == "dist_shard":
            if val not in ("interleave", "block"):
                raise ValueError(
                    f"dist_shard={val!r}: must be interleave or block")
            self.dist_shard = val

    def init(self):
        imgs = read_idx_images(self.path_img).astype(np.float32) / 256.0
        labels = read_idx_labels(self.path_label).astype(np.float32)
        if self.batch_size <= 0:
            raise ValueError("MNISTIterator: batch_size must be set")
        inst = np.arange(len(labels), dtype=np.uint32) + self.index_offset
        if self.shuffle:
            rng = np.random.RandomState(42 + self.seed)
            perm = rng.permutation(len(labels))
            imgs, labels, inst = imgs[perm], labels[perm], inst[perm]
        if self.dist_num_worker > 1:
            # distributed data sharding after the deterministic shuffle
            # so shards are disjoint AND mixed; equal-truncated so every
            # worker runs the same batch count (see data.shard_rows).
            # dist_shard = block deals rows out in local-batch-size
            # blocks instead: the assembled global SPMD batch is then
            # row-identical to a single-process run — the bitwise
            # parity contract of the MESH=1 lane
            from .data import shard_rows

            sl = shard_rows(
                len(labels), self.dist_worker_rank, self.dist_num_worker,
                block=(self.batch_size if self.dist_shard == "block"
                       else 1),
            )
            imgs, labels, inst = imgs[sl], labels[sl], inst[sl]
        if self.input_flat:
            self._img = imgs.reshape(len(labels), -1)
        else:
            self._img = imgs[..., None]  # NHWC with C=1
        self._label = labels[:, None]
        self._inst = inst
        if not self.silent:
            print(
                f"MNISTIterator: load {len(labels)} images, "
                f"shuffle={self.shuffle}, shape={self._img.shape}"
            )

    def before_first(self):
        self._loc = 0

    def next(self) -> bool:
        assert self._img is not None, "init() not called"
        if self._loc + self.batch_size <= self._img.shape[0]:
            self._loc += self.batch_size
            return True
        return False

    def value(self) -> DataBatch:
        lo, hi = self._loc - self.batch_size, self._loc
        return DataBatch(
            data=self._img[lo:hi],
            label=self._label[lo:hi],
            inst_index=self._inst[lo:hi],
        )
