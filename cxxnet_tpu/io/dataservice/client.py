"""``iter = service``: the data-service client iterator.

Slots into the ordered chain factory as a base iterator — any trainer,
tenant loop, or eval conf becomes service-fed by replacing its local
decode chain with::

    data = train
    iter = service
    data_service_addr = 127.0.0.1:9040
    iter = end

The stream is addressed, not positional: the client's durable cursor is
``(epoch, local block k)``, advanced only when block ``k`` has been
delivered to the consumer.  An RPC worker thread keeps up to
``data_service_window`` GETs pipelined on one TCP session and feeds a
bounded queue (the ``threadbuffer`` discipline: generation counter for
rewinds, producer errors relayed into the consumer's ``next()``, a
:class:`~cxxnet_tpu.utils.faults.Watchdog` so a wedged server fails
fast instead of hanging the train loop).  Every RPC passes the
``dataservice.rpc`` fault site.

Recovery: any transport error — including a server SIGKILL mid-epoch —
drops the connection, reconnects with bounded retries, re-OPENs, and
re-requests from the cursor; because the server deals a deterministic
addressed stream, the resumed bytes are identical to the uninterrupted
ones (the DSVC parity lane proves this end to end with checkpoint
CRCs).  The OPENED fingerprint is pinned at the first handshake: a
reconnect landing on a server with different data fails loudly instead
of silently splicing two datasets into one run.

Epoch anchoring matches the CLI train loop: each round's
``before_first()`` + ``set_param("augment_epoch", N)`` pins the epoch
the GETs are keyed by; plain ``for batch in it`` loops advance epochs
0, 1, 2, ... on their own.
"""

from __future__ import annotations

import collections
import queue
import socket
import threading
import time
from typing import Deque, Optional

from ...obs.registry import registry as obs_registry
from ...utils import faults
from ...utils.faults import Watchdog, WatchdogError
from ..data import DataBatch, DataIter
from . import wire

__all__ = ["ServiceIterator"]

_END = object()


class _WorkerError:
    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


class ServiceIterator(DataIter):
    """Client end of the data service (``iter = service``)."""

    def __init__(self) -> None:
        self.addr = ""
        self.batch_size = 0
        self.rank = 0
        self.nworker = 1
        self.silent = 0
        self.window = 2
        self.retries = 60
        self.retry_delay_s = 0.5
        self.connect_timeout_s = 5.0
        self.watchdog_timeout_s = 600.0
        self._epoch = -1
        self._pin = False            # augment_epoch pinned for next pass
        self._started = False
        self._gen = 0
        self._gen_lock = threading.Condition()
        self._stop = False
        self._closed = False
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[Watchdog] = None
        self._cur: Optional[DataBatch] = None
        self._conn: Optional[socket.socket] = None
        self._conn_lock = threading.Lock()
        self._fingerprint: Optional[str] = None
        self.reconnects = 0
        self._m_reconnects = None
        self._m_stall = None

    def supports_dist_shard(self) -> bool:
        return True

    def set_param(self, name: str, val: str) -> None:
        if name == "data_service_addr":
            self.addr = val
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "dist_worker_rank":
            self.rank = int(val)
        elif name == "dist_num_worker":
            self.nworker = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "data_service_window":
            self.window = max(1, int(val))
        elif name == "data_service_retries":
            self.retries = int(val)
        elif name == "data_service_retry_delay_s":
            self.retry_delay_s = float(val)
        elif name == "data_service_connect_timeout_s":
            self.connect_timeout_s = float(val)
        elif name in ("data_service_timeout_s", "watchdog_timeout_s"):
            self.watchdog_timeout_s = float(val)
        elif name == "augment_epoch":
            e = int(val)
            with self._gen_lock:
                if e != self._epoch:
                    self._epoch = e
                    if self._started:
                        # the live pass was keyed by the wrong epoch:
                        # restart the generation on the corrected one
                        self._gen += 1
                        self._gen_lock.notify_all()
                self._pin = True

    def init(self) -> None:
        if not self.addr or ":" not in self.addr:
            raise ValueError(
                "iter=service needs data_service_addr = host:port")
        if self.batch_size <= 0:
            raise ValueError("iter=service needs batch_size")
        host, port = self.addr.rsplit(":", 1)
        self._host, self._port = host, int(port)
        reg = obs_registry()
        self._m_reconnects = reg.counter(
            "dataservice_reconnects_total",
            "Client reconnect+resume cycles against the data service.")
        self._m_stall = reg.histogram(
            "dataservice_client_stall_seconds",
            "Consumer time blocked waiting for the service stream.")
        self._q = queue.Queue(maxsize=self.window)
        self._thread = threading.Thread(
            target=self._worker, name="dataservice-client", daemon=True)
        self._watchdog = Watchdog(
            what="data service client",
            timeout_s=self.watchdog_timeout_s,
            thread=self._thread,
        )
        self._thread.start()
        if not self.silent:
            print(f"ServiceIterator: {self.addr} window={self.window} "
                  f"rank={self.rank}/{self.nworker}", flush=True)

    # ------------------------------------------------------------------
    # connection management (worker thread only, except close())
    def _ensure_conn(self) -> socket.socket:
        with self._conn_lock:
            if self._conn is not None:
                return self._conn
        sock = socket.create_connection(
            (self._host, self._port), timeout=self.connect_timeout_s)
        try:
            sock.settimeout(None)
            wire.write_frame(sock, wire.encode_open(
                self.batch_size, self.rank, self.nworker, self.window))
            body = wire.read_frame(sock)
            if body is None:
                raise ConnectionError("server closed during OPEN")
            kind, payload = wire.decode_kind(body)
            if kind == wire.ERR:
                doc = wire.decode_json(payload)
                raise wire.ServiceError(doc.get("reason", "internal"),
                                        doc.get("detail", ""))
            if kind != wire.OPENED:
                raise wire.WireError(
                    "bad_kind", f"expected OPENED, got kind {kind}")
            doc = wire.decode_json(payload)
            fp = str(doc.get("fingerprint", ""))
            if self._fingerprint is None:
                self._fingerprint = fp
            elif fp != self._fingerprint:
                raise RuntimeError(
                    "data_service: dataset fingerprint changed across "
                    f"reconnect ({self._fingerprint} -> {fp}); refusing "
                    "to splice two datasets into one deterministic run")
        except BaseException:
            sock.close()
            raise
        with self._conn_lock:
            self._conn = sock
        return sock

    def _drop_conn(self) -> None:
        with self._conn_lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------------------
    # worker
    def _stale(self, gen: int) -> bool:
        with self._gen_lock:
            return self._stop or self._gen != gen

    def _put(self, item) -> bool:
        gen = item[0]
        while True:
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                if self._stale(gen):
                    return False

    def _worker(self) -> None:
        served = 0
        wd = self._watchdog
        try:
            while True:
                with self._gen_lock:
                    while not self._stop and self._gen <= served:
                        wd.beat()  # idling for a rewind is progress
                        self._gen_lock.wait(timeout=0.5)
                    if self._stop:
                        return
                    gen, epoch = self._gen, self._epoch
                # a fresh generation must not receive frames pipelined
                # for the previous one: start from a clean session
                self._drop_conn()
                try:
                    self._serve_gen(gen, epoch)
                except Exception as e:  # noqa: BLE001 - relayed
                    self._put((gen, _WorkerError(e)))
                    self._put((gen, _END))
                    self._drop_conn()
                served = gen
        finally:
            with self._conn_lock:
                conn, self._conn = self._conn, None
            if conn is not None:
                try:
                    wire.write_frame(conn, wire.encode_close())
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass

    def _serve_gen(self, gen: int, epoch: int) -> None:
        wd = self._watchdog
        k_done = 0                 # durable cursor: blocks delivered
        k_send = 0
        outstanding: Deque[int] = collections.deque()
        got_eoe = False
        attempts = 0
        while True:
            if self._stale(gen):
                self._drop_conn()
                return
            try:
                # every wire interaction passes the chaos site: an
                # injected ioerror exercises the same reconnect+resume
                # path a SIGKILLed server does
                faults.fault_point("dataservice.rpc")
                conn = self._ensure_conn()
                while not got_eoe and len(outstanding) < self.window:
                    wire.write_frame(conn, wire.encode_get(epoch, k_send))
                    outstanding.append(k_send)
                    k_send += 1
                if not outstanding:
                    self._put((gen, _END))
                    return
                body = wire.read_frame(conn)
                if body is None:
                    raise ConnectionError("server closed the stream")
                kind, payload = wire.decode_kind(body)
                expect = outstanding[0]
                if kind == wire.BATCH:
                    ep, blk, _hit, data, label, inst, padd = \
                        wire.decode_batch(payload)
                    if ep != epoch or blk != expect:
                        raise ConnectionError(
                            f"stream desync: got ({ep},{blk}), "
                            f"want ({epoch},{expect})")
                    outstanding.popleft()
                    wd.beat()
                    if not self._put((gen, DataBatch(
                            data=data, label=label, inst_index=inst,
                            num_batch_padd=padd))):
                        self._drop_conn()
                        return  # consumer rewound or stopped
                    k_done += 1
                    attempts = 0
                    wd.beat()
                elif kind == wire.EOE:
                    ep, _nblocks = wire.decode_eoe(payload)
                    if ep != epoch:
                        raise ConnectionError(
                            f"stream desync: EOE for epoch {ep}, "
                            f"want {epoch}")
                    outstanding.popleft()
                    got_eoe = True
                    wd.beat()
                elif kind == wire.ERR:
                    doc = wire.decode_json(payload)
                    raise wire.ServiceError(
                        doc.get("reason", "internal"),
                        doc.get("detail", ""))
                else:
                    raise wire.WireError(
                        "bad_kind",
                        f"unexpected kind {kind} inside a session")
            except wire.ServiceError as e:
                if e.reason != "overloaded":
                    raise
                # 429-style shed: back off and retry the admission
                self._recover(gen, epoch)
                attempts += 1
                if attempts > self.retries:
                    raise
                outstanding.clear()
                k_send = k_done
                got_eoe = False
                time.sleep(self.retry_delay_s)
            except OSError as e:
                # transport loss (incl. injected faults and a killed
                # server): reconnect and resume from the durable cursor
                attempts += 1
                if attempts > self.retries:
                    raise ConnectionError(
                        f"data_service at {self.addr} unreachable after "
                        f"{self.retries} reconnect attempts: "
                        f"{type(e).__name__}: {e}") from e
                self._recover(gen, epoch)
                outstanding.clear()
                k_send = k_done
                got_eoe = False
                time.sleep(self.retry_delay_s)

    def _recover(self, gen: int, epoch: int) -> None:
        self._drop_conn()
        self.reconnects += 1
        if self._m_reconnects is not None:
            self._m_reconnects.inc()
        if not self.silent:
            print(f"ServiceIterator: connection lost, resuming "
                  f"epoch {epoch} (reconnect #{self.reconnects})",
                  flush=True)

    # ------------------------------------------------------------------
    # consumer protocol
    def before_first(self) -> None:
        assert self._q is not None, "init() not called"
        with self._gen_lock:
            if self._pin:
                self._pin = False
            else:
                self._epoch += 1
            self._started = True
            self._gen += 1
            self._gen_lock.notify_all()

    def next(self) -> bool:
        assert self._q is not None, "init() not called"
        wd = self._watchdog
        t0 = time.monotonic()
        try:
            while True:
                try:
                    gen, item = self._q.get(timeout=0.2)
                except queue.Empty:
                    t = self._thread
                    if (t is not None and not t.is_alive()
                            and self._q.empty()):
                        raise WatchdogError(
                            "data service client worker died without "
                            "delivering a result") from None
                    if wd is not None:
                        wd.check()
                    continue
                if gen != self._gen:
                    continue  # stale generation
                if item is _END:
                    return False
                if isinstance(item, _WorkerError):
                    raise item.exc
                self._cur = item
                return True
        finally:
            if self._m_stall is not None:
                self._m_stall.observe(time.monotonic() - t0)

    def value(self) -> DataBatch:
        assert self._cur is not None
        return self._cur

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._gen_lock:
            self._stop = True
            self._gen_lock.notify_all()
        # unblock a worker parked in recv: shut the socket down under
        # it (the worker owns the close)
        with self._conn_lock:
            if self._conn is not None:
                try:
                    self._conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        thread, self._thread = self._thread, None
        if self._q is not None:
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)

    def __del__(self):  # pragma: no cover - GC-order dependent
        try:
            self.close()
        except Exception:
            pass
