"""Disaggregated input-data service (doc/io.md "Data service").

A standalone decode/augment fleet: ``task=data_service`` hosts a conf's
iterator chain behind the binary ``CXD1`` batch protocol
(:mod:`.wire`); ``iter = service`` (:class:`.client.ServiceIterator`)
is the drop-in chain base that streams from it.  The stream is
addressed by ``(dataset fingerprint, epoch, block)`` and therefore
bitwise-deterministic across cache hits, reconnects, and server
restarts — the property the DSVC parity lane pins with checkpoint CRCs.
"""

from .cache import ChunkCache
from .client import ServiceIterator
from .server import BatchPlant, DataServiceServer, dataset_fingerprint

__all__ = [
    "ChunkCache", "ServiceIterator", "BatchPlant", "DataServiceServer",
    "dataset_fingerprint",
]
