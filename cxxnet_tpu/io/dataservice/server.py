"""Data-service server: a shared decode/augment fleet member.

``task=data_service`` hosts the conf's ``data`` section iterator chain
(the SAME ``create_iterator`` chain a local trainer would build) behind
the ``CXD1`` protocol, so N trainers/tenants/eval jobs on one pool
decode each block once instead of N times (the disaggregated input
pipeline of the TensorFlow-systems design, arXiv 1605.08695 — and the
off-accelerator-host placement 1901.05803 argues for).

Determinism contract: the stream is addressed, not positional.  A GET
names ``(epoch, local block k)``; the server maps it to global block
``j = k * nworker + rank`` of the epoch's stream and produces it by
rewinding its chain (``before_first`` + ``augment_epoch``) and stepping
forward — legal because the chains are epoch-keyed and history-free
(one-shot shuffle at ``init``; pure-hash ``RecordRNG`` augmentation
keyed by ``(epoch, record index)``).  Two consequences the tests pin
down: a client that reconnects after a server SIGKILL re-requests its
cursor and receives byte-identical rows, and the global stream dealt
across ``nworker`` clients is exactly the ``dist_shard = block`` deal a
local multi-process run performs — so service-fed training is bitwise
equal (checkpoint CRCs) to local-pipeline training.

An epoch's local length is ``epoch_len // nworker`` for every rank
(floor), matching ``shard_rows``'s equal-length contract; a GET at or
past it answers EOE.

Admission: at most ``max_sessions`` concurrent sessions — the
``max_sessions + 1``-th OPEN is shed with an ``overloaded`` ERR (the
429 analog of the serving plane); per-session pipelining is bounded by
the OPENED-clamped window.  Decoded blocks land in a byte-bounded LRU
(:mod:`.cache`) keyed ``(dataset_fingerprint, epoch, global block)``;
the fingerprint covers the section entries AND the referenced files'
sizes, so a dataset swap under a running server changes the key space
instead of serving stale rows.

Observability: ``dataservice_sessions``, ``dataservice_batches_total
{hit}``, ``dataservice_cache_bytes``, ``dataservice_shed_total``,
``dataservice_produce_seconds``, ``dataservice_queue_wait_seconds``;
an HTTP sidecar serves ``/healthz``, ``/statsz`` and ``/metricsz``.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ...config import cfg_get
from ...obs import events as obs_events
from ...obs.registry import registry as obs_registry
from ..data import ConfigEntry, create_iterator
from . import wire
from .cache import CachedBlock, ChunkCache

__all__ = ["dataset_fingerprint", "BatchPlant", "DataServiceServer"]


def dataset_fingerprint(entries) -> str:
    """Identity of the dataset+chain config this server deals.

    crc32 over the ordered section entries plus the byte size of every
    entry value that is an existing file — enough to distinguish "same
    conf, different files" (regenerated data) from the stream a client
    checkpointed against, cheap enough to compute at every OPEN."""
    h = 0
    for name, val in entries:
        h = zlib.crc32(f"{name}={val}\n".encode("utf-8"), h)
        if val and os.path.isfile(val):
            h = zlib.crc32(
                f"{name}:{os.path.getsize(val)}\n".encode("utf-8"), h)
    return f"{h & 0xFFFFFFFF:08x}"


class _Session:
    __slots__ = ("sid", "rank", "nworker", "window", "epoch", "block",
                 "batches", "peer")

    def __init__(self, sid: int, rank: int, nworker: int, window: int,
                 peer: str) -> None:
        self.sid = sid
        self.rank = rank
        self.nworker = nworker
        self.window = window
        self.peer = peer
        self.epoch = -1   # last cursor served
        self.block = -1
        self.batches = 0


class BatchPlant:
    """The server's single decode/augment chain plus the block cache.

    One chain, one lock: block production is serialized (the chain is a
    stateful single-threaded object), cache hits bypass the lock
    entirely — that is where the multi-tenant concurrency comes from.
    """

    def __init__(self, section_entries: List[ConfigEntry],
                 global_entries: List[ConfigEntry],
                 cache_bytes: int, silent: bool = False) -> None:
        self.section_entries = list(section_entries)
        self.global_entries = list(global_entries)
        self.silent = silent
        self.fingerprint = dataset_fingerprint(self.section_entries)
        bs = cfg_get(self.global_entries + self.section_entries,
                     "batch_size")
        if bs is None:
            raise ValueError("data_service: the conf must set batch_size "
                             "(the block size the stream is dealt in)")
        self.batch_size = int(bs)
        self.cache = ChunkCache(cache_bytes)
        self._lock = threading.Lock()
        self._chain = None
        self._epoch = -1          # epoch the chain is positioned in
        self._pos = 0             # next global block the chain produces
        self._lens: Dict[int, int] = {}   # epoch -> global block count
        self.blocks_produced = 0
        reg = obs_registry()
        self._m_batches = reg.counter(
            "dataservice_batches_total",
            "Blocks served by the data service.", labelnames=("hit",))
        reg.gauge(
            "dataservice_cache_bytes",
            "Decoded bytes held by the data-service chunk cache.",
        ).set_function(lambda: float(self.cache.bytes))
        self._m_produce = reg.histogram(
            "dataservice_produce_seconds",
            "Wall time decoding+augmenting one block on a cache miss.")
        self._m_wait = reg.histogram(
            "dataservice_queue_wait_seconds",
            "Time a request waited for the plant chain on a cache miss.")

    def init(self) -> None:
        """Build and init the chain exactly as a local trainer would:
        section entries at construction, global entries via set_param,
        then ``init()`` (mirrors ``cli._create_iterators``)."""
        self._chain = create_iterator(self.section_entries)
        for n, v in self.global_entries:
            self._chain.set_param(n, v)
        self._chain.init()

    def close(self) -> None:
        if self._chain is not None:
            self._chain.close()
            self._chain = None

    # ------------------------------------------------------------------
    def _rewind(self, epoch: int) -> None:
        # before_first() then augment_epoch — the exact per-round
        # re-anchoring sequence the CLI train loop issues, so the
        # chain's epoch-keyed state matches a local run of epoch N
        # regardless of what this chain served before
        self._chain.before_first()
        self._chain.set_param("augment_epoch", str(epoch))
        self._epoch = epoch
        self._pos = 0

    def _produce_up_to(self, epoch: int, j: int) -> Optional[CachedBlock]:
        """Step the chain to global block ``j`` of ``epoch``, caching
        every block on the way; None when the epoch ends first (the
        epoch's length is recorded as a side effect)."""
        if self._chain is None:
            raise RuntimeError("BatchPlant.init() not called")
        if epoch != self._epoch or j < self._pos:
            self._rewind(epoch)
        out: Optional[CachedBlock] = None
        while self._pos <= j:
            if not self._chain.next():
                self._lens[epoch] = self._pos
                return None
            b = self._chain.value()
            blk = CachedBlock(b.data, b.label, b.inst_index,
                              b.num_batch_padd)
            self.cache.put((self.fingerprint, epoch, self._pos), blk)
            self.blocks_produced += 1
            self._pos += 1
            out = blk
        return out

    def deal(self, epoch: int, k: int, rank: int,
             nworker: int) -> Tuple[str, object, bool]:
        """Serve local block ``k`` of ``epoch`` for ``(rank, nworker)``.

        Returns ``("batch", CachedBlock, cache_hit)`` or
        ``("eoe", local_block_count, False)``."""
        L = self._lens.get(epoch)
        if L is not None and k >= L // nworker:
            return "eoe", L // nworker, False
        j = k * nworker + rank
        key = (self.fingerprint, epoch, j)
        blk = self.cache.get(key, record=False)
        if blk is not None:
            self.cache.note_hit()
            self._m_batches.labels(hit="hit").inc()
            return "batch", blk, True
        t0 = time.monotonic()
        with self._lock:
            self._m_wait.observe(time.monotonic() - t0)
            # a concurrent producer may have filled the block while we
            # waited for the chain
            blk = self.cache.get(key, record=False)
            if blk is not None:
                self.cache.note_hit()
                self._m_batches.labels(hit="hit").inc()
                return "batch", blk, True
            t1 = time.monotonic()
            blk = self._produce_up_to(epoch, j)
            self._m_produce.observe(time.monotonic() - t1)
            if blk is None:
                L = self._lens[epoch]
                return "eoe", L // nworker, False
            self.cache.note_miss()
        self._m_batches.labels(hit="miss").inc()
        return "batch", blk, False

    def stats(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "batch_size": self.batch_size,
            "blocks_produced": self.blocks_produced,
            "epoch_lens": dict(self._lens),
            "cache": self.cache.stats(),
        }


class _TCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    # close() force-drops live session sockets itself (the SIGKILL
    # analog tests rely on); joining handler threads here would
    # deadlock against a still-connected client
    block_on_close = False


class DataServiceServer:
    """One data-service process: TCP batch plane + HTTP health plane.

    Tests drive it in-process via :meth:`start` / :meth:`close`; the
    CLI task blocks in :meth:`serve_forever` and stops it from a signal
    handler via :meth:`shutdown`."""

    def __init__(self, section_entries, global_entries, host="127.0.0.1",
                 port: int = 0, http_port: int = 0, max_sessions: int = 64,
                 cache_bytes: int = 256 << 20, window: int = 4,
                 ready_file: str = "", silent: bool = False) -> None:
        self.plant = BatchPlant(section_entries, global_entries,
                                cache_bytes, silent=silent)
        self.host = host
        self.port = port
        self.http_port = http_port
        self.max_sessions = int(max_sessions)
        self.window = int(window)
        self.ready_file = ready_file
        self.silent = silent
        self._sessions: Dict[int, _Session] = {}
        self._conns: set = set()   # live session sockets, for close()
        self._next_sid = 1
        self._lock = threading.Lock()
        self._tcp: Optional[_TCPServer] = None
        self._http: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        self._stopped = threading.Event()
        self._closed = False
        reg = obs_registry()
        self._m_sessions = reg.gauge(
            "dataservice_sessions",
            "Live data-service client sessions.")
        self._m_shed = reg.counter(
            "dataservice_shed_total",
            "Data-service admission refusals.", labelnames=("reason",))

    # ------------------------------------------------------------------
    # session plumbing
    def _admit(self, doc: dict, peer: str):
        try:
            bs = int(doc["batch_size"])
            rank = int(doc.get("rank", 0))
            nworker = int(doc.get("nworker", 1))
            window = int(doc.get("window", self.window))
        except (KeyError, TypeError, ValueError):
            return None, wire.encode_err(
                "bad_request", f"malformed OPEN params {doc!r}")
        if nworker < 1 or not 0 <= rank < nworker:
            return None, wire.encode_err(
                "bad_request", f"rank {rank} outside nworker {nworker}")
        if bs != self.plant.batch_size:
            return None, wire.encode_err(
                "batch_size_mismatch",
                f"client batch_size {bs} != service block size "
                f"{self.plant.batch_size}; point the service conf at "
                "the client's LOCAL batch size")
        with self._lock:
            if len(self._sessions) >= self.max_sessions:
                self._m_shed.labels(reason="overloaded").inc()
                return None, wire.encode_err(
                    "overloaded",
                    f"{len(self._sessions)} sessions at the "
                    f"max_sessions={self.max_sessions} ceiling")
            sid = self._next_sid
            self._next_sid += 1
            s = _Session(sid, rank, nworker,
                         max(1, min(window, self.window)), peer)
            self._sessions[sid] = s
            self._m_sessions.set(float(len(self._sessions)))
        obs_events.emit("dataservice.open", session=sid, peer=peer,
                        rank=rank, nworker=nworker)
        return s, None

    def _evict(self, s: _Session) -> None:
        with self._lock:
            self._sessions.pop(s.sid, None)
            self._m_sessions.set(float(len(self._sessions)))
        obs_events.emit("dataservice.close", session=s.sid,
                        batches=s.batches)

    def _handle_conn(self, sock: socket.socket, peer: str) -> None:
        session: Optional[_Session] = None
        with self._lock:
            self._conns.add(sock)
        try:
            body = wire.read_frame(sock)
            if body is None:
                return
            kind, payload = wire.decode_kind(body)
            if kind != wire.OPEN:
                wire.write_frame(sock, wire.encode_err(
                    "bad_request", "first frame must be OPEN"))
                return
            session, err = self._admit(wire.decode_json(payload), peer)
            if session is None:
                wire.write_frame(sock, err)
                return
            wire.write_frame(sock, wire.encode_opened(
                session.sid, self.plant.fingerprint, session.window))
            while True:
                body = wire.read_frame(sock)
                if body is None:
                    return  # client gone: EOF is a teardown signal
                kind, payload = wire.decode_kind(body)
                if kind == wire.CLOSE:
                    return
                if kind != wire.GET:
                    wire.write_frame(sock, wire.encode_err(
                        "bad_request",
                        f"unexpected frame kind {kind} in session"))
                    return
                epoch, k = wire.decode_get(payload)
                what, obj, hit = self.plant.deal(
                    epoch, k, session.rank, session.nworker)
                if what == "eoe":
                    wire.write_frame(sock, wire.encode_eoe(epoch, obj))
                else:
                    session.epoch, session.block = epoch, k
                    session.batches += 1
                    wire.write_frame(sock, wire.encode_batch(
                        obj.data, obj.label, obj.inst_index,
                        obj.num_batch_padd, epoch, k, hit))
        except (wire.WireError, ConnectionError, BrokenPipeError,
                OSError) as e:
            if not self.silent:
                print(f"data_service: session "
                      f"{session.sid if session else '?'} from {peer} "
                      f"dropped: {type(e).__name__}: {e}", flush=True)
            if isinstance(e, wire.WireError):
                try:
                    wire.write_frame(sock, wire.encode_err(
                        e.reason, str(e)))
                except OSError:
                    pass
        finally:
            with self._lock:
                self._conns.discard(sock)
            if session is not None:
                self._evict(session)

    # ------------------------------------------------------------------
    # lifecycle
    def start(self) -> None:
        """Init the plant, bind both planes, start serving in daemon
        threads, write the ready file; returns immediately."""
        self.plant.init()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                outer._handle_conn(self.request,
                                   f"{self.client_address[0]}:"
                                   f"{self.client_address[1]}")

        self._tcp = _TCPServer((self.host, self.port), _Handler)
        self.port = self._tcp.server_address[1]

        class _HTTP(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet health probes
                pass

            def do_GET(self) -> None:
                if self.path == "/healthz":
                    body = json.dumps(outer.healthz()).encode()
                    ctype = "application/json"
                elif self.path == "/statsz":
                    body = json.dumps(outer.statsz(), sort_keys=True,
                                      default=str).encode()
                    ctype = "application/json"
                elif self.path == "/metricsz":
                    body = obs_registry().render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._http = ThreadingHTTPServer((self.host, self.http_port),
                                         _HTTP)
        self._http.daemon_threads = True
        self._http.block_on_close = False
        self.http_port = self._http.server_address[1]
        for srv, name in ((self._tcp, "dataservice-tcp"),
                          (self._http, "dataservice-http")):
            t = threading.Thread(target=srv.serve_forever,
                                 name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if self.ready_file:
            # tmp+rename: a poller never reads a half-written doc
            tmp = self.ready_file + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"host": self.host, "port": self.port,
                           "http_port": self.http_port,
                           "fingerprint": self.plant.fingerprint,
                           "pid": os.getpid()}, f)
            os.replace(tmp, self.ready_file)
        if not self.silent:
            print(f"data_service: dealing fp "
                  f"{self.plant.fingerprint} blocks of "
                  f"{self.plant.batch_size} on {self.host}:{self.port} "
                  f"(http {self.http_port})", flush=True)

    def serve_forever(self) -> None:
        self.start()
        self._stopped.wait()

    def shutdown(self) -> None:
        """Stop both planes; safe from any thread (including a signal
        handler's helper thread)."""
        self._stopped.set()
        for srv in (self._tcp, self._http):
            if srv is not None:
                srv.shutdown()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.shutdown()
        # drop live sessions dead, like a SIGKILL would: clients must
        # see a broken pipe and take the reconnect-resume path
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for srv in (self._tcp, self._http):
            if srv is not None:
                srv.server_close()
        for t in self._threads:
            t.join(timeout=5.0)
        self.plant.close()

    # ------------------------------------------------------------------
    # health plane
    def healthz(self) -> dict:
        return {"status": "ok", "sessions": len(self._sessions),
                "fingerprint": self.plant.fingerprint}

    def statsz(self) -> dict:
        with self._lock:
            sessions = [{
                "session": s.sid, "peer": s.peer, "rank": s.rank,
                "nworker": s.nworker, "epoch": s.epoch, "block": s.block,
                "batches": s.batches,
            } for s in self._sessions.values()]
        st = self.plant.stats()
        st.update({
            "sessions": sessions,
            "max_sessions": self.max_sessions,
            "window": self.window,
            "port": self.port,
            "http_port": self.http_port,
        })
        return st
