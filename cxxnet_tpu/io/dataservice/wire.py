"""``CXD1``: the length-prefixed binary batch protocol of the data service.

The serving plane already moves bulk floats in binary frames
(``serve/wire.py``, ``CXB1``/``CXR1``) because JSON codec — not the
model — was the fleet bottleneck; the input plane has exactly the same
shape problem at scale (a decoded batch is megabytes of f32), so the
data service speaks the same dialect: little-endian ``struct`` headers
behind a 4-byte magic, stable machine-readable error tokens, and
``np.frombuffer`` zero-copy payload views.  Unlike CXB1 (one frame per
HTTP body) these frames ride a raw TCP stream, so every frame is
preceded by a ``u32`` byte length — the framing that lets a client
pipeline GETs and match responses without a parser state machine.

Frame kinds (header = magic ``CXD1`` + kind byte)::

    OPEN   0  client->server  JSON session params (batch_size, rank,
                              nworker, window)
    OPENED 1  server->client  JSON session grant (session id, dataset
                              fingerprint, clamped window)
    GET    2  client->server  <IQ>  epoch, local block index
    BATCH  3  server->client  _BATCH header + dims + f32 data +
                              f32 label + optional u32 inst_index
    EOE    4  server->client  <IQ>  epoch, local blocks in the epoch
    ERR    5  server->client  JSON {reason, detail}; ``overloaded`` is
                              the 429-style admission shed
    CLOSE  6  client->server  polite session teardown (EOF works too)

``BATCH`` echoes ``(epoch, block)`` so a client that reconnects
mid-stream can verify it is receiving exactly the cursor it asked for;
``flags`` bit0 marks a server cache hit (observability rides the wire),
bit1 marks an ``inst_index`` payload.

Reason tokens (``WireError.reason``): ``bad_magic``, ``bad_kind``,
``bad_json``, ``bad_open``, ``truncated_frame``, ``truncated_body``,
``trailing_bytes``, ``oversize_shape``.  Server refusals arrive as ERR
frames and surface as :class:`ServiceError` (reason tokens there:
``overloaded``, ``batch_size_mismatch``, ``bad_request``, ``internal``).

See doc/io.md "Data service" for the protocol contract.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import List, Optional, Tuple

import numpy as np

__all__ = [
    "MAGIC", "OPEN", "OPENED", "GET", "BATCH", "EOE", "ERR", "CLOSE",
    "WireError", "ServiceError", "MAX_FRAME_BYTES",
    "read_frame", "write_frame",
    "encode_open", "encode_opened", "encode_get", "encode_batch",
    "encode_eoe", "encode_err", "encode_close",
    "decode_kind", "decode_json", "decode_get", "decode_batch",
    "decode_eoe",
]

MAGIC = b"CXD1"

OPEN, OPENED, GET, BATCH, EOE, ERR, CLOSE = range(7)
_KIND_NAMES = ("OPEN", "OPENED", "GET", "BATCH", "EOE", "ERR", "CLOSE")

_HDR = struct.Struct("<4sB")      # magic, kind
_LEN = struct.Struct("<I")        # stream frame length prefix
_GET = struct.Struct("<IQ")       # epoch, local block
_EOE = struct.Struct("<IQ")       # epoch, local blocks this epoch
#: BATCH: flags, epoch, block, nrows, num_batch_padd, label_width, ndim
_BATCH = struct.Struct("<BIQIIHB")

FLAG_CACHE_HIT = 0x01
FLAG_HAS_INST = 0x02

_MAX_NDIM = 8
_F32 = np.dtype("<f4")
_U32 = np.dtype("<u4")

#: one decoded batch tops out well under this; the bound kills a
#: desynchronized length prefix before it becomes a giant allocation
MAX_FRAME_BYTES = 256 << 20


class WireError(ValueError):
    """Malformed ``CXD1`` frame.  ``reason`` is the stable token tests
    and clients key on; the text is for humans."""

    def __init__(self, reason: str, detail: str) -> None:
        self.reason = reason
        super().__init__(detail)


class ServiceError(RuntimeError):
    """A well-formed ERR frame from the server (refusal, not protocol
    damage).  ``reason == 'overloaded'`` is the retriable admission
    shed; everything else is a caller bug or server fault."""

    def __init__(self, reason: str, detail: str) -> None:
        self.reason = reason
        super().__init__(f"{reason}: {detail}")


# ----------------------------------------------------------------------
# stream framing
def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes; EOF mid-read is a ConnectionError so
    the client's reconnect path treats a killed server like any other
    broken pipe."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        b = sock.recv(min(n - got, 1 << 20))
        if not b:
            raise ConnectionError(
                f"connection closed {got}/{n} bytes into a frame")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


def read_frame(sock: socket.socket) -> Optional[bytes]:
    """Next frame body, or None on a clean EOF at a frame boundary."""
    head = b""
    while len(head) < _LEN.size:
        b = sock.recv(_LEN.size - len(head))
        if not b:
            if head:
                raise ConnectionError("connection closed inside a "
                                      "frame length prefix")
            return None
        head += b
    (n,) = _LEN.unpack(head)
    if n < _HDR.size or n > MAX_FRAME_BYTES:
        raise WireError("truncated_frame",
                        f"frame length {n} outside "
                        f"[{_HDR.size}, {MAX_FRAME_BYTES}]")
    return _recv_exact(sock, n)


def write_frame(sock: socket.socket, parts) -> None:
    """Send one frame from header+payload buffers with a single length
    prefix; the payload arrays are written straight from their
    memoryviews (no join copy)."""
    if isinstance(parts, (bytes, bytearray, memoryview)):
        parts = [parts]
    total = sum(len(p) for p in parts)
    if total > MAX_FRAME_BYTES:
        raise WireError("oversize_shape",
                        f"frame of {total} bytes exceeds "
                        f"{MAX_FRAME_BYTES}")
    sock.sendall(_LEN.pack(total))
    for p in parts:
        sock.sendall(p)


# ----------------------------------------------------------------------
# encoders
def _json_frame(kind: int, doc: dict) -> bytes:
    return _HDR.pack(MAGIC, kind) + json.dumps(
        doc, sort_keys=True, separators=(",", ":")).encode("utf-8")


def encode_open(batch_size: int, rank: int, nworker: int,
                window: int) -> bytes:
    return _json_frame(OPEN, {
        "batch_size": int(batch_size), "rank": int(rank),
        "nworker": int(nworker), "window": int(window),
    })


def encode_opened(session: int, fingerprint: str, window: int) -> bytes:
    return _json_frame(OPENED, {
        "session": int(session), "fingerprint": fingerprint,
        "window": int(window),
    })


def encode_get(epoch: int, block: int) -> bytes:
    return _HDR.pack(MAGIC, GET) + _GET.pack(epoch, block)


def encode_eoe(epoch: int, nblocks: int) -> bytes:
    return _HDR.pack(MAGIC, EOE) + _EOE.pack(epoch, nblocks)


def encode_err(reason: str, detail: str) -> bytes:
    return _json_frame(ERR, {"reason": reason, "detail": detail})


def encode_close() -> bytes:
    return _HDR.pack(MAGIC, CLOSE)


def encode_batch(data: np.ndarray, label: np.ndarray,
                 inst_index: Optional[np.ndarray], num_batch_padd: int,
                 epoch: int, block: int,
                 cache_hit: bool) -> List[bytes]:
    """``[header, data, label, inst?]`` buffers for :func:`write_frame`
    — the decoded arrays go to the socket without a join copy."""
    d = np.ascontiguousarray(data, _F32)
    lab = np.ascontiguousarray(label, _F32)
    if d.ndim < 1 or d.ndim > _MAX_NDIM:
        raise WireError("oversize_shape", f"cannot frame ndim {d.ndim}")
    nrows = d.shape[0]
    if lab.ndim != 2 or lab.shape[0] != nrows:
        raise WireError("oversize_shape",
                        f"label shape {lab.shape} does not match "
                        f"{nrows} data rows")
    flags = FLAG_CACHE_HIT if cache_hit else 0
    parts: List[bytes] = []
    if inst_index is not None:
        flags |= FLAG_HAS_INST
    head = _HDR.pack(MAGIC, BATCH) + _BATCH.pack(
        flags, epoch, block, nrows, num_batch_padd, lab.shape[1], d.ndim)
    head += struct.pack(f"<{d.ndim}I", *d.shape)
    parts.append(head)
    parts.append(memoryview(d).cast("B"))
    parts.append(memoryview(lab).cast("B"))
    if inst_index is not None:
        inst = np.ascontiguousarray(inst_index, _U32)
        if inst.shape != (nrows,):
            raise WireError("oversize_shape",
                            f"inst_index shape {inst.shape} for "
                            f"{nrows} rows")
        parts.append(memoryview(inst).cast("B"))
    return parts


# ----------------------------------------------------------------------
# decoders
def decode_kind(body) -> Tuple[int, memoryview]:
    """Validate the header; ``(kind, payload view)``."""
    view = memoryview(body)
    if len(view) < _HDR.size:
        raise WireError("truncated_frame",
                        f"{len(view)} bytes cannot hold a CXD1 header")
    magic, kind = _HDR.unpack_from(view, 0)
    if magic != MAGIC:
        raise WireError("bad_magic", f"bad frame magic {bytes(magic)!r}")
    if kind >= len(_KIND_NAMES):
        raise WireError("bad_kind", f"unknown kind byte {kind}")
    return kind, view[_HDR.size:]


def decode_json(payload: memoryview) -> dict:
    try:
        doc = json.loads(bytes(payload).decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        raise WireError("bad_json", "frame payload is not JSON")
    if not isinstance(doc, dict):
        raise WireError("bad_json", "frame payload is not a JSON object")
    return doc


def _fixed(payload: memoryview, st: struct.Struct, what: str):
    if len(payload) != st.size:
        raise WireError("truncated_body",
                        f"{what} payload is {len(payload)} bytes, "
                        f"want {st.size}")
    return st.unpack_from(payload, 0)


def decode_get(payload: memoryview) -> Tuple[int, int]:
    return _fixed(payload, _GET, "GET")  # (epoch, block)


def decode_eoe(payload: memoryview) -> Tuple[int, int]:
    return _fixed(payload, _EOE, "EOE")  # (epoch, nblocks)


def decode_batch(payload: memoryview):
    """``(epoch, block, cache_hit, data, label, inst, num_batch_padd)``
    — arrays are read-only ``np.frombuffer`` views over the frame."""
    if len(payload) < _BATCH.size:
        raise WireError("truncated_body",
                        f"BATCH payload is {len(payload)} bytes, "
                        f"header alone is {_BATCH.size}")
    flags, epoch, block, nrows, padd, label_width, ndim = \
        _BATCH.unpack_from(payload, 0)
    if not 1 <= ndim <= _MAX_NDIM:
        raise WireError("bad_kind", f"BATCH ndim {ndim} outside "
                                    f"1..{_MAX_NDIM}")
    dims_end = _BATCH.size + 4 * ndim
    if len(payload) < dims_end:
        raise WireError("truncated_body", "BATCH ends inside its shape")
    dims = struct.unpack_from(f"<{ndim}I", payload, _BATCH.size)
    if dims[0] != nrows:
        raise WireError("oversize_shape",
                        f"BATCH dim0 {dims[0]} != nrows {nrows}")
    data_bytes = 4
    for d in dims:
        if d < 1:
            raise WireError("oversize_shape",
                            f"non-positive dim {d} in shape {dims}")
        data_bytes *= d
        if data_bytes > MAX_FRAME_BYTES:
            raise WireError("oversize_shape",
                            f"shape {dims} implies > {MAX_FRAME_BYTES} "
                            "payload bytes")
    label_bytes = 4 * nrows * label_width
    inst_bytes = 4 * nrows if flags & FLAG_HAS_INST else 0
    body_end = dims_end + data_bytes + label_bytes + inst_bytes
    if len(payload) < body_end:
        raise WireError("truncated_body",
                        f"BATCH payload needs {body_end - dims_end} "
                        f"bytes, frame has {len(payload) - dims_end}")
    if len(payload) > body_end:
        raise WireError("trailing_bytes",
                        f"{len(payload) - body_end} bytes past the "
                        "BATCH payload")
    data = np.frombuffer(payload, _F32, count=data_bytes // 4,
                         offset=dims_end).reshape(dims)
    label = np.frombuffer(payload, _F32, count=nrows * label_width,
                          offset=dims_end + data_bytes)
    label = label.reshape(nrows, label_width)
    inst = None
    if flags & FLAG_HAS_INST:
        inst = np.frombuffer(payload, _U32, count=nrows,
                             offset=dims_end + data_bytes + label_bytes)
    return (epoch, block, bool(flags & FLAG_CACHE_HIT),
            data, label, inst, padd)
