"""Bounded decoded-chunk LRU for the data service.

One server feeding N tenants on the same dataset decodes each block
once; the other N-1 reads should be memory reads.  Keys are
``(dataset_fingerprint, epoch, global_block)`` — the exact coordinates
the deterministic stream is addressed by — so a hit is *bitwise* the
batch a miss would have produced, and a fingerprint change (the data
files moved under the server) can never serve stale bytes.

Entries are defensive copies: iterator chains reuse staging buffers
between ``next()`` calls, so caching the live views would let block
k+1's decode scribble over block k's cached rows.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np


class CachedBlock:
    """One decoded block: immutable arrays + padding count."""

    __slots__ = ("data", "label", "inst_index", "num_batch_padd", "nbytes")

    def __init__(self, data: np.ndarray, label: np.ndarray,
                 inst_index: Optional[np.ndarray],
                 num_batch_padd: int) -> None:
        self.data = np.array(data, dtype=np.float32, copy=True)
        self.label = np.array(label, dtype=np.float32, copy=True)
        self.inst_index = (None if inst_index is None
                           else np.array(inst_index, dtype=np.uint32,
                                         copy=True))
        self.num_batch_padd = int(num_batch_padd)
        self.nbytes = (self.data.nbytes + self.label.nbytes
                       + (0 if self.inst_index is None
                          else self.inst_index.nbytes))
        for a in (self.data, self.label, self.inst_index):
            if a is not None:
                a.setflags(write=False)


class ChunkCache:
    """Thread-safe byte-bounded LRU.  ``max_bytes = 0`` disables the
    cache entirely (every get misses, puts are dropped) — the server
    still works, it just decodes per request."""

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._od: "OrderedDict[Tuple, CachedBlock]" = OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: Tuple, record: bool = True) -> Optional[CachedBlock]:
        """Lookup; ``record=False`` leaves the hit/miss counters to the
        caller (the server probes twice per miss — lock-free, then
        under the plant lock — but must account each deal exactly
        once so the lane-asserted hit rate stays truthful)."""
        with self._lock:
            blk = self._od.get(key)
            if blk is None:
                if record:
                    self.misses += 1
                return None
            self._od.move_to_end(key)
            if record:
                self.hits += 1
            return blk

    def note_hit(self) -> None:
        with self._lock:
            self.hits += 1

    def note_miss(self) -> None:
        with self._lock:
            self.misses += 1

    def put(self, key: Tuple, blk: CachedBlock) -> None:
        if self.max_bytes <= 0 or blk.nbytes > self.max_bytes:
            return
        with self._lock:
            old = self._od.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._od[key] = blk
            self.bytes += blk.nbytes
            while self.bytes > self.max_bytes and self._od:
                _, victim = self._od.popitem(last=False)
                self.bytes -= victim.nbytes
                self.evictions += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._od),
                "bytes": self.bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": (self.hits / total) if total else 0.0,
            }
