"""Individual-image-file iterator (``iter = img``).

Parity: ``/root/reference/src/io/iter_img-inl.hpp`` — reads a ``.lst``
file (``index \\t labels \\t filename``) and loads each image from
``image_root + filename`` (PIL instead of OpenCV; RGB HWC float 0..255).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .batch import DataInst, InstIterator
from .imgbin import parse_lst_line


class ImageIterator(InstIterator):
    def supports_dist_shard(self) -> bool:
        return True

    def __init__(self) -> None:
        self.image_list = ""
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self.image_root = ""
        self.silent = 0
        self._recs: List[Tuple[int, np.ndarray, str]] = []
        self._pos = 0
        self._out: Optional[DataInst] = None

    def set_param(self, name, val):
        if name == "image_list":
            self.image_list = val
        elif name == "image_root":
            self.image_root = val
        elif name == "silent":
            self.silent = int(val)
        elif name == "dist_num_worker":
            self.dist_num_worker = int(val)
        elif name == "dist_worker_rank":
            self.dist_worker_rank = int(val)

    def init(self):
        if not self.image_list:
            raise ValueError("ImageIterator: must set image_list")
        with open(self.image_list, "r", encoding="utf-8") as f:
            self._recs = [parse_lst_line(l) for l in f if l.strip()]
        if self.dist_num_worker > 1:
            from .data import shard_rows

            keep = shard_rows(
                len(self._recs), self.dist_worker_rank, self.dist_num_worker
            )
            self._recs = [self._recs[i] for i in keep]
        if not self.silent:
            print(f"ImageIterator: {len(self._recs)} images from {self.image_list}")

    def before_first(self):
        self._pos = 0

    def next(self) -> bool:
        if self._pos >= len(self._recs):
            return False
        from PIL import Image

        idx, labels, fname = self._recs[self._pos]
        self._pos += 1
        img = Image.open(self.image_root + fname)
        if img.mode != "RGB":
            img = img.convert("RGB")
        self._out = DataInst(idx, np.asarray(img, np.float32), labels)
        return True

    def value(self) -> DataInst:
        assert self._out is not None
        return self._out
