"""CSV instance iterator.

Parity: ``/root/reference/src/io/iter_csv-inl.hpp`` — each row is
``label_width`` labels followed by ``prod(input_shape)`` dense features,
comma-separated; ``has_header`` skips the first line, ``#`` starts a
comment (``np.loadtxt`` conventions).

Resilience (doc/robustness.md): the file read retries transient
``OSError`` under the unified :class:`~cxxnet_tpu.utils.faults.
RetryPolicy` (all ``retry_*`` keys); with ``max_bad_records > 0`` rows
that fail to parse (bad floats, wrong column count) are skipped and
quarantined — exceeding the budget aborts with a summary.  The default
``max_bad_records = 0`` keeps the strict legacy behavior AND the
``np.loadtxt`` C fast path: the first bad row aborts, exactly as
before.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..utils import faults
from ..utils.faults import BadRecordBudget, RetryPolicy
from .batch import DataInst, InstIterator


class CSVIterator(InstIterator):
    def supports_dist_shard(self) -> bool:
        return True

    def __init__(self) -> None:
        self.filename = ""
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self.label_width = 1
        self.has_header = 0
        self.silent = 0
        self.input_shape = (1, 1, 0)
        self.max_bad_records = 0
        self.quarantine_dir = ""
        self._retry_cfg: List[Tuple[str, str]] = []
        self._budget: BadRecordBudget | None = None
        self._rows: np.ndarray | None = None
        self._pos = 0

    def set_param(self, name, val):
        if name == "filename":
            self.filename = val
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "has_header":
            self.has_header = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "input_shape":
            c, h, w = (int(t) for t in val.split(","))
            self.input_shape = (c, h, w)
        elif name == "dist_num_worker":
            self.dist_num_worker = int(val)
        elif name == "dist_worker_rank":
            self.dist_worker_rank = int(val)
        elif name == "max_bad_records":
            self.max_bad_records = int(val)
        elif name == "quarantine_dir":
            self.quarantine_dir = val
        elif name in RetryPolicy.CONFIG_KEYS:
            self._retry_cfg.append((name, val))

    def _retry(self) -> RetryPolicy:
        return RetryPolicy.from_cfg(self._retry_cfg)

    def _load_strict(self, want: int) -> np.ndarray:
        """The pre-budget reader, verbatim semantics: ``np.loadtxt``'s C
        tokenizer, first bad row aborts (used when no budget is set and
        no fault is armed — the overwhelmingly common configuration)."""
        def _read():
            faults.fault_point("csv.read")
            return np.loadtxt(
                self.filename,
                delimiter=",",
                skiprows=1 if self.has_header else 0,
                dtype=np.float32,
                ndmin=2,
            )

        rows = self._retry().run(_read, what=f"reading {self.filename}",
                                 silent=bool(self.silent))
        if rows.shape[1] != want:
            raise ValueError(
                f"CSVIterator: row has {rows.shape[1]} columns, expected "
                f"{want} (label_width + input size)"
            )
        return rows

    def _load_tolerant(self, want: int) -> np.ndarray:
        """Per-row parse with skip-and-quarantine under the budget."""
        lines = faults.retried_read_lines(
            self.filename, "csv.read", self._retry_cfg,
            silent=bool(self.silent))
        parsed: List[np.ndarray] = []
        for lineno, line in enumerate(lines, start=1):
            if self.has_header and lineno == 1:
                continue
            # np.loadtxt parity: '#' starts a comment; comment-only and
            # blank lines are not records
            line = line.split("#", 1)[0]
            if not line.strip():
                continue
            line = faults.fault_point("csv.row", line)
            try:
                row = np.asarray(
                    [float(t) for t in line.strip().split(",")], np.float32
                )
                if row.shape[0] != want:
                    raise ValueError(
                        f"row has {row.shape[0]} columns, expected {want} "
                        f"(label_width + input size)"
                    )
            except ValueError as e:
                self._budget.record(self.filename, f"line{lineno}", e)
                continue
            parsed.append(row)
        if not parsed:
            raise ValueError(f"CSVIterator: {self.filename} has no usable rows")
        return np.stack(parsed)

    def init(self):
        nfeat = self.input_shape[0] * self.input_shape[1] * self.input_shape[2]
        if nfeat <= 0:
            raise ValueError("CSVIterator: input_shape must be set")
        want = self.label_width + nfeat
        self._budget = BadRecordBudget(
            self.max_bad_records, what="csv", silent=bool(self.silent),
            quarantine_dir=self.quarantine_dir or None,
        )
        # the loadtxt fast path is bypassed only when per-ROW semantics
        # are needed: a skip budget, or a corrupt fault on csv.row.
        # csv.read faults (I/O error, latency) deliberately hit the
        # strict path too — the chaos harness must exercise the
        # production default reader, not just the tolerant one.
        if (self.max_bad_records == 0
                and not faults.injector().armed("csv.row")):
            rows = self._load_strict(want)
        else:
            rows = self._load_tolerant(want)
        if self.dist_num_worker > 1:
            from .data import shard_rows

            rows = rows[shard_rows(
                len(rows), self.dist_worker_rank, self.dist_num_worker
            )]
        self._rows = rows
        if not self.silent:
            print(f"CSVIterator: filename={self.filename}, {len(rows)} rows")
            if self._budget.epoch_count:
                print(self._budget.summary(), flush=True)

    def before_first(self):
        self._pos = 0

    def next(self) -> bool:
        assert self._rows is not None, "init() not called"
        if self._pos < len(self._rows):
            self._pos += 1
            return True
        return False

    def value(self) -> DataInst:
        row = self._rows[self._pos - 1]
        c, h, w = self.input_shape
        feats = row[self.label_width:]
        data = feats.reshape(-1) if (c == 1 and h == 1) else feats.reshape(c, h, w).transpose(1, 2, 0)
        return DataInst(
            index=self._pos - 1, data=data, label=row[: self.label_width]
        )
