"""CSV instance iterator.

Parity: ``/root/reference/src/io/iter_csv-inl.hpp`` — each row is
``label_width`` labels followed by ``prod(input_shape)`` dense features,
comma-separated; ``has_header`` skips the first line.
"""

from __future__ import annotations

import numpy as np

from .batch import DataInst, InstIterator


class CSVIterator(InstIterator):
    def supports_dist_shard(self) -> bool:
        return True

    def __init__(self) -> None:
        self.filename = ""
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self.label_width = 1
        self.has_header = 0
        self.silent = 0
        self.input_shape = (1, 1, 0)
        self._rows: np.ndarray | None = None
        self._pos = 0

    def set_param(self, name, val):
        if name == "filename":
            self.filename = val
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "has_header":
            self.has_header = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "input_shape":
            c, h, w = (int(t) for t in val.split(","))
            self.input_shape = (c, h, w)
        elif name == "dist_num_worker":
            self.dist_num_worker = int(val)
        elif name == "dist_worker_rank":
            self.dist_worker_rank = int(val)

    def init(self):
        nfeat = self.input_shape[0] * self.input_shape[1] * self.input_shape[2]
        if nfeat <= 0:
            raise ValueError("CSVIterator: input_shape must be set")
        rows = np.loadtxt(
            self.filename,
            delimiter=",",
            skiprows=1 if self.has_header else 0,
            dtype=np.float32,
            ndmin=2,
        )
        want = self.label_width + nfeat
        if rows.shape[1] != want:
            raise ValueError(
                f"CSVIterator: row has {rows.shape[1]} columns, expected "
                f"{want} (label_width + input size)"
            )
        if self.dist_num_worker > 1:
            from .data import shard_rows

            rows = rows[shard_rows(
                len(rows), self.dist_worker_rank, self.dist_num_worker
            )]
        self._rows = rows
        if not self.silent:
            print(f"CSVIterator: filename={self.filename}, {len(rows)} rows")

    def before_first(self):
        self._pos = 0

    def next(self) -> bool:
        assert self._rows is not None, "init() not called"
        if self._pos < len(self._rows):
            self._pos += 1
            return True
        return False

    def value(self) -> DataInst:
        row = self._rows[self._pos - 1]
        c, h, w = self.input_shape
        feats = row[self.label_width:]
        data = feats.reshape(-1) if (c == 1 and h == 1) else feats.reshape(c, h, w).transpose(1, 2, 0)
        return DataInst(
            index=self._pos - 1, data=data, label=row[: self.label_width]
        )
