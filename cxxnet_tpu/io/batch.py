"""Instance→batch collation with round-batch semantics.

Parity: ``BatchAdaptIterator`` (``/root/reference/src/io/
iter_batch_proc-inl.hpp:16-128``):

* collates ``DataInst`` from the wrapped instance iterator into fixed
  ``batch_size`` batches (static shapes — XLA requirement on TPU);
* ``round_batch=1``: the short final batch wraps around to the dataset
  head; ``num_batch_padd`` = number of wrapped instances; the *next*
  epoch then continues from the wrap point instead of rewinding (the
  reference's ``num_overflow_`` dance), so over epochs every instance is
  seen equally often;
* ``round_batch=0``: the short batch is emitted padded with whatever was
  in the buffer, ``num_batch_padd`` = missing count;
* ``test_skipread=1``: after the first batch, ``next()`` keeps returning
  the same batch without touching the base iterator (decode-free IO
  throughput measurement, SURVEY §4.2).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

from ..utils.profiler import pipeline_stats
from .data import DataBatch, DataIter


@dataclasses.dataclass
class DataInst:
    """One instance (parity: ``DataInst``, data.h:42-56)."""

    index: int
    data: np.ndarray     # HWC image or flat vector
    label: np.ndarray    # (label_width,)


class InstIterator:
    """Instance-level iterator protocol (``IIterator<DataInst>``)."""

    def supports_dist_shard(self) -> bool:
        return False

    def set_param(self, name: str, val: str) -> None:
        pass

    def init(self) -> None:
        pass

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> bool:
        raise NotImplementedError

    def value(self) -> DataInst:
        raise NotImplementedError

    def close(self) -> None:
        """Release resources; wrappers delegate down the chain."""


class BatchAdaptIterator(DataIter):
    def __init__(self, base: InstIterator) -> None:
        self.base = base
        self.batch_size = 0
        self.label_width = 1
        self.round_batch = 0
        self.test_skipread = 0
        self.silent = 0
        self._shape: Optional[tuple] = None  # (C,H,W) net convention
        self._t_build = 0.0
        self._num_overflow = 0
        self._head = 1
        self._out: Optional[DataBatch] = None

    def supports_dist_shard(self) -> bool:
        return self.base.supports_dist_shard()

    def set_param(self, name, val):
        self.base.set_param(name, val)
        if name == "batch_size":
            self.batch_size = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "round_batch":
            self.round_batch = int(val)
        elif name == "test_skipread":
            self.test_skipread = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "input_shape":
            c, h, w = (int(t) for t in val.split(","))
            self._shape = (c, h, w)

    def init(self):
        if self.batch_size <= 0:
            raise ValueError("BatchAdaptIterator: batch_size must be set")
        if self._shape is None:
            raise ValueError("BatchAdaptIterator: input_shape must be set")
        self.base.init()
        c, h, w = self._shape
        dshape = (
            (self.batch_size, w) if (c == 1 and h == 1)
            else (self.batch_size, h, w, c)
        )
        self._data = np.zeros(dshape, np.float32)
        self._label = np.zeros((self.batch_size, self.label_width), np.float32)
        self._inst = np.zeros(self.batch_size, np.uint32)

    def before_first(self):
        if self.round_batch == 0 or self._num_overflow == 0:
            self.base.before_first()
        else:
            self._num_overflow = 0
        self._head = 1

    def _store(self, top: int, d: DataInst) -> None:
        x = d.data
        if self._data.ndim == 2:
            x = x.reshape(-1)
        self._data[top] = x
        self._label[top] = np.asarray(d.label, np.float32).reshape(-1)[: self.label_width]
        self._inst[top] = d.index

    def next(self) -> bool:
        if self.test_skipread and self._head == 0:
            return True
        self._head = 0
        if self._num_overflow:
            return False
        # batch-build stage accounting: the time spent collating /
        # copying instances into the batch buffers, EXCLUDING the base
        # pulls (those bill to the decode/augment stages)
        self._t_build = 0.0
        padd = 0
        top = 0
        while self.base.next():
            t0 = time.perf_counter()
            self._store(top, self.base.value())
            self._t_build += time.perf_counter() - t0
            top += 1
            if top >= self.batch_size:
                self._emit(0)
                return True
        if top != 0:
            if self.round_batch:
                self._num_overflow = 0
                self.base.before_first()
                while top < self.batch_size:
                    if not self.base.next():
                        raise ValueError("number of instances must exceed batch size")
                    t0 = time.perf_counter()
                    self._store(top, self.base.value())
                    self._t_build += time.perf_counter() - t0
                    top += 1
                    self._num_overflow += 1
                padd = self._num_overflow
            else:
                padd = self.batch_size - top
            self._emit(padd)
            return True
        return False

    def _emit(self, padd: int) -> None:
        t0 = time.perf_counter()
        self._out = DataBatch(
            data=self._data.copy(),
            label=self._label.copy(),
            inst_index=self._inst.copy(),
            num_batch_padd=padd,
        )
        pipeline_stats().add(
            "batch",
            self._t_build + (time.perf_counter() - t0),
            rows=self.batch_size,
        )

    def value(self) -> DataBatch:
        assert self._head == 0 and self._out is not None, "call next() first"
        return self._out

    def close(self) -> None:
        self.base.close()
