"""Byte-level text iterator for language modeling.

New TPU-first scope — the reference has no sequence data path (SURVEY
§5).  Follows the framework's iterator conventions (``set_param``
config, batch-major ``DataBatch``, equal-truncated distributed
sharding).

``iter = text`` config keys:

* ``filename`` — UTF-8/binary text file; tokens are raw bytes
  (vocab 256, no tokenizer dependency)
* ``seq_len`` — window length T; each instance is ``T`` input ids with
  the next byte at every position as the label (``label_width = T``)
* ``batch_size``
* ``stride`` — window start spacing (default ``seq_len``:
  non-overlapping; smaller values augment)
* ``shuffle`` / ``seed_data`` — one-shot window shuffle
* ``dist_num_worker`` / ``dist_worker_rank`` — equal-truncated window
  sharding (see ``data.shard_rows``)

Emits ``data (N, T)`` float32 ids and ``label (N, T)`` float32 next-ids
— the ``embedding`` layer consumes the ids, the per-position ``softmax``
loss consumes the labels.
"""

from __future__ import annotations

import numpy as np

from ..utils import faults
from ..utils.faults import RetryPolicy
from .data import DataBatch, DataIter


class TextIterator(DataIter):
    def supports_dist_shard(self) -> bool:
        return True

    def __init__(self) -> None:
        self.filename = ""
        self.seq_len = 0
        self.batch_size = 0
        self.stride = 0
        self.shuffle = 0
        self.seed = 0
        self.silent = 0
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self.round_batch = 1
        self._retry_cfg: list = []
        self._raw: np.ndarray | None = None
        self._starts: np.ndarray | None = None
        self._loc = 0
        self._padd = 0

    def set_param(self, name, val):
        if name == "filename":
            self.filename = val
        elif name == "seq_len":
            self.seq_len = int(val)
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "stride":
            self.stride = int(val)
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "seed_data":
            self.seed = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "dist_num_worker":
            self.dist_num_worker = int(val)
        elif name == "dist_worker_rank":
            self.dist_worker_rank = int(val)
        elif name == "round_batch":
            self.round_batch = int(val)
        elif name in RetryPolicy.CONFIG_KEYS:
            self._retry_cfg.append((name, val))

    def init(self):
        if self.seq_len <= 0 or self.batch_size <= 0:
            raise ValueError("text: set seq_len and batch_size")

        def _read():
            faults.fault_point("text.read")
            with open(self.filename, "rb") as f:
                return np.frombuffer(f.read(), np.uint8)

        raw = RetryPolicy.from_cfg(self._retry_cfg).run(
            _read, what=f"reading {self.filename}",
            silent=bool(self.silent))
        t = self.seq_len
        stride = self.stride or t
        starts = np.arange(0, len(raw) - t, stride, dtype=np.int64)
        if len(starts) == 0:
            raise ValueError(
                f"text: {self.filename} has {len(raw)} bytes, need more "
                f"than seq_len={t}"
            )
        if self.shuffle:
            rng = np.random.RandomState(42 + self.seed)
            starts = starts[rng.permutation(len(starts))]
        if self.dist_num_worker > 1:
            from .data import shard_rows

            starts = starts[
                shard_rows(
                    len(starts), self.dist_worker_rank, self.dist_num_worker
                )
            ]
        # windows materialize per batch in value() — an up-front
        # (num_windows, T+1) array costs 4*(seq_len/stride) times the
        # corpus in RAM (stride < seq_len is the documented augmentation
        # mode), only the byte buffer + start offsets are kept
        self._raw = raw
        self._starts = starts
        if not self.silent:
            print(
                f"TextIterator: {self.filename}: {len(raw)} bytes -> "
                f"{len(starts)} windows of T={t}"
            )

    def before_first(self):
        self._loc = 0
        self._padd = 0

    def next(self) -> bool:
        assert self._raw is not None, "init() not called"
        n = len(self._starts)
        if self._loc + self.batch_size <= n:
            self._loc += self.batch_size
            self._padd = 0
            return True
        if self.round_batch and self._loc < n:
            # final partial batch: wrap to fill, flag the padding so
            # eval trims and the train path masks it
            # (iter_batch_proc-inl.hpp:84-99 round_batch semantics)
            self._padd = self._loc + self.batch_size - n
            self._loc = n
            return True
        return False

    def value(self) -> DataBatch:
        lo, hi = self._loc - self.batch_size + self._padd, self._loc
        t = self.seq_len
        take = self._starts[lo:hi]
        if self._padd:
            take = np.concatenate([take, self._starts[: self._padd]])
        idx = take[:, None] + np.arange(t + 1)[None, :]
        win = self._raw[idx].astype(np.float32)
        # inst_index mirrors `take`: wrapped pad rows reuse the leading
        # window ids, so prediction bookkeeping stays attributable
        inst = np.arange(lo, hi, dtype=np.uint32)
        if self._padd:
            inst = np.concatenate(
                [inst, np.arange(self._padd, dtype=np.uint32)]
            )
        return DataBatch(
            data=win[:, :-1],
            label=win[:, 1:],
            inst_index=inst,
            num_batch_padd=self._padd,
        )
