"""Data pipeline core: DataBatch, iterator protocol, chain factory.

Parity: ``/root/reference/src/io/data.h`` (``DataInst``/``DataBatch`` with
``num_batch_padd`` for short final batches, ``extra_data`` side inputs) and
``/root/reference/src/io/data.cpp:24-82`` (the ordered ``iter = X`` chain
factory: base iterators at the bottom, ``threadbuffer``/``membuffer``/
``attachtxt`` wrap the iterator below them; params following an ``iter=``
line configure the current top of the chain, which forwards them down).

Layout note: batches are NHWC (or flat ``(N, D)``) numpy float32 — the
TPU-native transposition of the reference's NCHW batches.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

ConfigEntry = Tuple[str, str]


@dataclasses.dataclass
class DataBatch:
    """One mini-batch. ``num_batch_padd`` trailing instances are padding
    (replicated data to keep shapes static) and must be excluded from
    evaluation/prediction output (data.h:86-88).

    The sparse part mirrors the reference's CSR fields
    (``data.h:97-101``: ``sparse_row_ptr`` / ``sparse_data``) with the
    Entry struct-array split into parallel index/value arrays — the
    layout ``scipy.sparse.csr_matrix`` and XLA gather/segment ops
    consume directly, instead of an array-of-structs a TPU can't use."""

    data: np.ndarray                  # (N, H, W, C) or (N, D)
    label: np.ndarray                 # (N, label_width) float32
    inst_index: Optional[np.ndarray] = None
    num_batch_padd: int = 0
    extra_data: List[np.ndarray] = dataclasses.field(default_factory=list)
    #: CSR row pointer, shape (N+1,), int64 — None for dense batches
    sparse_row_ptr: Optional[np.ndarray] = None
    #: CSR column indices (Entry.findex), shape (nnz,), int32
    sparse_index: Optional[np.ndarray] = None
    #: CSR values (Entry.fvalue), shape (nnz,), float32
    sparse_value: Optional[np.ndarray] = None

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    def is_sparse(self) -> bool:
        """Parity: ``DataBatch::is_sparse`` (data.h:166-168)."""
        return self.sparse_row_ptr is not None

    def get_row_sparse(self, rid: int):
        """Row ``rid`` as (indices, values) — parity
        ``DataBatch::GetRowSparse`` (data.h:170-175)."""
        if not self.is_sparse():
            raise ValueError("GetRowSparse on a dense batch")
        lo, hi = self.sparse_row_ptr[rid], self.sparse_row_ptr[rid + 1]
        return self.sparse_index[lo:hi], self.sparse_value[lo:hi]


def shard_rows(n_rows: int, rank: int, nworker: int, block: int = 1):
    """Equal-length row shard for distributed data parallelism.

    ``block = 1`` (default): worker ``rank`` takes rows ``rank::nworker``
    truncated to ``n_rows // nworker`` — disjoint AND class-mixed even
    on unshuffled data.  Shards are always the same length: unequal
    shards (plain ``k::n`` slicing) deadlock the SPMD train loop — the
    process with one extra batch issues a collective the others never
    join.

    ``block > 1`` (``dist_shard = block`` with the LOCAL batch size):
    rows are dealt out in contiguous blocks of ``block`` round-robin,
    so worker ``rank``'s k-th local batch is exactly rows
    ``[k*B*nworker + rank*B, ... + B)`` of the global stream — the
    global SPMD batch assembled across workers is the IDENTICAL rows in
    the IDENTICAL order a single-process run of the same mesh feeds.
    That alignment is what makes the multi-process trainer bitwise equal
    to the single-process one (the MESH=1 parity lane): interleaved
    shards permute rows across data-axis shards, which reorders the
    gradient reduction and drifts ~1 ulp/step.  Returns an index array.
    """
    import numpy as _np

    if block <= 1:
        per = n_rows // nworker
        if per == 0:
            raise ValueError(
                f"cannot shard {n_rows} rows over {nworker} workers"
            )
        return _np.arange(rank, n_rows, nworker)[:per]
    nblocks = n_rows // (block * nworker)
    if nblocks == 0:
        raise ValueError(
            f"cannot shard {n_rows} rows over {nworker} workers in "
            f"blocks of {block}"
        )
    starts = (_np.arange(nblocks) * nworker + rank) * block
    return (starts[:, None] + _np.arange(block)[None, :]).reshape(-1)


class DataIter:
    """Iterator protocol (parity: ``IIterator``, data.h:19-39)."""

    #: True for source iterators that honor ``dist_num_worker`` /
    #: ``dist_worker_rank`` (wrappers delegate).  The CLI refuses to
    #: run multi-process with a train iterator that would silently feed
    #: every process identical data.
    def supports_dist_shard(self) -> bool:
        return False

    def set_param(self, name: str, val: str) -> None:  # noqa: D401
        pass

    def init(self) -> None:
        pass

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> bool:
        raise NotImplementedError

    def value(self) -> DataBatch:
        raise NotImplementedError

    def close(self) -> None:
        """Release resources (threads, native readers).  Idempotent;
        wrappers delegate down the chain.  Base iterators holding no
        resources inherit this no-op."""

    # python sugar
    def __iter__(self):
        self.before_first()
        while self.next():
            yield self.value()


def create_iterator(cfg: Sequence[ConfigEntry]) -> DataIter:
    """Build an iterator chain from an ordered config section."""
    # imports here to avoid cycles
    from .augment import AugmentIterator
    from .batch import BatchAdaptIterator
    from .csv import CSVIterator
    from .img import ImageIterator
    from .imgbin import ImageBinIterator
    from .membuffer import MemBufferIterator
    from .mnist import MNISTIterator
    from .pipeline import ParallelAugmentIterator
    from .prefetch import ThreadBufferIterator
    from .synth import SyntheticIterator
    from .attach_txt import AttachTxtIterator
    from .libsvm import LibSVMIterator
    from .text import TextIterator

    it: Optional[DataIter] = None
    for name, val in cfg:
        if name == "iter":
            if val == "mnist":
                if it is not None:
                    raise ValueError("mnist cannot chain over another iterator")
                it = MNISTIterator()
            elif val in ("imgbin", "imgbinx"):
                if it is not None:
                    raise ValueError("imgbin cannot chain over another iterator")
                # the decode+augment stage parallelizes when the section
                # sets num_decode_workers > 1 (io/pipeline.py); it is a
                # transparent pass-through otherwise
                it = BatchAdaptIterator(ParallelAugmentIterator(
                    AugmentIterator(ImageBinIterator())))
            elif val == "img":
                if it is not None:
                    raise ValueError("img cannot chain over another iterator")
                it = BatchAdaptIterator(ParallelAugmentIterator(
                    AugmentIterator(ImageIterator())))
            elif val == "csv":
                if it is not None:
                    raise ValueError("csv cannot chain over another iterator")
                it = BatchAdaptIterator(CSVIterator())
            elif val == "synthetic":
                if it is not None:
                    raise ValueError("synthetic cannot chain over another iterator")
                it = SyntheticIterator()
            elif val == "text":
                if it is not None:
                    raise ValueError("text cannot chain over another iterator")
                it = TextIterator()
            elif val == "libsvm":
                if it is not None:
                    raise ValueError("libsvm cannot chain over another iterator")
                it = LibSVMIterator()
            elif val == "service":
                if it is not None:
                    raise ValueError("service cannot chain over another iterator")
                # network base iterator: streams blocks from a shared
                # task=data_service decode fleet (io/dataservice/)
                from .dataservice.client import ServiceIterator

                it = ServiceIterator()
            elif val == "threadbuffer":
                if it is None:
                    raise ValueError("must specify input of threadbuffer")
                it = ThreadBufferIterator(it)
            elif val == "membuffer":
                if it is None:
                    raise ValueError("must specify input of membuffer")
                it = MemBufferIterator(it)
            elif val == "attachtxt":
                if it is None:
                    raise ValueError("must specify input of attachtxt")
                it = AttachTxtIterator(it)
            elif val == "end":
                continue
            else:
                raise ValueError(f"unknown iterator type {val!r}")
            continue
        if it is not None:
            it.set_param(name, val)
    if it is None:
        raise ValueError("must specify iterator by iter=itername")
    return it
