"""Synthetic data iterator (framework extension, not in the reference).

Generates a deterministic random dataset in RAM — the benchmark/test
stand-in for datasets that are not shipped (the reference assumes you
downloaded MNIST/ImageNet).  The labels are drawn from a fixed linear
teacher over the inputs so that models can actually *learn* from it in
overfit tests.

Config keys::

    nsample      number of instances (default 512)
    input_shape  C,H,W (same convention as the net config)
    nclass       number of classes (default 10)
    label_width  label columns (default 1; class id in column 0)
    batch_size   required
    seed_data    RNG seed
"""

from __future__ import annotations

import numpy as np

from .data import DataBatch, DataIter


class SyntheticIterator(DataIter):
    def supports_dist_shard(self) -> bool:
        return True

    def __init__(self) -> None:
        self.nsample = 512
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self.input_shape = (1, 1, 16)
        self.nclass = 10
        self.label_width = 1
        self.layout = "auto"  # seq: emit (N, T, D) sequence batches
        self.batch_size = 0
        self.seed = 0
        self._loc = 0
        self._data: np.ndarray | None = None
        self._label: np.ndarray | None = None

    def set_param(self, name, val):
        if name == "nsample":
            self.nsample = int(val)
        elif name == "input_shape":
            z, y, x = (int(t) for t in val.split(","))
            self.input_shape = (z, y, x)
        elif name == "nclass":
            self.nclass = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "seed_data":
            self.seed = int(val)
        elif name == "layout":
            self.layout = val
        elif name == "dist_num_worker":
            self.dist_num_worker = int(val)
        elif name == "dist_worker_rank":
            self.dist_worker_rank = int(val)

    def init(self):
        if self.batch_size <= 0:
            raise ValueError("SyntheticIterator: batch_size must be set")
        rng = np.random.RandomState(1234 + self.seed)
        c, h, w = self.input_shape
        if self.layout == "seq":
            shape = (self.nsample, h, w)
        elif c == 1 and h == 1:
            shape = (self.nsample, w)
        else:
            shape = (self.nsample, h, w, c)
        self._data = rng.randn(*shape).astype(np.float32)
        flat = self._data.reshape(self.nsample, -1)
        teacher = rng.randn(flat.shape[1], self.nclass).astype(np.float32)
        if self.dist_num_worker > 1 and self.dist_worker_rank > 0:
            # each worker draws DISTINCT samples (disjoint rng streams)
            # labelled by the SAME teacher; rank 0 keeps the exact
            # single-process stream so 1-vs-n runs stay comparable
            rng_k = np.random.RandomState(
                1234 + self.seed + 7919 * self.dist_worker_rank
            )
            self._data = rng_k.randn(*shape).astype(np.float32)
            flat = self._data.reshape(self.nsample, -1)
        cls = (flat @ teacher).argmax(-1).astype(np.float32)
        lab = np.zeros((self.nsample, self.label_width), np.float32)
        lab[:, 0] = cls
        self._label = lab

    def before_first(self):
        self._loc = 0

    def next(self) -> bool:
        assert self._data is not None, "init() not called"
        if self._loc + self.batch_size <= self.nsample:
            self._loc += self.batch_size
            return True
        return False

    def value(self) -> DataBatch:
        lo, hi = self._loc - self.batch_size, self._loc
        return DataBatch(
            data=self._data[lo:hi],
            label=self._label[lo:hi],
            inst_index=np.arange(lo, hi, dtype=np.uint32),
        )
