"""In-RAM batch cache (``iter = membuffer``).

Parity: ``/root/reference/src/io/iter_mem_buffer-inl.hpp`` — caches the
first ``max_nbatch`` batches of the wrapped iterator and replays them;
used for small-sample overfit smoke tests (SURVEY §4.5).
"""

from __future__ import annotations

from typing import List

from .data import DataBatch, DataIter


class MemBufferIterator(DataIter):
    def __init__(self, base: DataIter) -> None:
        self.base = base
        self.max_nbatch = 0  # 0 = cache everything
        self.silent = 0
        self._cache: List[DataBatch] = []
        self._filled = False
        self._pos = 0

    def supports_dist_shard(self) -> bool:
        return self.base.supports_dist_shard()

    def set_param(self, name, val):
        self.base.set_param(name, val)
        if name == "max_nbatch":
            self.max_nbatch = int(val)
        elif name == "silent":
            self.silent = int(val)

    def init(self):
        self.base.init()
        self.base.before_first()
        while self.base.next():
            self._cache.append(self.base.value())
            if self.max_nbatch and len(self._cache) >= self.max_nbatch:
                break
        self._filled = True
        if not self.silent:
            print(f"MemBufferIterator: cached {len(self._cache)} batches")

    def before_first(self):
        self._pos = 0

    def next(self) -> bool:
        if self._pos < len(self._cache):
            self._pos += 1
            return True
        return False

    def value(self) -> DataBatch:
        return self._cache[self._pos - 1]

    def close(self) -> None:
        self.base.close()
