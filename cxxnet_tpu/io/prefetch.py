"""Threaded batch prefetch (``iter = threadbuffer``).

Parity: ``ThreadBufferIterator`` (``/root/reference/src/io/
iter_batch_proc-inl.hpp:131-219``) over the generic double-buffer
(``/root/reference/src/utils/thread_buffer.h``): a producer thread pulls
batches from the wrapped iterator into a bounded queue so host-side
decode/augment overlaps with device compute — the classic input-pipeline
overlap that feeds the TPU.

Epoch restarts are handled with a generation counter: ``before_first``
bumps the generation; the producer re-reads it between items and restarts
the wrapped iterator; the consumer discards queue entries from stale
generations.  This replaces the reference's semaphore handshake with an
equivalent that cannot deadlock on mid-epoch rewinds.

Fault tolerance: an exception from the wrapped iterator (decode error,
I/O failure) is captured, enqueued, and re-raised in the CONSUMER's
``next()`` — previously it killed the daemon thread silently and the
consumer blocked forever on an empty queue.  The producer survives the
error and serves the next epoch after a ``before_first`` rewind.

A :class:`~cxxnet_tpu.utils.faults.Watchdog` guards the other hang mode:
a producer stuck INSIDE the wrapped iterator (I/O stall, hung decoder)
never enqueues anything, so the consumer would block forever on ``get``.
The producer heartbeats on every step; when no beat lands for
``watchdog_timeout_s`` (default 600, ``0`` disables) while the consumer
is waiting, ``next()`` raises :class:`WatchdogError` with the hung
thread's stack instead of hanging the train loop.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from ..utils import faults
from ..utils.faults import Watchdog, WatchdogError
from .data import DataBatch, DataIter

_END = object()


class _ProducerError:
    """Queue wrapper for an exception raised inside the producer thread."""

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


class ThreadBufferIterator(DataIter):
    def __init__(self, base: DataIter) -> None:
        self.base = base
        self.buffer_size = 2
        self.silent = 0
        self.watchdog_timeout_s = 600.0  # 0 disables the stall guard
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[Watchdog] = None
        self._cur: Optional[DataBatch] = None
        self._gen = 0                      # consumer's current epoch
        self._gen_lock = threading.Condition()
        self._stop = False
        self._closed = False

    def supports_dist_shard(self) -> bool:
        return self.base.supports_dist_shard()

    def set_param(self, name, val):
        self.base.set_param(name, val)
        if name == "buffer_size":
            self.buffer_size = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "watchdog_timeout_s":
            self.watchdog_timeout_s = float(val)

    def init(self):
        self.base.init()
        self._q = queue.Queue(maxsize=self.buffer_size)
        self._gen = 0
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._watchdog = Watchdog(
            what="prefetch producer",
            timeout_s=self.watchdog_timeout_s,
            thread=self._thread,
        )
        self._thread.start()
        if not self.silent:
            print(f"ThreadBufferIterator: buffer_size={self.buffer_size}")

    # ------------------------------------------------------------------
    def _producer(self):
        # served = 0: production starts at the consumer's FIRST
        # before_first() (generation 1) — the DataIter contract
        # (``data.py::DataIter.__iter__``) guarantees one precedes any
        # next().  Producing generation 0 eagerly would race the first
        # rewind: a wrapped-iterator pass (and any error it raised)
        # could be consumed and discarded as stale before the consumer
        # ever observed it.
        served = 0  # last generation fully produced
        wd = self._watchdog
        while True:
            with self._gen_lock:
                while not self._stop and self._gen <= served:
                    wd.beat()  # idle-waiting for a rewind is progress
                    self._gen_lock.wait(timeout=0.5)
                if self._stop:
                    return
                gen = self._gen
            try:
                self.base.before_first()
                while True:
                    with self._gen_lock:
                        if self._stop:
                            return
                        if self._gen != gen:
                            break  # consumer rewound; restart epoch
                    wd.beat()
                    faults.fault_point("prefetch.producer")
                    if not self.base.next():
                        self._put((gen, _END))
                        break
                    self._put((gen, self.base.value()))
                    wd.beat()
            except Exception as e:  # noqa: BLE001 - relayed to consumer
                # deliver the failure to the consumer instead of dying
                # silently (which left next() blocked forever); the
                # producer stays alive to serve the next epoch.  The
                # trailing _END terminates the epoch for a consumer that
                # swallows the error and calls next() again — otherwise
                # that retry would block on the empty queue
                self._put((gen, _ProducerError(e)))
                self._put((gen, _END))
            served = gen

    def _put(self, item) -> None:
        # bounded put that aborts if the consumer rewound or stopped
        gen = item[0]
        while True:
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                with self._gen_lock:
                    if self._stop or self._gen != gen:
                        return

    # ------------------------------------------------------------------
    def before_first(self):
        assert self._q is not None, "init() not called"
        with self._gen_lock:
            self._gen += 1
            self._gen_lock.notify_all()

    def next(self) -> bool:
        assert self._q is not None, "init() not called"
        wd = self._watchdog
        while True:
            try:
                gen, item = self._q.get(timeout=0.2)
            except queue.Empty:
                t = self._thread
                if t is not None and not t.is_alive() and self._q.empty():
                    raise WatchdogError(
                        "prefetch producer thread died without delivering "
                        "a result; the input pipeline cannot continue"
                    ) from None
                if wd is not None:
                    wd.check()  # raises WatchdogError on a hung producer
                continue
            if gen != self._gen:
                continue  # stale epoch
            if item is _END:
                return False
            if isinstance(item, _ProducerError):
                raise item.exc  # surface the producer's failure here
            self._cur = item
            return True

    def value(self) -> DataBatch:
        assert self._cur is not None
        return self._cur

    def close(self):
        """Stop and JOIN the producer, then close the wrapped iterator.

        The old close() only flagged ``_stop`` and returned — the
        producer thread (possibly blocked in ``put``) leaked, and
        ``base`` never released its resources; tests accumulated daemon
        threads.  Draining the queue unblocks a full-queue ``put`` so
        the producer can observe ``_stop`` and exit; the join is
        bounded because a producer hung inside ``base.next()`` is a
        daemon thread the interpreter may abandon."""
        if self._closed:
            return
        self._closed = True
        with self._gen_lock:
            self._stop = True
            self._gen_lock.notify_all()
        thread, self._thread = self._thread, None
        if self._q is not None:
            while True:  # unblock a producer waiting in _put
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        # duck-typed bases (tests, user code) may predate close()
        close_base = getattr(self.base, "close", None)
        if close_base is not None:
            close_base()

    def __del__(self):  # pragma: no cover - GC-order dependent
        try:
            self.close()
        except Exception:
            pass
