"""Threaded batch prefetch (``iter = threadbuffer``).

Parity: ``ThreadBufferIterator`` (``/root/reference/src/io/
iter_batch_proc-inl.hpp:131-219``) over the generic double-buffer
(``/root/reference/src/utils/thread_buffer.h``): a producer thread pulls
batches from the wrapped iterator into a bounded queue so host-side
decode/augment overlaps with device compute — the classic input-pipeline
overlap that feeds the TPU.

Epoch restarts are handled with a generation counter: ``before_first``
bumps the generation; the producer re-reads it between items and restarts
the wrapped iterator; the consumer discards queue entries from stale
generations.  This replaces the reference's semaphore handshake with an
equivalent that cannot deadlock on mid-epoch rewinds.

Fault tolerance: an exception from the wrapped iterator (decode error,
I/O failure) is captured, enqueued, and re-raised in the CONSUMER's
``next()`` — previously it killed the daemon thread silently and the
consumer blocked forever on an empty queue.  The producer survives the
error and serves the next epoch after a ``before_first`` rewind.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from .data import DataBatch, DataIter

_END = object()


class _ProducerError:
    """Queue wrapper for an exception raised inside the producer thread."""

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


class ThreadBufferIterator(DataIter):
    def __init__(self, base: DataIter) -> None:
        self.base = base
        self.buffer_size = 2
        self.silent = 0
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._cur: Optional[DataBatch] = None
        self._gen = 0                      # consumer's current epoch
        self._gen_lock = threading.Condition()
        self._stop = False

    def supports_dist_shard(self) -> bool:
        return self.base.supports_dist_shard()

    def set_param(self, name, val):
        self.base.set_param(name, val)
        if name == "buffer_size":
            self.buffer_size = int(val)
        elif name == "silent":
            self.silent = int(val)

    def init(self):
        self.base.init()
        self._q = queue.Queue(maxsize=self.buffer_size)
        self._gen = 0
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()
        if not self.silent:
            print(f"ThreadBufferIterator: buffer_size={self.buffer_size}")

    # ------------------------------------------------------------------
    def _producer(self):
        # served = 0: production starts at the consumer's FIRST
        # before_first() (generation 1) — the DataIter contract
        # (``data.py::DataIter.__iter__``) guarantees one precedes any
        # next().  Producing generation 0 eagerly would race the first
        # rewind: a wrapped-iterator pass (and any error it raised)
        # could be consumed and discarded as stale before the consumer
        # ever observed it.
        served = 0  # last generation fully produced
        while True:
            with self._gen_lock:
                while not self._stop and self._gen <= served:
                    self._gen_lock.wait(timeout=0.5)
                if self._stop:
                    return
                gen = self._gen
            try:
                self.base.before_first()
                while True:
                    with self._gen_lock:
                        if self._stop:
                            return
                        if self._gen != gen:
                            break  # consumer rewound; restart epoch
                    if not self.base.next():
                        self._put((gen, _END))
                        break
                    self._put((gen, self.base.value()))
            except Exception as e:  # noqa: BLE001 - relayed to consumer
                # deliver the failure to the consumer instead of dying
                # silently (which left next() blocked forever); the
                # producer stays alive to serve the next epoch.  The
                # trailing _END terminates the epoch for a consumer that
                # swallows the error and calls next() again — otherwise
                # that retry would block on the empty queue
                self._put((gen, _ProducerError(e)))
                self._put((gen, _END))
            served = gen

    def _put(self, item) -> None:
        # bounded put that aborts if the consumer rewound or stopped
        gen = item[0]
        while True:
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                with self._gen_lock:
                    if self._stop or self._gen != gen:
                        return

    # ------------------------------------------------------------------
    def before_first(self):
        assert self._q is not None, "init() not called"
        with self._gen_lock:
            self._gen += 1
            self._gen_lock.notify_all()

    def next(self) -> bool:
        assert self._q is not None, "init() not called"
        while True:
            gen, item = self._q.get()
            if gen != self._gen:
                continue  # stale epoch
            if item is _END:
                return False
            if isinstance(item, _ProducerError):
                raise item.exc  # surface the producer's failure here
            self._cur = item
            return True

    def value(self) -> DataBatch:
        assert self._cur is not None
        return self._cur

    def close(self):
        with self._gen_lock:
            self._stop = True
            self._gen_lock.notify_all()
