"""Side-feature join iterator (``iter = attachtxt``).

Parity: ``/root/reference/src/io/iter_attach_txt-inl.hpp`` — joins
per-instance dense features from a text file into ``batch.extra_data``
by instance id.  File format: each line ``inst_index v1 v2 ... vk``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from .data import DataBatch, DataIter


class AttachTxtIterator(DataIter):
    def __init__(self, base: DataIter) -> None:
        self.base = base
        self.filename = ""
        self.silent = 0
        self._table: Dict[int, np.ndarray] = {}
        self._width = 0
        self._cur: Optional[DataBatch] = None

    def supports_dist_shard(self) -> bool:
        return self.base.supports_dist_shard()

    def set_param(self, name, val):
        self.base.set_param(name, val)
        if name in ("attach_file", "filename"):
            self.filename = val
        elif name == "silent":
            self.silent = int(val)

    def init(self):
        self.base.init()
        if not self.filename:
            raise ValueError("AttachTxtIterator: must set attach_file")
        with open(self.filename, "r", encoding="utf-8") as f:
            for line in f:
                toks = line.split()
                if not toks:
                    continue
                self._table[int(float(toks[0]))] = np.asarray(
                    [float(t) for t in toks[1:]], np.float32
                )
        self._width = len(next(iter(self._table.values()))) if self._table else 0
        if not self.silent:
            print(f"AttachTxtIterator: {len(self._table)} rows, width={self._width}")

    def before_first(self):
        self.base.before_first()

    def next(self) -> bool:
        if not self.base.next():
            return False
        b = self.base.value()
        extra = np.zeros((b.batch_size, self._width), np.float32)
        if b.inst_index is not None:
            for i, idx in enumerate(b.inst_index):
                row = self._table.get(int(idx))
                if row is not None:
                    extra[i] = row
        # replace() keeps every other DataBatch field (incl. the CSR
        # sparse part) flowing through the wrap
        self._cur = dataclasses.replace(
            b, extra_data=b.extra_data + [extra]
        )
        return True

    def value(self) -> DataBatch:
        assert self._cur is not None
        return self._cur

    def close(self) -> None:
        self.base.close()
