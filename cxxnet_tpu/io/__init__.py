"""Composable data pipeline (iterator chains configured by ``iter = X``)."""

from .batch import BatchAdaptIterator, DataInst, InstIterator  # noqa: F401
from .data import DataBatch, DataIter, create_iterator  # noqa: F401
