"""Parallel host data pipeline: ordered multi-worker decode + augment.

The device hot path is one fused, donated, optionally scanned SPMD
program (``nnet/trainer.py``); past ~2000 img/s the bottleneck is the
HOST — a single Python thread doing per-instance JPEG decode + augment
behind the ``iter = threadbuffer`` producer.  This stage parallelizes
exactly that work, the way the TensorFlow paper's parallel input
pipelines do (PAPERS.md, Abadi et al. 2016 §4.2), while keeping the
augmentation stream **bitwise deterministic**:

* ``num_decode_workers = N`` (N > 1) starts N daemon worker threads.
  PIL's JPEG decode and numpy's array ops release the GIL, so a thread
  pool — not processes — already scales across cores with zero IPC.
* Records are fetched from the source ON THE CONSUMER thread in epoch
  order (so fault-injection draws, quarantine accounting, and the
  distributed epoch cap replay exactly like the serial path), grouped
  into chunks, and decoded+augmented by the pool; chunk results are
  consumed strictly in submission order with a bounded in-flight
  window (``decode_queue_depth`` chunks), so memory stays bounded and
  output order never depends on worker scheduling.
* Every record's augmentation draws come from a private RNG seeded by
  ``(seed_data, epoch, record index)`` (``io/augment.py``), so worker
  count, chunking, buffer depth, and mid-epoch rewinds cannot change
  the stream: serial and parallel runs produce bitwise-identical
  batches (``tests/test_host_pipeline.py``).
* For encoded-image sources with no affine warp, the work is SPLIT:
  workers run only GIL-releasing PIL C ops (decode, crop, flip) and
  return small uint8 windows; the float tail (mean / jitter / scale)
  runs once, vectorized, on the consumer
  (``AugmentIterator.augment_pil`` / ``augment_tail``).  Other
  sources take the array path: workers decode and run the vectorized
  whole-batch augment (``augment_insts``).
* A :class:`~cxxnet_tpu.utils.faults.Watchdog` guards the pool: a hung
  worker (I/O stall, poisoned decode) raises ``WatchdogError`` with
  the workers' stacks instead of blocking the train loop forever, and
  the ``pipeline.worker`` fault site makes that path chaos-testable.

With ``num_decode_workers <= 1`` (the default) the stage is a
transparent pass-through to the serial augment chain.

Wiring (``io/data.py``): ``imgbin``/``img`` chains build
``BatchAdapt(ParallelAugment(Augment(source)))``; ``iter =
threadbuffer`` still double-buffers whole batches on top, overlapping
the whole host stage with device compute.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time
import traceback
from typing import List, Optional

from ..utils import faults
from ..utils.faults import Watchdog, WatchdogError
from ..utils.profiler import pipeline_stats
from .augment import AugmentIterator
from .batch import DataInst, InstIterator


class _BadRecord:
    """A worker-side decode failure, relayed to the consumer so the
    skip-and-quarantine budget stays single-threaded and in order."""

    __slots__ = ("source", "offset", "exc")

    def __init__(self, source, offset, exc) -> None:
        self.source = source
        self.offset = offset
        self.exc = exc


class _WorkerError:
    """A non-record failure inside a worker (bug, injected I/O error):
    re-raised in the consumer's ``next()``."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


class ParallelAugmentIterator(InstIterator):
    """Ordered decode+augment pool over an :class:`AugmentIterator`.

    Two source modes, picked at ``init()``:

    * **raw mode** — the augmenter's base exposes the raw-record API
      (``next_raw``/``decode_record``/``record_bad``; the pure-Python
      imgbin reader): workers decode AND augment.
    * **instance mode** — any other base (native reader, ``iter=img``,
      custom iterators): instances are pulled serially (already
      decoded) and workers parallelize the augmentation only.
    """

    def __init__(self, aug: AugmentIterator) -> None:
        self.aug = aug
        self.num_workers = 0        # <= 1: serial pass-through
        self.chunk_size = 24        # records per worker task (measured
        # knee: big enough to amortize consumer wakeups, small enough
        # not to churn the cache with idle in-flight output)
        self.queue_depth = 0        # in-flight chunks; 0 = per-core default
        self.watchdog_timeout_s = 600.0
        self.silent = 0
        self._threads: List[threading.Thread] = []
        self._in_q: Optional[queue.Queue] = None
        self._results = {}
        self._cond = threading.Condition()
        self._stop = False
        self._gen = 0
        self._seq_submit = 0        # next chunk seq to submit
        self._seq_take = 0          # next chunk seq to consume
        self._exhausted = False
        self._pending: List[object] = []
        self._pending_pos = 0
        self._yielded = 0           # successes this epoch (epoch_cap)
        self._raw_source = None     # base when raw mode is active
        self._pil_mode = False      # split decode-worker/float-tail layout
        self._cap = 0               # cached epoch_cap (set per epoch)
        self._watchdog: Optional[Watchdog] = None
        self._out: Optional[DataInst] = None
        self._closed = False
        self._init_done = False
        self._pool_started = False
        self._pool_lock = threading.Lock()  # guards _threads membership
        self._worker_seq = 0                # monotonic worker name ids
        self._poison_pending = 0            # shrink tokens in flight

    # ------------------------------------------------------------------
    def supports_dist_shard(self) -> bool:
        return self.aug.supports_dist_shard()

    def set_param(self, name, val):
        self.aug.set_param(name, val)
        if name == "num_decode_workers":
            self.num_workers = int(val)
        elif name == "decode_chunk":
            self.chunk_size = max(1, int(val))
        elif name == "decode_queue_depth":
            self.queue_depth = int(val)
        elif name == "watchdog_timeout_s":
            self.watchdog_timeout_s = float(val)
        elif name == "silent":
            self.silent = int(val)

    @property
    def parallel(self) -> bool:
        return self._pool_started or self.num_workers > 1

    def init(self):
        self.aug.init()
        self._init_done = True
        if self.num_workers > 1:
            self._start_pool()

    # ------------------------------------------------------------------
    # runtime resize (the self-tuning controller's live knobs;
    # doc/performance.md "Self-tuning runtime")
    def request_workers(self, n: int) -> int:
        """Set the decode-pool worker target at runtime (thread-safe).

        An active pool resizes immediately — new threads are spawned,
        surplus ones drain out via poison tokens; record order and the
        augmentation stream are unaffected (ordering is sequence-number
        based, RNG draws are per-record).  A chain still on the serial
        path grows its pool at the next :meth:`before_first` (the safe
        point — mid-epoch the consumer owns the source cursor).  Once a
        pool exists it never tears back down to the serial path; a
        target of 1 runs the pool with one worker, which is bitwise
        identical and within noise of the serial path."""
        n = max(1, int(n))
        self.num_workers = n
        if self._closed:
            return n
        if self._pool_started:
            self._reconcile_pool()
        from ..tune.controller import set_effective

        set_effective("num_decode_workers", n)
        return n

    def set_queue_depth(self, n: int) -> int:
        """Resize the in-flight chunk window at runtime (immediate:
        the consumer re-reads it on every refill; shrinking below the
        current in-flight count just pauses submission until consumed)."""
        n = max(1, int(n))
        self.queue_depth = n
        from ..tune.controller import set_effective

        set_effective("decode_queue_depth", n)
        return n

    def effective_workers(self) -> int:
        """Worker threads currently alive (the resize ground truth)."""
        with self._pool_lock:
            return sum(1 for t in self._threads if t.is_alive())

    def _spawn_worker(self) -> None:
        t = threading.Thread(
            target=self._worker, daemon=True,
            name=f"decode-worker-{self._worker_seq}",
        )
        self._worker_seq += 1
        t.start()
        self._threads.append(t)

    def _reconcile_pool(self) -> None:
        """Converge live worker threads toward ``num_workers``: spawn
        the shortfall, poison the surplus (each None token retires one
        worker).  Tokens still in flight count against the surplus —
        without that, back-to-back shrinks would over-poison the pool
        down to zero workers and wedge the consumer — and tokens
        drained by a generation flip are re-credited there, so the
        count converges, never wedges."""
        with self._pool_lock:
            self._threads = [t for t in self._threads if t.is_alive()]
            alive = len(self._threads)
            target = max(1, self.num_workers)
            effective = alive - self._poison_pending
            if effective < target:
                for _ in range(target - effective):
                    self._spawn_worker()
            else:
                for _ in range(effective - target):
                    self._in_q.put(None)
                    self._poison_pending += 1

    def _start_pool(self):
        src = self.aug.base
        if (getattr(src, "next_raw", None) is not None
                and getattr(src, "raw_available", lambda: False)()):
            self._raw_source = src
        # split layout when possible: workers run only GIL-releasing
        # PIL C ops (decode/crop/flip) and return small uint8 windows;
        # the float tail runs vectorized on the consumer.  Keeping the
        # numpy float passes out of the workers is what lets the pool
        # scale — interleaved GIL-held float ops across many workers
        # convoy the whole pool on small hosts.
        self._pil_mode = (
            self._raw_source is not None
            and getattr(src, "pil_available", lambda: False)()
            and self.aug.pil_path_ok()
        )
        if self.queue_depth <= 0:
            # in-flight chunks should cover the cores that can actually
            # run workers (plus pipeline slack), not the worker count —
            # a window much larger than the hardware just churns the
            # allocator and cache with chunks nobody is consuming yet
            self.queue_depth = max(
                2, min(self.num_workers, os.cpu_count() or self.num_workers)
            )
        self._in_q = queue.Queue()
        self._watchdog = Watchdog(
            what="decode pool", timeout_s=self.watchdog_timeout_s,
        )
        with self._pool_lock:
            for _ in range(self.num_workers):
                self._spawn_worker()
        self._pool_started = True
        from ..tune.controller import set_effective

        set_effective("num_decode_workers", self.num_workers)
        set_effective("decode_queue_depth", self.queue_depth)
        if not self.silent:
            mode = ("decode+crop (split float tail)" if self._pil_mode
                    else "decode+augment" if self._raw_source
                    else "augment")
            print(f"ParallelAugmentIterator: {self.num_workers} workers "
                  f"({mode}), chunk={self.chunk_size}, "
                  f"window={self.queue_depth} chunks")

    # ------------------------------------------------------------------
    # worker side
    def _worker(self) -> None:
        while True:
            task = self._in_q.get()
            if task is None:
                # a shrink token (or close()): retire.  The token count
                # and this thread's pool membership flip together under
                # the lock, so a concurrent reconcile always sees a
                # consistent (alive - pending) and can never over-
                # poison through the thread-teardown window.  close()'s
                # tokens were never counted pending — clamp at zero.
                with self._pool_lock:
                    self._poison_pending = max(0, self._poison_pending - 1)
                    try:
                        self._threads.remove(threading.current_thread())
                    except ValueError:
                        pass
                return
            gen, seq, epoch, mode, items = task
            try:
                faults.fault_point("pipeline.worker")
                result = self._process(epoch, mode, items)
            except BaseException as e:  # noqa: BLE001 - relayed to consumer
                result = _WorkerError(e)
            with self._cond:
                if gen == self._gen and not self._stop:
                    self._results[seq] = result
                    if self._watchdog is not None:
                        self._watchdog.beat()
                    self._cond.notify_all()

    def _process(self, epoch: int, mode: str, items):
        """One chunk's worker work, preserving record order; failures
        become in-place :class:`_BadRecord` markers.  Returns
        ``(kind, epoch, results)`` where kind ``"tail"`` means the
        consumer still owes the records the vectorized float tail."""
        if mode == "pil":
            src = self._raw_source
            out: List[object] = []
            t0 = time.perf_counter()
            for rec in items:
                try:
                    im = src.decode_pil(rec)
                except Exception as e:  # noqa: BLE001 - untrusted bytes
                    # only DECODE failures are quarantinable data; an
                    # augment error (e.g. image smaller than the crop)
                    # propagates like the serial path's ValueError
                    out.append(_BadRecord(rec.source, rec.offset, e))
                    continue
                out.append(self.aug.augment_pil(
                    im, rec.index, rec.labels, epoch))
            pipeline_stats().add("decode", time.perf_counter() - t0,
                                 rows=len(items))
            return ("tail", epoch, out)
        if mode == "raw":
            src = self._raw_source
            decoded: List[object] = []
            for rec in items:
                try:
                    decoded.append(
                        DataInst(rec.index, src.decode_record(rec),
                                 rec.labels)
                    )
                except Exception as e:  # noqa: BLE001 - untrusted bytes
                    decoded.append(_BadRecord(rec.source, rec.offset, e))
        else:
            decoded = list(items)
        ok = [d for d in decoded if isinstance(d, DataInst)]
        t0 = time.perf_counter()
        augmented = iter(self.aug.augment_insts(ok, epoch, apply_mean=True))
        pipeline_stats().add("augment", time.perf_counter() - t0,
                             rows=len(ok))
        return ("final", epoch,
                [next(augmented) if isinstance(d, DataInst) else d
                 for d in decoded])

    # ------------------------------------------------------------------
    # consumer side
    def _fetch_chunk(self):
        """Pull up to ``chunk_size`` work items from the source (consumer
        thread, epoch order).  Returns ``(mode, items)`` or None."""
        items: List[object] = []
        if self._raw_source is not None:
            fetch_block = getattr(self._raw_source, "next_raw_block", None)
            if fetch_block is not None:
                items = fetch_block(self.chunk_size)
                if len(items) < self.chunk_size:
                    self._exhausted = True
            else:
                while len(items) < self.chunk_size:
                    rec = self._raw_source.next_raw()
                    if rec is None:
                        self._exhausted = True
                        break
                    items.append(rec)
            mode = "pil" if self._pil_mode else "raw"
            return (mode, items) if items else None
        src = self.aug.base
        while len(items) < self.chunk_size:
            if not src.next():
                self._exhausted = True
                break
            items.append(src.value())
        return ("inst", items) if items else None

    def _refill(self) -> None:
        while (not self._exhausted
               and self._seq_submit - self._seq_take < self.queue_depth):
            chunk = self._fetch_chunk()
            if chunk is None:
                break
            mode, items = chunk
            self._in_q.put(
                (self._gen, self._seq_submit, self.aug.epoch, mode, items)
            )
            self._seq_submit += 1
        if self._watchdog is not None:
            self._watchdog.beat()  # submission is progress too

    def _stall_diagnostic(self, dt: float) -> str:
        msg = self._watchdog.diagnostic(dt)
        frames = sys._current_frames()
        for t in self._threads:
            if not t.is_alive():
                msg += f"\nworker {t.name!r} is DEAD"
                continue
            frame = frames.get(t.ident)
            if frame is not None:
                stack = "".join(traceback.format_stack(frame))
                msg += f"\nworker {t.name!r} stack:\n{stack}"
        return msg

    def _wait_result(self, seq: int):
        """Block until chunk ``seq`` lands, with stall detection."""
        wd = self._watchdog
        since = time.monotonic()
        with self._cond:
            while seq not in self._results:
                self._cond.wait(0.2)
                if wd is not None and wd.enabled:
                    # progress = the newer of the pool's last beat and
                    # the start of THIS wait (a legitimately idle pool
                    # must not look hung the moment a wait begins)
                    dt = min(wd.stalled_for(),
                             time.monotonic() - since)
                    if dt > wd.timeout_s:
                        from ..obs import emit as obs_emit

                        obs_emit("watchdog.fire", what=wd.what,
                                 stalled_s=dt, timeout_s=wd.timeout_s)
                        raise WatchdogError(self._stall_diagnostic(dt))
            return self._results.pop(seq)

    def before_first(self):
        if (not self._pool_started and self.num_workers > 1
                and self._init_done and not self._closed):
            # a runtime request_workers() on a serial chain lands here:
            # the epoch boundary is the safe point to grow the pool (the
            # consumer owns the source cursor mid-epoch)
            self._start_pool()
        if not self._pool_started:
            self.aug.before_first()
            return
        with self._cond:
            self._gen += 1
            self._results.clear()
        # drain queued-but-unstarted tasks of the old generation so the
        # workers don't burn time decoding records nobody will consume;
        # swallowed shrink tokens are re-credited so reconcile re-issues
        # exactly the surplus
        drained_tokens = 0
        try:
            while True:
                if self._in_q.get_nowait() is None:
                    drained_tokens += 1
        except queue.Empty:
            pass
        if drained_tokens:
            with self._pool_lock:
                self._poison_pending = max(
                    0, self._poison_pending - drained_tokens)
        # apply any pending resize AFTER the drain, prune dead
        self._reconcile_pool()
        self._seq_submit = 0
        self._seq_take = 0
        self._exhausted = False
        self._pending = []
        self._pending_pos = 0
        self._yielded = 0
        self._cap = (getattr(self._raw_source, "epoch_cap", 0)
                     if self._raw_source is not None else 0)
        self.aug.before_first()
        if self._watchdog is not None:
            self._watchdog.beat()

    def next(self) -> bool:
        if not self._pool_started:
            if not self.aug.next():
                return False
            self._out = self.aug.value()
            return True
        cap = self._cap
        while True:
            if cap and self._yielded >= cap:
                return False
            if self._pending_pos < len(self._pending):
                item = self._pending[self._pending_pos]
                self._pending_pos += 1
                if isinstance(item, _BadRecord):
                    # budget accounting on the consumer, in record
                    # order — raises BadDataError past the budget
                    self._raw_source.record_bad(
                        item.source, item.offset, item.exc
                    )
                    continue
                self._out = item
                self._yielded += 1
                return True
            self._refill()
            if self._seq_take >= self._seq_submit:
                # exhausted and fully drained: every in-flight decode
                # failure has passed through record_bad by now, so the
                # source's epoch skip summary is finally accurate
                note = getattr(self._raw_source, "note_epoch_end", None)
                if note is not None:
                    note()
                return False
            result = self._wait_result(self._seq_take)
            self._seq_take += 1
            # the consumed chunk freed a window slot: hand the workers
            # their next task BEFORE draining these records, so the
            # pool never sits idle while the consumer yields
            self._refill()
            if isinstance(result, _WorkerError):
                raise result.exc
            kind, chunk_epoch, records = result
            if kind == "tail":
                # the vectorized float tail (mean/jitter/scale) runs
                # HERE, once, off the workers' GIL footprint
                ok = [d for d in records if isinstance(d, DataInst)]
                t0 = time.perf_counter()
                done = iter(self.aug.augment_tail(ok, chunk_epoch))
                pipeline_stats().add("augment", time.perf_counter() - t0,
                                     rows=len(ok))
                records = [next(done) if isinstance(d, DataInst) else d
                           for d in records]
            self._pending = records
            self._pending_pos = 0

    def value(self) -> DataInst:
        assert self._out is not None
        return self._out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._in_q is not None:
            for _ in self._threads:
                self._in_q.put(None)
        for t in self._threads:
            if t.is_alive():
                t.join(timeout=2.0)  # daemons: a hung decode never
                # blocks interpreter exit
        self._threads = []
        self.aug.close()

    def __del__(self):  # pragma: no cover - GC-order dependent
        try:
            self.close()
        except Exception:
            pass
