"""Packed binary-page image reader + writer — the ImageNet-scale format.

The reference streams 64 MB ``BinaryPage`` shards of packed JPEG blobs
with a parallel ``.lst`` label file, double-buffered across two reader
threads (``/root/reference/src/io/iter_thread_imbin_x-inl.hpp``,
``/root/reference/src/utils/io.h:225-300``).  This implementation keeps
the same architecture — page-granular sequential reads, shard sharding by
worker rank, background prefetch — and reads TWO page layouts,
auto-detected per file by the leading u32:

* ``CXBP`` (this framework's native layout; written by
  ``tools/im2bin.py`` default mode):

      page file := { page }*
      page      := magic u32 | nrec u32 | {len u32}*nrec | {blob}*nrec

* the reference's ``BinaryPage`` bit-format
  (``/root/reference/src/utils/io.h:225-300``; written by the
  reference's ``tools/im2bin.cpp``): fixed 64 MiB pages of little-endian
  i32s where ``data[0] = nrec``, ``data[1..nrec+1]`` are cumulative blob
  byte sizes (``data[1] = 0``), and blob ``r`` occupies the byte range
  ``[page_end - data[r+2], page_end - data[r+1])`` — blobs pack
  backwards from the end of the page.  ``RefBinPageWriter`` emits this
  layout byte-for-byte, so cxxnet-era ``.bin`` + ``.lst`` packs train
  without repacking (and new packs can be written for the reference).

``.lst`` line format parity: ``index \t label(s) \t filename``.

Distributed sharding parity (iter_thread_imbin_x-inl.hpp:108-139): with
``dist_num_worker > 1``, worker ``dist_worker_rank`` reads the subset of
shard files (round-robin by file).
"""

from __future__ import annotations

import io as _io
import os
import struct
import time
import warnings
from typing import IO, List, Optional, Tuple

import numpy as np

from ..utils import faults
from ..utils.faults import BadRecordBudget
from ..utils.profiler import pipeline_stats
from .batch import DataInst, InstIterator

PAGE_MAGIC = 0x43584250  # "CXBP"
DEFAULT_PAGE_SIZE = 64 << 20
# the reference's BinaryPage: kPageSize = 64<<18 i32s = 64 MiB exactly
# (io.h:226); every page on disk is this many bytes, full or not
REF_PAGE_BYTES = (64 << 18) * 4


class BinPageWriter:
    """Pack blobs into ~page_size pages (tools/im2bin analog)."""

    def __init__(self, path: str, page_size: int = DEFAULT_PAGE_SIZE) -> None:
        self.f: IO[bytes] = open(path, "wb")
        self.page_size = page_size
        self._blobs: List[bytes] = []
        self._cur = 0

    def push(self, blob: bytes) -> None:
        if self._cur + len(blob) + 8 > self.page_size and self._blobs:
            self.flush_page()
        self._blobs.append(blob)
        self._cur += len(blob) + 4

    def flush_page(self) -> None:
        if not self._blobs:
            return
        self.f.write(struct.pack("<II", PAGE_MAGIC, len(self._blobs)))
        for b in self._blobs:
            self.f.write(struct.pack("<I", len(b)))
        for b in self._blobs:
            self.f.write(b)
        self._blobs, self._cur = [], 0

    def close(self) -> None:
        self.flush_page()
        self.f.close()


class RefBinPageWriter:
    """Write the reference's BinaryPage bit-format byte-for-byte.

    Mirrors ``BinaryPage::Push/Save`` (io.h:254-271) + the ``im2bin.cpp``
    page-flush loop: i32 header array growing from the front, blobs
    packing backwards from the 64 MiB page end, every saved page padded
    to exactly ``REF_PAGE_BYTES``.
    """

    def __init__(self, path: str) -> None:
        self.f: IO[bytes] = open(path, "wb")
        self._blobs: List[bytes] = []
        self._cum = 0  # data_[nrec+1]: cumulative blob bytes

    def _free_bytes(self) -> int:
        # FreeBytes() (io.h:286-288): ints not yet used by the header,
        # minus the blob bytes already packed at the tail
        n = len(self._blobs)
        return (REF_PAGE_BYTES // 4 - (n + 2)) * 4 - self._cum

    def push(self, blob: bytes) -> None:
        if self._free_bytes() < len(blob) + 4:
            self.flush_page()
            if self._free_bytes() < len(blob) + 4:
                raise ValueError(
                    f"blob of {len(blob)} bytes exceeds the 64 MiB page"
                )
        self._blobs.append(blob)
        self._cum += len(blob)

    def flush_page(self) -> None:
        if not self._blobs:
            return
        hdr = np.zeros(len(self._blobs) + 2, "<i4")
        hdr[0] = len(self._blobs)
        hdr[1:] = 0
        np.cumsum([len(b) for b in self._blobs], out=hdr[2:])
        page = bytearray(REF_PAGE_BYTES)
        page[: hdr.nbytes] = hdr.tobytes()
        end = REF_PAGE_BYTES
        for b in self._blobs:  # first blob lands at the very page end
            page[end - len(b): end] = b
            end -= len(b)
        self.f.write(page)
        self._blobs, self._cum = [], 0

    def close(self) -> None:
        self.flush_page()
        self.f.close()


def detect_bin_format(path: str) -> str:
    """``'cxbp'`` or ``'ref'`` by the leading u32.  A reference page
    starts with its record count — far below the CXBP magic value — and
    reference files are whole 64 MiB pages."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        head = f.read(8)
    if len(head) < 8:
        raise ValueError(f"{path}: too short to be a page file")
    first, second = struct.unpack("<II", head)
    if first == PAGE_MAGIC:
        return "cxbp"
    if size % REF_PAGE_BYTES == 0 and second == 0:
        return "ref"
    raise ValueError(
        f"{path}: neither CXBP (magic {PAGE_MAGIC:#x}) nor reference "
        f"BinaryPage (64 MiB pages, first offset 0); got "
        f"head=({first:#x}, {second:#x}), size={size}"
    )


def iter_ref_bin_pages(path: str):
    """Yield lists of blobs from a reference-format ``.bin`` (io.h layout).

    Page-granular: one 64 MiB read per page; each blob is a zero-copy
    ``memoryview`` slice of the page buffer (no per-instance copy)."""
    with open(path, "rb") as f:
        while True:
            page = f.read(REF_PAGE_BYTES)
            if not page:
                return
            if len(page) < REF_PAGE_BYTES:
                raise ValueError(f"{path}: truncated 64 MiB page")
            nrec = struct.unpack_from("<i", page)[0]
            if nrec < 0 or (nrec + 2) * 4 > REF_PAGE_BYTES:
                raise ValueError(f"{path}: corrupt page (nrec={nrec})")
            offs = np.frombuffer(page, "<i4", count=nrec + 1, offset=4)
            if offs[0] != 0 or (np.diff(offs) < 0).any() or (
                int(offs[-1]) + (nrec + 2) * 4 > REF_PAGE_BYTES
            ):
                raise ValueError(f"{path}: corrupt page offsets")
            mv = memoryview(page)
            yield [
                mv[REF_PAGE_BYTES - int(offs[r + 1]):
                   REF_PAGE_BYTES - int(offs[r])]
                for r in range(nrec)
            ]


def iter_cxbp_pages(path: str):
    """Yield lists of blobs, one list per CXBP page.

    Page-granular: header + length table + ONE read for the whole blob
    region, then zero-copy ``memoryview`` slices — the old per-blob
    ``f.read(l)`` did one syscall and one bytes allocation per instance.
    A truncated final page yields short tail blobs (the downstream
    decoder fails on them record by record), matching the short-read
    behavior of the per-blob reads."""
    with open(path, "rb") as f:
        while True:
            hdr = f.read(8)
            if len(hdr) < 8:
                return
            magic, nrec = struct.unpack("<II", hdr)
            if magic != PAGE_MAGIC:
                raise ValueError(f"{path}: bad page magic {magic:#x}")
            lens_raw = f.read(4 * nrec)
            if len(lens_raw) < 4 * nrec:
                raise ValueError(f"{path}: truncated page length table")
            lens = struct.unpack(f"<{nrec}I", lens_raw)
            mv = memoryview(f.read(sum(lens)))
            out, off = [], 0
            for l in lens:
                out.append(mv[off: off + l])
                off += l
            yield out


def iter_bin_pages(path: str):
    """Yield lists of blobs per page; the layout is auto-detected, so
    cxxnet-era reference packs and native CXBP packs both read.  An
    empty pack (what a writer closed on zero pushes produces) yields no
    pages; a 1-7 byte file is a truncation and still raises."""
    if os.path.getsize(path) == 0:
        return iter(())
    if detect_bin_format(path) == "ref":
        return iter_ref_bin_pages(path)
    return iter_cxbp_pages(path)


def parse_lst_line(line: str) -> Tuple[int, np.ndarray, str]:
    """``index \\t labels... \\t filename`` (tab-separated)."""
    parts = line.rstrip("\n").split("\t")
    if len(parts) < 3:
        raise ValueError(f"bad .lst line: {line!r}")
    idx = int(float(parts[0]))
    labels = np.asarray([float(t) for t in parts[1:-1]], np.float32)
    return idx, labels, parts[-1]


def decode_image(blob) -> np.ndarray:
    """JPEG/PNG blob (bytes-like) → HWC RGB float32 (values 0..255, like
    the reference's raw decode; scaling is the augmenter's job via
    ``divideby``/``scale``)."""
    return decode_image_u8(blob).astype(np.float32)


def decode_image_u8(blob) -> np.ndarray:
    """JPEG/PNG blob → HWC RGB **uint8**.  The hot decode path: the
    float32 conversion is deferred to the augmenter (uint8 → float32 is
    exact, so converting after the crop instead of before it changes no
    values while moving 4x less memory per record)."""
    from PIL import Image

    img = Image.open(_io.BytesIO(blob))
    if img.mode != "RGB":
        img = img.convert("RGB")
    return np.asarray(img)


class RawRecord:
    """One undecoded record: the unit of work the parallel decode pool
    (``io/pipeline.py``) hands to a worker.  ``source``/``offset`` are
    the quarantine coordinates for :meth:`ImageBinIterator.record_bad`
    when the worker's decode fails."""

    __slots__ = ("index", "labels", "payload", "source", "offset")

    def __init__(self, index: int, labels: np.ndarray, payload,
                 source: str, offset) -> None:
        self.index = index
        self.labels = labels
        self.payload = payload
        self.source = source
        self.offset = offset


def _count_lst_rows(lst_path: str) -> int:
    """Row count of a .lst label file (cheap: line count)."""
    n = 0
    with open(lst_path, "r", encoding="utf-8") as f:
        for line in f:
            if line.strip():
                n += 1
    return n


class ImageBinIterator(InstIterator):
    """Instance iterator over one or more page shards + .lst label files."""

    def supports_dist_shard(self) -> bool:
        return True

    def __init__(self) -> None:
        self.image_bin: List[str] = []
        self.image_list: List[str] = []
        self.image_conf_prefix = ""  # printf shard pattern, e.g. tr_%03d
        self.image_conf_ids = ""  # inclusive id range "lb-ub"
        self.silent = 0
        self.shuffle_shards = 0
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self._records: List[Tuple[int, np.ndarray]] = []  # (index, labels)
        self._shards: List[Tuple[str, str]] = []
        self._page_iter = None
        self._page: List[bytes] = []
        self._page_pos = 0
        self._shard_pos = 0
        self._rec_pos = 0
        self._out: Optional[DataInst] = None
        self._raw = 0  # raw float blobs instead of encoded images
        self.native_decoder = 1  # C++ reader+decode pool when buildable
        self.decode_thread = 0  # 0 = auto (ncpu - 2)
        self._native = None  # NativePageReader
        self._native_labels: List[Tuple[int, np.ndarray]] = []
        self._native_pos = 0
        self._epoch_cap = 0
        self._served = 0
        self.max_bad_records = 0  # skip budget per epoch; 0 = strict
        self.quarantine_dir = ""
        self._budget: Optional[BadRecordBudget] = None

    def set_param(self, name, val):
        if name in ("image_bin", "image_bin_x"):
            self.image_bin.append(val)
        elif name in ("image_list", "image_list_x"):
            self.image_list.append(val)
        elif name == "image_conf_prefix":
            self.image_conf_prefix = val
        elif name == "image_conf_ids":
            self.image_conf_ids = val
        elif name == "silent":
            self.silent = int(val)
        elif name == "shuffle_bin":
            self.shuffle_shards = int(val)
        elif name == "raw_pixels":
            self._raw = int(val)
        elif name == "dist_num_worker":
            self.dist_num_worker = int(val)
        elif name == "dist_worker_rank":
            self.dist_worker_rank = int(val)
        elif name == "native_decoder":
            self.native_decoder = int(val)
        elif name == "decode_thread":
            self.decode_thread = int(val)
        elif name == "max_bad_records":
            self.max_bad_records = int(val)
        elif name == "quarantine_dir":
            self.quarantine_dir = val

    def init(self):
        # PS_RANK env parity: the reference applies it UNCONDITIONALLY
        # (iter_thread_imbin-inl.hpp:190-194), so a hadoop-style launch
        # where the conf carries dist_num_worker and only the env knows
        # the rank still shards correctly
        if os.environ.get("PS_RANK"):
            self.dist_worker_rank = int(os.environ["PS_RANK"])
            if self.dist_num_worker == 1:
                self.dist_num_worker = int(
                    os.environ.get("PS_NUM_WORKER", "1") or 1
                )
        conf_mode = bool(self.image_conf_prefix)
        if conf_mode:
            # shard-list shorthand: a printf pattern plus an inclusive id
            # range expands to <prefix%i>.lst/.bin pairs, and workers take
            # CONTIGUOUS id blocks (iter_thread_imbin-inl.hpp:189-220)
            if self.image_bin or self.image_list:
                raise ValueError(
                    "imgbin: set either image_conf_prefix or "
                    "image_bin/image_list, not both"
                )
            import re as _re

            m = _re.fullmatch(r"\s*(\d+)-(\d+)\s*", self.image_conf_ids)
            if not m:
                raise ValueError(
                    "imgbin: image_conf_ids only supports a range like 1-100"
                )
            lb, ub = int(m.group(1)), int(m.group(2))
            if ub < lb:
                raise ValueError("imgbin: image_conf_ids range is empty")
            try:
                names = [self.image_conf_prefix % i for i in range(lb, ub + 1)]
                if names[0] == self.image_conf_prefix:
                    raise ValueError("pattern formats nothing")
            except (TypeError, ValueError) as e:
                raise ValueError(
                    "imgbin: image_conf_prefix must contain one %d-style "
                    f"pattern (got {self.image_conf_prefix!r}): {e}"
                ) from e
            self.image_bin = [n + ".bin" for n in names]
            self.image_list = [n + ".lst" for n in names]
        if len(self.image_bin) != len(self.image_list):
            raise ValueError("imgbin: need matching image_bin / image_list counts")
        if not self.image_bin:
            raise ValueError("imgbin: must set image_bin and image_list")
        shards = list(zip(self.image_bin, self.image_list))
        self._epoch_cap = 0
        if self.dist_num_worker > 1:
            if len(shards) < self.dist_num_worker:
                raise ValueError(
                    f"imgbin: {len(shards)} shard file(s) cannot feed "
                    f"{self.dist_num_worker} workers distinct data — "
                    "repack with tools/imgbin_partition_maker.py "
                    "(>= one shard per worker)"
                )
            if conf_mode:
                # ceil-step contiguous blocks; a tail worker may come up
                # empty even when len(shards) >= num_worker (e.g. 4 ids
                # over 3 workers -> blocks of 2,2,0)
                step = -(-len(shards) // self.dist_num_worker)
                owner = lambda i: i // step  # noqa: E731
                if (self.dist_num_worker - 1) * step >= len(shards):
                    raise ValueError(
                        "imgbin: too many workers — the image_conf_ids "
                        "range cannot be divided into non-empty "
                        "contiguous blocks"
                    )
            else:
                owner = lambda i: i % self.dist_num_worker  # noqa: E731
            mine = [
                s
                for i, s in enumerate(shards)
                if owner(i) == self.dist_worker_rank
            ]
            # equal-steps contract (io/data.shard_rows): every process
            # must run the same batch count per round or the SPMD train
            # loop deadlocks.  All .lst files are in the conf, so each
            # worker can count every worker's rows and cap its own epoch
            # at the global minimum.
            per_worker = [0] * self.dist_num_worker
            for i, (_, lst) in enumerate(shards):
                per_worker[owner(i)] += _count_lst_rows(lst)
            self._epoch_cap = min(per_worker)
            if self._epoch_cap == 0:
                # 0 would read as "no cap" in next() and revive the
                # unequal-steps deadlock; an empty worker is a packing
                # error either way
                raise ValueError(
                    f"imgbin: worker {per_worker.index(0)}'s shard files "
                    "contain 0 rows — repack so every worker gets data"
                )
            shards = mine
        self._shards = shards
        if self.native_decoder and not self._raw and self.max_bad_records > 0:
            # skip-and-quarantine needs record-level error isolation,
            # which only the pure-Python reader provides — the native
            # reader pool decodes ahead across threads and a corrupt
            # record would abort it wholesale.  A set budget therefore
            # forces the Python path, uniformly on every machine.
            self.native_decoder = 0
            if not self.silent:
                print("imgbin: max_bad_records set; using the pure-Python "
                      "reader for skip-and-quarantine", flush=True)
        if self.native_decoder and not self._raw:
            try:
                from .native import NativePageReader, available

                if available():
                    self._native = NativePageReader(
                        [b for b, _ in shards], self.decode_thread
                    )
                    self._native_labels = []
                    for _, lst in shards:
                        self._native_labels.extend(self._load_labels(lst))
            except Exception as e:
                if self._native is not None:
                    self._native.close()  # stop reader/decode threads
                    self._native = None
                warnings.warn(
                    f"imgbin: native decoder disabled, pure-Python fallback: {e}"
                )
        self._budget = BadRecordBudget(
            self.max_bad_records, what="imgbin",
            silent=bool(self.silent),
            quarantine_dir=self.quarantine_dir or None,
        )
        self.before_first()

    def _load_labels(self, lst_path: str) -> List[Tuple[int, np.ndarray]]:
        out = []
        with open(lst_path, "r", encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    idx, labels, _ = parse_lst_line(line)
                    out.append((idx, labels))
        return out

    def before_first(self):
        self._served = 0
        if self._budget is not None:
            self._budget.start_epoch()
        if self._native is not None:
            self._native.reset()
            self._native_pos = 0
            return
        self._shard_pos = 0
        self._open_shard(0)

    def _open_shard(self, k: int) -> None:
        while k < len(self._shards):
            bin_path, lst_path = self._shards[k]
            try:
                records = self._load_labels(lst_path)
            except (OSError, ValueError) as e:
                if self._budget is None:
                    raise
                self._budget.record(bin_path, "open", e,
                                    note="whole shard skipped")
                k += 1
                continue
            try:
                page_iter = iter_bin_pages(bin_path)
            except (OSError, ValueError) as e:
                # shard unreadable at open time (bad page format,
                # missing file): quarantine the whole shard — with its
                # record count, so the loss is never under-reported —
                # or abort via the budget when skipping is not allowed
                if self._budget is None:
                    raise
                self._budget.record(
                    bin_path, "open", e,
                    note=f"whole shard skipped, {len(records)} record(s) "
                         "dropped")
                k += 1
                continue
            self._records = records
            self._page_iter = page_iter
            self._page, self._page_pos, self._rec_pos = [], 0, 0
            self._shard_pos = k
            return
        self._shard_pos = k
        self._page_iter = None

    def next(self) -> bool:
        if self._epoch_cap and self._served >= self._epoch_cap:
            return False
        if not self._next_inner():
            if (self._budget is not None and self._budget.epoch_count
                    and not self.silent):
                print(self._budget.summary(), flush=True)
            return False
        self._served += 1
        return True

    def _next_inner(self) -> bool:
        if self._native is not None:
            rec = self._native.next()
            if rec is None:
                return False
            kind, payload = rec
            if kind == 1:
                data = np.asarray(payload, np.float32)
            else:
                data = decode_image(payload)  # non-JPEG: PIL fallback
            idx, labels = self._native_labels[self._native_pos]
            self._native_pos += 1
            self._out = DataInst(idx, data, labels)
            return True
        return self._next_python()

    def _next_python(self) -> bool:
        while True:
            rec = self._raw_next()
            if rec is None:
                return False
            try:
                # float32 here (the iterator's long-standing instance
                # contract for direct consumers); the pool's worker
                # paths decode to uint8 instead and convert after the
                # crop — both are exact, so the streams stay identical
                t0 = time.perf_counter()
                data = (self._decode_raw(rec.payload) if self._raw
                        else decode_image(rec.payload))
                pipeline_stats().add("decode", time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001 - untrusted bytes
                # corrupt record: quarantine + skip; BadDataError
                # aborts with a summary once the budget is exhausted
                self._budget.record(rec.source, rec.offset, e)
                continue
            self._out = DataInst(rec.index, data, rec.labels)
            return True

    def _raw_next(self) -> Optional[RawRecord]:
        """Next undecoded record of the Python reader (page/shard
        advance, page-level quarantine, ``imgbin.record`` fault point —
        everything except the decode).  None at epoch end."""
        while True:
            if self._page_iter is None:
                return None
            bin_path = self._shards[self._shard_pos][0]
            if self._page_pos < len(self._page):
                blob = self._page[self._page_pos]
                self._page_pos += 1
                rec = self._rec_pos
                idx, labels = self._records[rec]
                self._rec_pos += 1
                # the fault draw happens HERE, on the consumer thread in
                # record order, so chaos schedules replay independently
                # of decode worker count/interleaving
                blob = faults.fault_point("imgbin.record", blob)
                return RawRecord(idx, labels, blob, bin_path, rec)
            try:
                faults.fault_point("imgbin.page")
                self._page = next(self._page_iter)
                self._page_pos = 0
            except StopIteration:
                self._shard_pos += 1
                self._open_shard(self._shard_pos)
                if self._shard_pos >= len(self._shards):
                    return None
            except (OSError, ValueError) as e:
                # corrupt/unreadable page: past this point the shard's
                # blob↔label alignment is unrecoverable, so quarantine
                # the page — reporting the trailing records it drops —
                # and resume at the next shard boundary
                dropped = len(self._records) - self._rec_pos
                self._budget.record(
                    bin_path, f"page@rec{self._rec_pos}", e,
                    note=f"{dropped} trailing record(s) of the shard "
                         "dropped")
                self._shard_pos += 1
                self._open_shard(self._shard_pos)
                if self._shard_pos >= len(self._shards):
                    return None

    # ------------------------------------------------------------------
    # raw-record API for the parallel decode pool (io/pipeline.py)
    def raw_available(self) -> bool:
        """True when :meth:`next_raw` can feed the pool: the pure-Python
        reader path (the native reader decodes on its own C++ pool and
        yields only decoded instances)."""
        return self._native is None

    @property
    def epoch_cap(self) -> int:
        """Distributed equal-steps cap on instances per epoch (0 = no
        cap).  In raw mode the POOL enforces it on decoded successes —
        the exact semantics of the serial ``next()`` counter."""
        return self._epoch_cap

    def next_raw(self) -> Optional[RawRecord]:
        """Pool-facing: next undecoded record, or None at source end.
        The epoch skip summary is NOT printed here — decode failures
        from in-flight chunks are still unaccounted at raw exhaustion;
        the pool calls :meth:`note_epoch_end` once fully drained."""
        return self._raw_next()

    def note_epoch_end(self) -> None:
        """Pool-facing epoch close: print the skip/quarantine summary
        (the serial ``next()`` contract) now that every worker decode
        failure has been recorded by the consumer."""
        if (self._budget is not None and self._budget.epoch_count
                and not self.silent):
            print(self._budget.summary(), flush=True)

    def next_raw_block(self, k: int) -> List[RawRecord]:
        """Up to ``k`` raw records in one call (the pool's chunk fetch
        — one method dispatch per chunk instead of per record)."""
        out: List[RawRecord] = []
        while len(out) < k:
            rec = self.next_raw()
            if rec is None:
                break
            out.append(rec)
        return out

    def decode_record(self, rec: RawRecord) -> np.ndarray:
        """Decode one raw record — a pure function of the payload, safe
        to call concurrently from pool workers."""
        t0 = time.perf_counter()
        if self._raw:
            data = self._decode_raw(rec.payload)
        else:
            # uint8: the augmenter converts (exactly) after cropping
            data = decode_image_u8(rec.payload)
        pipeline_stats().add("decode", time.perf_counter() - t0)
        return data

    def pil_available(self) -> bool:
        """True when records are encoded images :meth:`decode_pil` can
        produce (raw float blobs have no PIL form)."""
        return not self._raw

    def decode_pil(self, rec: RawRecord):
        """Decode one record to a loaded RGB PIL image (the split
        worker path: crop/flip then happen as PIL C ops).  Pure
        function of the payload — pool-worker safe.  The caller times
        the whole chunk (one stats add per chunk, not per record)."""
        from PIL import Image

        im = Image.open(_io.BytesIO(rec.payload))
        if im.mode != "RGB":
            im = im.convert("RGB")
        im.load()
        return im

    def record_bad(self, source: str, offset, exc: BaseException) -> None:
        """Quarantine accounting for a worker-side decode failure;
        called by the pool CONSUMER in record order (the budget is
        single-threaded by design)."""
        self._budget.record(source, offset, exc)

    @staticmethod
    def _decode_raw(blob: bytes) -> np.ndarray:
        h, w, c = struct.unpack("<HHH", blob[:6])
        return np.frombuffer(blob, np.float32, offset=8).reshape(h, w, c).copy()

    def value(self) -> DataInst:
        assert self._out is not None
        return self._out

    def close(self) -> None:
        if self._native is not None:
            self._native.close()  # stop reader/decode threads
            self._native = None


def encode_raw(img: np.ndarray) -> bytes:
    """Raw-pixel blob: u16 h,w,c + pad + float32 HWC (decode-free bench path)."""
    h, w, c = img.shape
    return struct.pack("<HHHH", h, w, c, 0) + img.astype(np.float32).tobytes()
