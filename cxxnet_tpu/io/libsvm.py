"""LibSVM-format sparse iterator: CSR ``DataBatch`` source.

Parity: the reference keeps CSR fields on ``DataBatch``
(``/root/reference/src/io/data.h:97-101``) but ships no iterator that
fills them; this is the minimal source that does, so the sparse surface
is exercisable end to end.  Format: one instance per line,
``label idx:val idx:val ...`` (0-based feature indices).

TPU note: sparse batches are a *host-side* representation.  The
``densify`` knob (default on) also materializes the dense ``(N, D)``
matrix — static-shaped, MXU-consumable — because data-dependent sparse
shapes cannot live under ``jit``; CSR stays attached for host-side
consumers (ranking losses, feature hashing, diagnostics).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..utils import faults
from ..utils.faults import BadRecordBudget, RetryPolicy
from .data import DataBatch, DataIter


class LibSVMIterator(DataIter):
    """In-memory CSR source over a libsvm text file."""

    def __init__(self) -> None:
        self.path: Optional[str] = None
        self.batch_size = 0
        self.num_feature = 0          # D; inferred from data when 0
        self.label_width = 1
        self.round_batch = 1
        self.densify = 1
        self.silent = 0
        self.max_bad_records = 0
        self.quarantine_dir = ""
        self._retry_cfg: List = []
        self._budget: Optional[BadRecordBudget] = None
        self._row_ptr: Optional[np.ndarray] = None
        self._index: Optional[np.ndarray] = None
        self._value: Optional[np.ndarray] = None
        self._label: Optional[np.ndarray] = None
        self._at = 0
        self._batch: Optional[DataBatch] = None

    def set_param(self, name: str, val: str) -> None:
        if name in ("data_path", "path", "data"):
            self.path = val
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "num_feature":
            self.num_feature = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "round_batch":
            self.round_batch = int(val)
        elif name == "densify":
            self.densify = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "max_bad_records":
            self.max_bad_records = int(val)
        elif name == "quarantine_dir":
            self.quarantine_dir = val
        elif name in RetryPolicy.CONFIG_KEYS:
            self._retry_cfg.append((name, val))

    def _read_lines(self) -> List[str]:
        return faults.retried_read_lines(
            self.path, "libsvm.read", self._retry_cfg,
            silent=bool(self.silent))

    def init(self) -> None:
        if not self.path:
            raise ValueError("libsvm: data_path required")
        if self.batch_size <= 0:
            raise ValueError("libsvm: batch_size required")
        self._budget = BadRecordBudget(
            self.max_bad_records, what="libsvm", silent=bool(self.silent),
            quarantine_dir=self.quarantine_dir or None,
        )
        row_ptr: List[int] = [0]
        idx: List[int] = []
        val: List[float] = []
        labels: List[List[float]] = []
        for lineno, line in enumerate(self._read_lines(), start=1):
            line = faults.fault_point("libsvm.row", line)
            toks = line.split()
            if not toks:
                continue
            mark_idx, mark_val = len(idx), len(val)
            try:
                lab = [float(x)
                       for x in toks[0].split(",")][: self.label_width]
                for t in toks[1:]:
                    i, _, v = t.partition(":")
                    fi = int(i)
                    if fi < 0:
                        raise ValueError(f"negative feature index {fi}")
                    idx.append(fi)
                    val.append(float(v))
            except ValueError as e:
                # corrupt row: roll back its partial features, then
                # quarantine + skip (abort past max_bad_records)
                del idx[mark_idx:], val[mark_val:]
                self._budget.record(self.path, f"line{lineno}", e)
                continue
            labels.append(lab)
            row_ptr.append(len(idx))
        if self._budget.epoch_count and not self.silent:
            print(self._budget.summary(), flush=True)
        self._row_ptr = np.asarray(row_ptr, np.int64)
        self._index = np.asarray(idx, np.int32)
        self._value = np.asarray(val, np.float32)
        lab = np.zeros((len(labels), self.label_width), np.float32)
        for r, ls in enumerate(labels):
            lab[r, : len(ls)] = ls
        self._label = lab
        if self.num_feature == 0:
            self.num_feature = int(self._index.max()) + 1 if idx else 1

    @property
    def num_inst(self) -> int:
        return 0 if self._label is None else self._label.shape[0]

    def before_first(self) -> None:
        self._at = 0

    def next(self) -> bool:
        n = self.num_inst
        if self._at >= n:
            return False
        take = min(self.batch_size, n - self._at)
        rows = list(range(self._at, self._at + take))
        padd = 0
        if take < self.batch_size:
            # the batch is ALWAYS emitted full-size with num_batch_padd
            # marking the pad rows (data.h:86-88; iter_batch_proc-inl.hpp
            # round_batch=0 branch pads in place) — a shape-varying last
            # batch would break static-shape jit consumers.  round_batch=1
            # wraps to the front (modulo keeps wrapping when the whole
            # file is smaller than one batch); round_batch=0 replicates
            # in-range rows, which consumers must ignore via the padd count
            padd = self.batch_size - take
            if self.round_batch:
                rows += [i % n for i in range(padd)]
            else:
                rows += [rows[-1]] * padd
        self._at += take
        self._batch = self._slice(rows, padd)
        return True

    def _slice(self, rows: List[int], padd: int) -> DataBatch:
        counts = self._row_ptr[1:] - self._row_ptr[:-1]
        row_ptr = np.zeros(len(rows) + 1, np.int64)
        np.cumsum(counts[rows], out=row_ptr[1:])
        index = np.concatenate(
            [self._index[self._row_ptr[r]:self._row_ptr[r + 1]] for r in rows]
        ) if rows else np.zeros(0, np.int32)
        value = np.concatenate(
            [self._value[self._row_ptr[r]:self._row_ptr[r + 1]] for r in rows]
        ) if rows else np.zeros(0, np.float32)
        if self.densify:
            dense = np.zeros((len(rows), self.num_feature), np.float32)
            for k in range(len(rows)):
                dense[k, index[row_ptr[k]:row_ptr[k + 1]]] = (
                    value[row_ptr[k]:row_ptr[k + 1]]
                )
        else:
            dense = np.zeros((len(rows), 0), np.float32)
        return DataBatch(
            data=dense,
            label=self._label[rows],
            inst_index=np.asarray(rows, np.int64),
            num_batch_padd=padd,
            sparse_row_ptr=row_ptr,
            sparse_index=index,
            sparse_value=value,
        )

    def value(self) -> DataBatch:
        assert self._batch is not None
        return self._batch
