"""ctypes binding for the native IO pipeline (``native/cxxnet_io.cc``).

The native library plays the role of the reference's ThreadBuffer page +
decode threads (``iter_thread_imbin_x-inl.hpp:203-354``): a C++ reader
thread streams CXBP pages while a libjpeg decode pool converts blobs to
HWC uint8, re-ordered to .lst order.  Python sees a simple pull
iterator.  Falls back gracefully: ``available()`` is False when the
shared library can't be built (no g++/libjpeg), and records the C++ side
couldn't decode (non-JPEG) come back as raw blobs for PIL.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libcxxnet_io.so"))

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    # Rebuild when the .so is missing or older than its source — a stale
    # library must never mask source drift.  An fcntl lock serializes
    # concurrent first-builds (multi-process training ranks all racing
    # make); the Makefile renames atomically so a mapped .so is never
    # rewritten in place.
    ndir = os.path.abspath(_NATIVE_DIR)
    src = os.path.join(ndir, "cxxnet_io.cc")
    try:
        stale = (not os.path.exists(_LIB_PATH)
                 or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src))
    except OSError:
        stale = True
    if stale:
        try:
            import fcntl

            with open(os.path.join(ndir, ".build.lock"), "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                subprocess.run(
                    ["make", "-C", ndir],
                    check=True, capture_output=True, timeout=120,
                )
        except Exception:
            if not os.path.exists(_LIB_PATH):
                return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.cxio_open.restype = ctypes.c_void_p
    lib.cxio_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.cxio_reset.argtypes = [ctypes.c_void_p]
    lib.cxio_next.restype = ctypes.c_int
    lib.cxio_next.argtypes = [ctypes.c_void_p]
    lib.cxio_kind.restype = ctypes.c_int
    lib.cxio_kind.argtypes = [ctypes.c_void_p]
    lib.cxio_shape.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.cxio_size.restype = ctypes.c_long
    lib.cxio_size.argtypes = [ctypes.c_void_p]
    lib.cxio_copy.restype = ctypes.c_long
    lib.cxio_copy.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long
    ]
    lib.cxio_close.argtypes = [ctypes.c_void_p]
    lib.cxio_error.restype = ctypes.c_char_p
    lib.cxio_error.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


class NativePageReader:
    """Ordered record stream over CXBP shards, decoded off-thread.

    ``next()`` returns ``(kind, payload)``: kind 1 → HWC uint8 ndarray;
    kind 0 → raw ``bytes`` for the caller to decode.
    """

    def __init__(self, bin_paths: List[str], n_decode: int = 0) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native IO library unavailable")
        if n_decode <= 0:
            n_decode = max(2, (os.cpu_count() or 4) - 2)
        joined = "\n".join(bin_paths).encode("utf-8")
        self._lib = lib
        self._h = lib.cxio_open(joined, n_decode)
        if not self._h:
            raise ValueError(f"cxio_open failed for {bin_paths}")

    def reset(self) -> None:
        self._lib.cxio_reset(self._h)

    def next(self) -> Optional[Tuple[int, object]]:
        lib = self._lib
        if not lib.cxio_next(self._h):
            # distinguish clean EOF from a reader failure: a missing or
            # corrupt shard must raise (silent truncation would misalign
            # records with .lst labels), matching the Python path's errors
            err = lib.cxio_error(self._h)
            if err:
                raise RuntimeError(err.decode("utf-8", "replace"))
            return None
        kind = lib.cxio_kind(self._h)
        size = lib.cxio_size(self._h)
        buf = np.empty(size, np.uint8)
        got = lib.cxio_copy(
            self._h, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)), size
        )
        if got != size:
            raise RuntimeError("cxio_copy size mismatch")
        if kind == 1:
            h = ctypes.c_int()
            w = ctypes.c_int()
            c = ctypes.c_int()
            lib.cxio_shape(self._h, h, w, c)
            return 1, buf.reshape(h.value, w.value, c.value)
        return 0, buf.tobytes()

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.cxio_close(self._h)
            self._h = None

    def __del__(self) -> None:  # pragma: no cover - finalizer
        try:
            self.close()
        except Exception:
            pass
