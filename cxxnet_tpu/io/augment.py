"""Per-instance augmentation: crop, mirror, mean subtraction, jitter, affine.

Parity: ``/root/reference/src/io/iter_augment_proc-inl.hpp`` (crop /
mirror / mean-image-or-value / contrast / illumination / scale, and the
first-run mean-image computation cached to ``image_mean``) plus
``/root/reference/src/io/image_augmenter-inl.hpp`` (rotation, shear,
aspect-ratio and scale jitter folded into a single affine warp, random
crop-size ranges, rotate lists).  The affine warp here uses PIL instead of
OpenCV ``warpAffine``; the parameter names and ranges are identical.

Channel-order note: the reference decodes with OpenCV (BGR) and parses
``mean_value = b,g,r``; this framework stores RGB, and ``mean_value`` is
applied in the file order to channels ``(2, 1, 0)`` so the same config
subtracts the same per-channel values.
"""

from __future__ import annotations

import math
import os
from typing import List, Optional

import numpy as np

from .batch import DataInst, InstIterator

_RAND_MAGIC = 111


class AugmentIterator(InstIterator):
    def supports_dist_shard(self) -> bool:
        return self.base.supports_dist_shard()

    def __init__(self, base: InstIterator) -> None:
        self.base = base
        self.shape = (0, 0, 0)           # (C,H,W) net convention
        self.rand_crop = 0
        self.rand_mirror = 0
        self.mirror = 0
        self.crop_y_start = -1
        self.crop_x_start = -1
        self.scale = 1.0
        self.silent = 0
        self.name_meanimg = ""
        self.mean_value: Optional[np.ndarray] = None  # per-channel, RGB order
        self.max_random_contrast = 0.0
        self.max_random_illumination = 0.0
        # affine params (image_augmenter)
        self.max_rotate_angle = 0.0
        self.max_shear_ratio = 0.0
        self.max_aspect_ratio = 0.0
        self.min_crop_size = -1
        self.max_crop_size = -1
        self.rotate = -1.0
        self.rotate_list: List[int] = []
        self.min_random_scale = 1.0
        self.max_random_scale = 1.0
        self.min_img_size = 0.0
        self.max_img_size = 1e10
        self.fill_value = 255
        self._rng = np.random.RandomState(_RAND_MAGIC)
        self._meanimg: Optional[np.ndarray] = None
        self._out: Optional[DataInst] = None

    def set_param(self, name, val):
        self.base.set_param(name, val)
        if name == "input_shape":
            c, h, w = (int(t) for t in val.split(","))
            self.shape = (c, h, w)
        elif name == "seed_data":
            self._rng = np.random.RandomState(_RAND_MAGIC + int(val))
        elif name == "rand_crop":
            self.rand_crop = int(val)
        elif name == "rand_mirror":
            self.rand_mirror = int(val)
        elif name == "mirror":
            self.mirror = int(val)
        elif name == "crop_y_start":
            self.crop_y_start = int(val)
        elif name == "crop_x_start":
            self.crop_x_start = int(val)
        elif name == "divideby":
            self.scale = 1.0 / float(val)
        elif name == "scale":
            self.scale = float(val)
        elif name == "image_mean":
            self.name_meanimg = val
        elif name == "mean_value":
            b, g, r = (float(t) for t in val.split(","))
            self.mean_value = np.asarray([r, g, b], np.float32)  # RGB order
        elif name == "max_random_contrast":
            self.max_random_contrast = float(val)
        elif name == "max_random_illumination":
            self.max_random_illumination = float(val)
        elif name == "max_rotate_angle":
            self.max_rotate_angle = float(val)
        elif name == "max_shear_ratio":
            self.max_shear_ratio = float(val)
        elif name == "max_aspect_ratio":
            self.max_aspect_ratio = float(val)
        elif name == "min_crop_size":
            self.min_crop_size = int(val)
        elif name == "max_crop_size":
            self.max_crop_size = int(val)
        elif name == "rotate":
            self.rotate = float(val)
        elif name == "rotate_list":
            self.rotate_list = [int(t) for t in val.replace(",", " ").split()]
        elif name == "min_random_scale":
            self.min_random_scale = float(val)
        elif name == "max_random_scale":
            self.max_random_scale = float(val)
        elif name == "min_img_size":
            self.min_img_size = float(val)
        elif name == "max_img_size":
            self.max_img_size = float(val)
        elif name == "fill_value":
            self.fill_value = int(val)
        elif name == "silent":
            self.silent = int(val)

    # ------------------------------------------------------------------
    def init(self):
        self.base.init()
        if self.name_meanimg:
            if os.path.exists(self.name_meanimg):
                with np.load(self.name_meanimg) as z:
                    self._meanimg = z["mean"]
                if not self.silent:
                    print(f"loading mean image from {self.name_meanimg}")
            else:
                self._create_mean_img()

    def _create_mean_img(self):
        if not self.silent:
            print(f"cannot find {self.name_meanimg}: creating mean image...")
        total, cnt = None, 0
        self.base.before_first()
        while self.base.next():
            d = self._augmented(self.base.value(), apply_mean=False)
            total = d.data.astype(np.float64) if total is None else total + d.data
            cnt += 1
        if total is None:
            raise ValueError("AugmentIterator: empty input, cannot build mean image")
        self._meanimg = (total / cnt).astype(np.float32)
        np.savez(self.name_meanimg, mean=self._meanimg)
        if not self.silent:
            print(f"saved mean image to {self.name_meanimg} ({cnt} images)")
        self.base.before_first()

    def before_first(self):
        self.base.before_first()

    def next(self) -> bool:
        if not self.base.next():
            return False
        self._out = self._augmented(self.base.value(), apply_mean=True)
        return True

    def value(self) -> DataInst:
        assert self._out is not None
        return self._out

    def close(self) -> None:
        self.base.close()

    # ------------------------------------------------------------------
    def _affine(self, img: np.ndarray) -> np.ndarray:
        """Rotation/shear/scale/aspect as one warp (image_augmenter:75-123)."""
        if (
            self.max_rotate_angle <= 0
            and self.max_shear_ratio <= 0
            and self.max_aspect_ratio <= 0
            and self.rotate < 0
            and not self.rotate_list
            and self.min_random_scale == 1.0
            and self.max_random_scale == 1.0
            and self.min_crop_size <= 0
        ):
            return img
        from PIL import Image

        rng = self._rng
        angle = 0.0
        if self.max_rotate_angle > 0:
            angle = rng.uniform(-self.max_rotate_angle, self.max_rotate_angle)
        if self.rotate > 0:
            angle = self.rotate
        if self.rotate_list:
            angle = float(self.rotate_list[rng.randint(len(self.rotate_list))])
        s = rng.uniform(-self.max_shear_ratio, self.max_shear_ratio) if self.max_shear_ratio > 0 else 0.0
        scale = rng.uniform(self.min_random_scale, self.max_random_scale)
        ratio = rng.uniform(-self.max_aspect_ratio, self.max_aspect_ratio) + 1.0 if self.max_aspect_ratio > 0 else 1.0
        hs = 2.0 * scale / (1.0 + ratio)
        ws = ratio * hs
        a = math.cos(math.radians(angle))
        b = math.sin(math.radians(angle))
        h, w = img.shape[:2]
        # forward warp matrix, exact parity with the reference
        # (image_augmenter-inl.hpp:96-104): dst = M @ (src_x, src_y) + t,
        # centered in a (new_w, new_h) = scale-clamped output canvas
        m00 = hs * a - s * b * ws
        m01 = hs * b + s * a * ws
        m10 = -b * ws
        m11 = a * ws
        new_w = int(round(max(self.min_img_size, min(self.max_img_size, scale * w))))
        new_h = int(round(max(self.min_img_size, min(self.max_img_size, scale * h))))
        tx = (new_w - (m00 * w + m01 * h)) / 2.0
        ty = (new_h - (m10 * w + m11 * h)) / 2.0
        det = m00 * m11 - m01 * m10
        if abs(det) < 1e-8:
            return img
        # PIL wants the inverse map (output coords → input coords)
        i00, i01 = m11 / det, -m01 / det
        i10, i11 = -m10 / det, m00 / det
        coeffs = (
            i00, i01, -(i00 * tx + i01 * ty),
            i10, i11, -(i10 * tx + i11 * ty),
        )
        mode = "F" if img.ndim == 2 or img.shape[2] == 1 else "RGB"
        if mode == "RGB":
            pim = Image.fromarray(np.clip(img, 0, 255).astype(np.uint8), "RGB")
        else:
            pim = Image.fromarray(img.reshape(h, w).astype(np.float32), "F")
        pim = pim.transform(
            (new_w, new_h), Image.AFFINE, coeffs,
            resample=Image.BILINEAR, fillcolor=self.fill_value,
        )
        out = np.asarray(pim, np.float32)
        if out.ndim == 2:
            out = out[..., None]
        # random crop-size: crop a random square then resize back (bowl.conf)
        if self.min_crop_size > 0 and self.max_crop_size >= self.min_crop_size:
            cs = rng.randint(self.min_crop_size, self.max_crop_size + 1)
            cs = min(cs, out.shape[0], out.shape[1])
            yy = rng.randint(out.shape[0] - cs + 1)
            xx = rng.randint(out.shape[1] - cs + 1)
            patch = out[yy : yy + cs, xx : xx + cs]
            if mode == "RGB":
                pim2 = Image.fromarray(np.clip(patch, 0, 255).astype(np.uint8), "RGB")
                pim2 = pim2.resize((w, h), Image.BILINEAR)
                out = np.asarray(pim2, np.float32)
            else:
                pim2 = Image.fromarray(patch.reshape(cs, cs), "F").resize((w, h), Image.BILINEAR)
                out = np.asarray(pim2, np.float32)[..., None]
        return out

    def _augmented(self, d: DataInst, *, apply_mean: bool) -> DataInst:
        """SetData parity (iter_augment_proc-inl.hpp:98-162), HWC layout."""
        c, th, tw = self.shape
        data = d.data.astype(np.float32)
        if c == 1 and th == 1:
            return DataInst(d.index, data.reshape(-1) * self.scale, d.label)
        if data.ndim == 2:
            data = data[..., None]
        data = self._affine(data)
        rng = self._rng
        h, w = data.shape[:2]
        if h < th or w < tw:
            raise ValueError("data size must be at least the net input size")
        yy_max, xx_max = h - th, w - tw
        if self.rand_crop and (yy_max or xx_max):
            yy = rng.randint(yy_max + 1)
            xx = rng.randint(xx_max + 1)
        else:
            yy, xx = yy_max // 2, xx_max // 2
        if h != th and self.crop_y_start != -1:
            yy = self.crop_y_start
        if w != tw and self.crop_x_start != -1:
            xx = self.crop_x_start
        contrast = 1.0
        illumination = 0.0
        if self.max_random_contrast > 0:
            contrast = rng.uniform(1 - self.max_random_contrast, 1 + self.max_random_contrast)
        if self.max_random_illumination > 0:
            illumination = rng.uniform(
                -self.max_random_illumination, self.max_random_illumination
            )
        do_mirror = self.mirror == 1 or (self.rand_mirror and rng.rand() < 0.5)

        if apply_mean and self.mean_value is not None:
            data = data - self.mean_value[: data.shape[2]]
            img = data[yy : yy + th, xx : xx + tw] * contrast + illumination
        elif apply_mean and self._meanimg is not None:
            if self._meanimg.shape == data.shape:
                data = data - self._meanimg
                img = data[yy : yy + th, xx : xx + tw] * contrast + illumination
            else:
                img = data[yy : yy + th, xx : xx + tw]
                if self._meanimg.shape == img.shape:
                    img = img - self._meanimg
                img = img * contrast + illumination
        else:
            img = data[yy : yy + th, xx : xx + tw]
        if do_mirror:
            img = img[:, ::-1]
        return DataInst(d.index, np.ascontiguousarray(img) * self.scale, d.label)
