"""Per-instance augmentation: crop, mirror, mean subtraction, jitter, affine.

Parity: ``/root/reference/src/io/iter_augment_proc-inl.hpp`` (crop /
mirror / mean-image-or-value / contrast / illumination / scale, and the
first-run mean-image computation cached to ``image_mean``) plus
``/root/reference/src/io/image_augmenter-inl.hpp`` (rotation, shear,
aspect-ratio and scale jitter folded into a single affine warp, random
crop-size ranges, rotate lists).  The affine warp here uses PIL instead of
OpenCV ``warpAffine``; the parameter names and ranges are identical.

Channel-order note: the reference decodes with OpenCV (BGR) and parses
``mean_value = b,g,r``; this framework stores RGB, and ``mean_value`` is
applied in the file order to channels ``(2, 1, 0)`` so the same config
subtracts the same per-channel values.

Determinism contract (doc/performance.md "Host input pipeline"): every
random draw for a record comes from a private ``RandomState`` seeded by
``(seed_data, epoch, record index)`` — there is NO shared mutable RNG.
The augmentation stream therefore depends only on the record sequence,
never on decode worker count, buffer depth, chunking, or where within
an epoch a run was resumed: serial and parallel pipelines produce
bitwise-identical batches (``tests/test_host_pipeline.py``).

The no-affine common case additionally has a whole-batch vectorized
fast path (:meth:`AugmentIterator.augment_batch`): crop / mirror /
mean-subtract / contrast / illumination / scale as batch-level numpy
ops over a uniform ``(N, H, W, C)`` stack, bitwise-identical to the
per-record path.  The parallel decode pool (``io/pipeline.py``) and
the first-run mean-image pass both run through it.
"""

from __future__ import annotations

import math
import os
import time
from typing import List, Optional, Sequence

import numpy as np

from ..utils.profiler import pipeline_stats
from .batch import DataInst, InstIterator

_RAND_MAGIC = 111

#: epoch index for draws made outside the training epoch sequence (the
#: first-run mean-image pass).  Training epochs start at 1 (the first
#: ``before_first`` of the chain), so 0 never collides.
MEAN_PASS_EPOCH = 0

_M64 = (1 << 64) - 1
_SLOT_ODD = 0x9E3779B97F4A7C15  # golden-ratio odd constant


def _splitmix64(z: int) -> int:
    """SplitMix64 finalizer (python-int form, exact 64-bit wrap)."""
    z = (z + 0x9E3779B97F4A7C15) & _M64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
    return z ^ (z >> 31)


def _splitmix64_vec(z: np.ndarray) -> np.ndarray:
    """SplitMix64 over a uint64 array (wrapping arithmetic)."""
    z = (z + np.uint64(0x9E3779B97F4A7C15))
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def record_key(seed_base: int, epoch: int, index: int) -> int:
    """The record's 64-bit RNG key: a SplitMix64 chain over
    ``(seed_data, epoch, record index)``."""
    h = _splitmix64(seed_base & _M64)
    h = _splitmix64(h ^ (epoch & _M64))
    return _splitmix64(h ^ (index & _M64))


def record_key_vec(seed_base: int, epoch: int,
                   indices: np.ndarray) -> np.ndarray:
    """Vectorized :func:`record_key` over an index array."""
    h = _splitmix64(seed_base & _M64)
    h = _splitmix64(h ^ (epoch & _M64))
    return _splitmix64_vec(np.uint64(h) ^ indices.astype(np.uint64))


def _slot_hash_vec(keys: np.ndarray, slot: int) -> np.ndarray:
    return _splitmix64_vec(keys ^ np.uint64((slot * _SLOT_ODD) & _M64))


def _u53(h) -> np.ndarray:
    """uint64 hash → uniform float64 in [0, 1) (53 mantissa bits)."""
    return (h >> np.uint64(11)) * (1.0 / (1 << 53))


# Fixed draw-slot assignments: every random decision of a record has a
# NAMED slot, so any pipeline stage — serial loop, vectorized batch,
# PIL-side decode worker, consumer-side float tail — can (re)compute
# exactly the draw it needs from ``(seed_data, epoch, index, slot)``
# without any other stage having run first.
S_CROP_Y = 0
S_CROP_X = 1
S_CONTRAST = 2
S_ILLUM = 3
S_MIRROR = 4
S_AFF_ANGLE = 8
S_AFF_ROTPICK = 9
S_AFF_SHEAR = 10
S_AFF_SCALE = 11
S_AFF_RATIO = 12
S_AFF_CSIZE = 13
S_AFF_CS_Y = 14
S_AFF_CS_X = 15


class RecordRNG:
    """Stateless per-record RNG: draw ``slot`` of record ``r`` is a pure
    hash of ``(seed_data, epoch, record index, slot)`` — no shared or
    sequential state, ~1 µs per draw (a seeded ``RandomState``/
    ``Philox`` object costs 30-150 µs to CONSTRUCT, which at JPEG-decode
    rates was itself a pipeline stage).  Slot draws vectorize exactly
    (:func:`_slot_hash_vec`), and fixed slot numbers mean different
    pipeline stages can recompute each other's draws independently."""

    __slots__ = ("key",)

    def __init__(self, key: int) -> None:
        self.key = key

    def _hash(self, slot: int) -> int:
        return _splitmix64(self.key ^ ((slot * _SLOT_ODD) & _M64))

    def rand(self, slot: int) -> float:
        """Uniform float64 in [0, 1)."""
        return (self._hash(slot) >> 11) * (1.0 / (1 << 53))

    def uniform(self, slot: int, lo: float = 0.0, hi: float = 1.0) -> float:
        return lo + (hi - lo) * self.rand(slot)

    def randint(self, slot: int, lo: int, hi: Optional[int] = None) -> int:
        """Integer in [lo, hi) (or [0, lo) with one argument) — modulo
        reduction; the negligible bias is part of the defined stream."""
        if hi is None:
            lo, hi = 0, lo
        return lo + self._hash(slot) % (hi - lo)


class AugmentIterator(InstIterator):
    def supports_dist_shard(self) -> bool:
        return self.base.supports_dist_shard()

    def __init__(self, base: InstIterator) -> None:
        self.base = base
        self.shape = (0, 0, 0)           # (C,H,W) net convention
        self.rand_crop = 0
        self.rand_mirror = 0
        self.mirror = 0
        self.crop_y_start = -1
        self.crop_x_start = -1
        self.scale = 1.0
        self.silent = 0
        self.name_meanimg = ""
        self.mean_value: Optional[np.ndarray] = None  # per-channel, RGB order
        self.max_random_contrast = 0.0
        self.max_random_illumination = 0.0
        # affine params (image_augmenter)
        self.max_rotate_angle = 0.0
        self.max_shear_ratio = 0.0
        self.max_aspect_ratio = 0.0
        self.min_crop_size = -1
        self.max_crop_size = -1
        self.rotate = -1.0
        self.rotate_list: List[int] = []
        self.min_random_scale = 1.0
        self.max_random_scale = 1.0
        self.min_img_size = 0.0
        self.max_img_size = 1e10
        self.fill_value = 255
        self._seed_base = _RAND_MAGIC
        self._epoch = 0          # bumped by every before_first()
        self._meanimg: Optional[np.ndarray] = None
        self._out: Optional[DataInst] = None

    def set_param(self, name, val):
        self.base.set_param(name, val)
        if name == "input_shape":
            c, h, w = (int(t) for t in val.split(","))
            self.shape = (c, h, w)
        elif name == "seed_data":
            self._seed_base = (_RAND_MAGIC + int(val)) & 0xFFFFFFFF
        elif name == "augment_epoch":
            # absolute epoch anchor: the task driver re-issues this
            # AFTER each round's before_first() with the ROUND counter,
            # so a preemption resume at round r draws the exact same
            # augmentation stream as an uninterrupted run's round r —
            # epochs are then a property of training progress, not of
            # how many times this process happened to rewind
            self._epoch = int(val)
        elif name == "rand_crop":
            self.rand_crop = int(val)
        elif name == "rand_mirror":
            self.rand_mirror = int(val)
        elif name == "mirror":
            self.mirror = int(val)
        elif name == "crop_y_start":
            self.crop_y_start = int(val)
        elif name == "crop_x_start":
            self.crop_x_start = int(val)
        elif name == "divideby":
            self.scale = 1.0 / float(val)
        elif name == "scale":
            self.scale = float(val)
        elif name == "image_mean":
            self.name_meanimg = val
        elif name == "mean_value":
            b, g, r = (float(t) for t in val.split(","))
            self.mean_value = np.asarray([r, g, b], np.float32)  # RGB order
        elif name == "max_random_contrast":
            self.max_random_contrast = float(val)
        elif name == "max_random_illumination":
            self.max_random_illumination = float(val)
        elif name == "max_rotate_angle":
            self.max_rotate_angle = float(val)
        elif name == "max_shear_ratio":
            self.max_shear_ratio = float(val)
        elif name == "max_aspect_ratio":
            self.max_aspect_ratio = float(val)
        elif name == "min_crop_size":
            self.min_crop_size = int(val)
        elif name == "max_crop_size":
            self.max_crop_size = int(val)
        elif name == "rotate":
            self.rotate = float(val)
        elif name == "rotate_list":
            self.rotate_list = [int(t) for t in val.replace(",", " ").split()]
        elif name == "min_random_scale":
            self.min_random_scale = float(val)
        elif name == "max_random_scale":
            self.max_random_scale = float(val)
        elif name == "min_img_size":
            self.min_img_size = float(val)
        elif name == "max_img_size":
            self.max_img_size = float(val)
        elif name == "fill_value":
            self.fill_value = int(val)
        elif name == "silent":
            self.silent = int(val)

    # ------------------------------------------------------------------
    # deterministic per-record RNG
    @property
    def epoch(self) -> int:
        """Current epoch index (count of ``before_first`` calls)."""
        return self._epoch

    def record_rng(self, epoch: int, index: int) -> RecordRNG:
        """The record's private RNG: keyed by ``(seed_data, epoch,
        record index)``, so the same record in the same epoch draws the
        same augmentation no matter which worker processes it, in what
        order, or whether the epoch was restarted mid-way."""
        return RecordRNG(record_key(self._seed_base, epoch, index))

    def _affine_active(self) -> bool:
        """True when :meth:`_affine` would do work (and draw from the
        record RNG) — the inverse of its early-return condition."""
        return not (
            self.max_rotate_angle <= 0
            and self.max_shear_ratio <= 0
            and self.max_aspect_ratio <= 0
            and self.rotate < 0
            and not self.rotate_list
            and self.min_random_scale == 1.0
            and self.max_random_scale == 1.0
            and self.min_crop_size <= 0
        )

    def _stochastic(self) -> bool:
        """Does augmenting a record consume any random draw?"""
        return (
            self._affine_active()
            or bool(self.rand_crop)
            or bool(self.rand_mirror)
            or self.max_random_contrast > 0
            or self.max_random_illumination > 0
        )

    def vectorizable(self) -> bool:
        """True when the whole-batch fast path applies: no affine warp
        (everything else — crop / mirror / mean / contrast /
        illumination / scale — vectorizes exactly)."""
        return not self._affine_active()

    # ------------------------------------------------------------------
    def init(self):
        self.base.init()
        if self.name_meanimg:
            if os.path.exists(self.name_meanimg):
                with np.load(self.name_meanimg) as z:
                    self._meanimg = z["mean"]
                if not self.silent:
                    print(f"loading mean image from {self.name_meanimg}")
            else:
                self._create_mean_img()

    def _create_mean_img(self):
        """First-run mean image, computed through the vectorized batch
        path in ONE pre-pool pass (chunks of decoded records are
        augmented as a stack), so ``image_mean`` creation does not
        serialize the first epoch record by record.  The per-record
        float64 accumulation order matches the legacy serial loop."""
        if not self.silent:
            print(f"cannot find {self.name_meanimg}: creating mean image...")
        total, cnt = None, 0
        chunk = 64
        self.base.before_first()
        more = True
        while more:
            insts: List[DataInst] = []
            while len(insts) < chunk:
                if not self.base.next():
                    more = False
                    break
                insts.append(self.base.value())
            if not insts:
                break
            for d in self.augment_insts(insts, MEAN_PASS_EPOCH,
                                        apply_mean=False):
                total = (d.data.astype(np.float64) if total is None
                         else total + d.data)
                cnt += 1
        if total is None:
            raise ValueError("AugmentIterator: empty input, cannot build mean image")
        self._meanimg = (total / cnt).astype(np.float32)
        np.savez(self.name_meanimg, mean=self._meanimg)
        if not self.silent:
            print(f"saved mean image to {self.name_meanimg} ({cnt} images)")
        self.base.before_first()

    def before_first(self):
        self._epoch += 1
        self.base.before_first()

    def next(self) -> bool:
        if not self.base.next():
            return False
        d = self.base.value()
        t0 = time.perf_counter()
        rng = (self.record_rng(self._epoch, d.index)
               if self._stochastic() else None)
        self._out = self._augmented(d, apply_mean=True, rng=rng)
        pipeline_stats().add("augment", time.perf_counter() - t0)
        return True

    def value(self) -> DataInst:
        assert self._out is not None
        return self._out

    def close(self) -> None:
        self.base.close()

    # ------------------------------------------------------------------
    def _affine(self, img: np.ndarray, rng) -> np.ndarray:
        """Rotation/shear/scale/aspect as one warp (image_augmenter:75-123)."""
        if not self._affine_active():
            return img
        from PIL import Image

        angle = 0.0
        if self.max_rotate_angle > 0:
            angle = rng.uniform(S_AFF_ANGLE, -self.max_rotate_angle,
                                self.max_rotate_angle)
        if self.rotate > 0:
            angle = self.rotate
        if self.rotate_list:
            angle = float(self.rotate_list[
                rng.randint(S_AFF_ROTPICK, len(self.rotate_list))])
        s = (rng.uniform(S_AFF_SHEAR, -self.max_shear_ratio,
                         self.max_shear_ratio)
             if self.max_shear_ratio > 0 else 0.0)
        scale = rng.uniform(S_AFF_SCALE, self.min_random_scale,
                            self.max_random_scale)
        ratio = (rng.uniform(S_AFF_RATIO, -self.max_aspect_ratio,
                             self.max_aspect_ratio) + 1.0
                 if self.max_aspect_ratio > 0 else 1.0)
        hs = 2.0 * scale / (1.0 + ratio)
        ws = ratio * hs
        a = math.cos(math.radians(angle))
        b = math.sin(math.radians(angle))
        h, w = img.shape[:2]
        # forward warp matrix, exact parity with the reference
        # (image_augmenter-inl.hpp:96-104): dst = M @ (src_x, src_y) + t,
        # centered in a (new_w, new_h) = scale-clamped output canvas
        m00 = hs * a - s * b * ws
        m01 = hs * b + s * a * ws
        m10 = -b * ws
        m11 = a * ws
        new_w = int(round(max(self.min_img_size, min(self.max_img_size, scale * w))))
        new_h = int(round(max(self.min_img_size, min(self.max_img_size, scale * h))))
        tx = (new_w - (m00 * w + m01 * h)) / 2.0
        ty = (new_h - (m10 * w + m11 * h)) / 2.0
        det = m00 * m11 - m01 * m10
        if abs(det) < 1e-8:
            return img
        # PIL wants the inverse map (output coords → input coords)
        i00, i01 = m11 / det, -m01 / det
        i10, i11 = -m10 / det, m00 / det
        coeffs = (
            i00, i01, -(i00 * tx + i01 * ty),
            i10, i11, -(i10 * tx + i11 * ty),
        )
        mode = "F" if img.ndim == 2 or img.shape[2] == 1 else "RGB"
        if mode == "RGB":
            pim = Image.fromarray(np.clip(img, 0, 255).astype(np.uint8), "RGB")
        else:
            pim = Image.fromarray(img.reshape(h, w).astype(np.float32), "F")
        pim = pim.transform(
            (new_w, new_h), Image.AFFINE, coeffs,
            resample=Image.BILINEAR, fillcolor=self.fill_value,
        )
        out = np.asarray(pim, np.float32)
        if out.ndim == 2:
            out = out[..., None]
        # random crop-size: crop a random square then resize back (bowl.conf)
        if self.min_crop_size > 0 and self.max_crop_size >= self.min_crop_size:
            cs = rng.randint(S_AFF_CSIZE, self.min_crop_size,
                             self.max_crop_size + 1)
            cs = min(cs, out.shape[0], out.shape[1])
            yy = rng.randint(S_AFF_CS_Y, out.shape[0] - cs + 1)
            xx = rng.randint(S_AFF_CS_X, out.shape[1] - cs + 1)
            patch = out[yy : yy + cs, xx : xx + cs]
            if mode == "RGB":
                pim2 = Image.fromarray(np.clip(patch, 0, 255).astype(np.uint8), "RGB")
                pim2 = pim2.resize((w, h), Image.BILINEAR)
                out = np.asarray(pim2, np.float32)
            else:
                pim2 = Image.fromarray(patch.reshape(cs, cs), "F").resize((w, h), Image.BILINEAR)
                out = np.asarray(pim2, np.float32)[..., None]
        return out

    def _augmented(self, d: DataInst, *, apply_mean: bool,
                   rng=None) -> DataInst:
        """SetData parity (iter_augment_proc-inl.hpp:98-162), HWC layout.

        ``rng`` is the record's private RandomState (None when no random
        augmentation is armed — no draw then happens)."""
        c, th, tw = self.shape
        data = d.data.astype(np.float32)
        if c == 1 and th == 1:
            return DataInst(d.index, data.reshape(-1) * self.scale, d.label)
        if data.ndim == 2:
            data = data[..., None]
        data = self._affine(data, rng)
        h, w = data.shape[:2]
        if h < th or w < tw:
            raise ValueError("data size must be at least the net input size")
        yy_max, xx_max = h - th, w - tw
        if self.rand_crop and (yy_max or xx_max):
            yy = rng.randint(S_CROP_Y, yy_max + 1)
            xx = rng.randint(S_CROP_X, xx_max + 1)
        else:
            yy, xx = yy_max // 2, xx_max // 2
        if h != th and self.crop_y_start != -1:
            yy = self.crop_y_start
        if w != tw and self.crop_x_start != -1:
            xx = self.crop_x_start
        contrast = 1.0
        illumination = 0.0
        if self.max_random_contrast > 0:
            contrast = rng.uniform(S_CONTRAST, 1 - self.max_random_contrast,
                                   1 + self.max_random_contrast)
        if self.max_random_illumination > 0:
            illumination = rng.uniform(
                S_ILLUM, -self.max_random_illumination,
                self.max_random_illumination,
            )
        do_mirror = self.mirror == 1 or (
            self.rand_mirror and rng.rand(S_MIRROR) < 0.5)

        if apply_mean and self.mean_value is not None:
            data = data - self.mean_value[: data.shape[2]]
            img = data[yy : yy + th, xx : xx + tw] * contrast + illumination
        elif apply_mean and self._meanimg is not None:
            if self._meanimg.shape == data.shape:
                data = data - self._meanimg
                img = data[yy : yy + th, xx : xx + tw] * contrast + illumination
            else:
                img = data[yy : yy + th, xx : xx + tw]
                if self._meanimg.shape == img.shape:
                    img = img - self._meanimg
                img = img * contrast + illumination
        else:
            img = data[yy : yy + th, xx : xx + tw]
        if do_mirror:
            img = img[:, ::-1]
        return DataInst(d.index, np.ascontiguousarray(img) * self.scale, d.label)

    # ------------------------------------------------------------------
    # whole-batch vectorized fast path
    def augment_insts(self, insts: Sequence[DataInst], epoch: int, *,
                      apply_mean: bool = True) -> List[DataInst]:
        """Augment a window of records, vectorized when possible.

        Uses :meth:`augment_batch` when no affine warp is armed and the
        decoded images share one shape; falls back to the per-record
        path otherwise.  Either way the output is bitwise-identical to
        calling :meth:`_augmented` record by record — the random draws
        come from the same per-record RNGs."""
        if not insts:
            return []
        c, th, tw = self.shape
        flat = c == 1 and th == 1
        shapes = {tuple(d.data.shape) for d in insts}
        if (not flat and len(shapes) == 1 and self.vectorizable()
                and len(next(iter(shapes))) >= 2):
            # native dtype (uint8 from the decoder): float32 conversion
            # happens during the crop copy — exact, 4x less bandwidth
            stack = np.stack([
                d.data if d.data.ndim == 3 else d.data[..., None]
                for d in insts
            ])
            out = self.augment_batch(
                stack, [d.index for d in insts], epoch,
                apply_mean=apply_mean,
            )
            return [DataInst(d.index, out[i], d.label)
                    for i, d in enumerate(insts)]
        out_insts = []
        for d in insts:
            rng = (self.record_rng(epoch, d.index)
                   if self._stochastic() else None)
            out_insts.append(self._augmented(d, apply_mean=apply_mean,
                                             rng=rng))
        return out_insts

    def augment_batch(self, stack: np.ndarray, indices: Sequence[int],
                      epoch: int, *, apply_mean: bool = True) -> np.ndarray:
        """Vectorized ``_augmented`` over a uniform ``(N, H, W, C)``
        stack (uint8 or float32) — the no-affine fast path: crop,
        mirror, mean-subtract, contrast, illumination and scale as
        batch-level numpy ops, float32 out.  Bitwise-identical to the
        per-record path: the draws come from the same per-record slot
        hashes (vectorized here), uint8→float32 conversion is exact on
        either side of the crop, and every float op is the same
        elementwise float32 operation in the same order."""
        assert self.vectorizable(), "affine warp has no batch path"
        n, h, w, cdim = stack.shape
        _, th, tw = self.shape
        if h < th or w < tw:
            raise ValueError("data size must be at least the net input size")
        yy_max, xx_max = h - th, w - tw
        yy = np.full(n, yy_max // 2, np.intp)
        xx = np.full(n, xx_max // 2, np.intp)
        contrast = None
        illum = None
        do_mirror = np.full(n, self.mirror == 1)
        # per-record fixed-slot draws, vectorized — the same hashes the
        # per-record RecordRNG computes in _augmented
        if self._stochastic():
            keys = record_key_vec(
                self._seed_base, epoch,
                np.asarray(indices, np.int64).astype(np.uint64),
            )
            if self.rand_crop and (yy_max or xx_max):
                yy = (_slot_hash_vec(keys, S_CROP_Y)
                      % np.uint64(yy_max + 1)).astype(np.intp)
                xx = (_slot_hash_vec(keys, S_CROP_X)
                      % np.uint64(xx_max + 1)).astype(np.intp)
            if self.max_random_contrast > 0:
                lo, hi = (1 - self.max_random_contrast,
                          1 + self.max_random_contrast)
                contrast = lo + (hi - lo) * _u53(
                    _slot_hash_vec(keys, S_CONTRAST))
            if self.max_random_illumination > 0:
                lo, hi = (-self.max_random_illumination,
                          self.max_random_illumination)
                illum = lo + (hi - lo) * _u53(_slot_hash_vec(keys, S_ILLUM))
            if self.mirror != 1 and self.rand_mirror:
                do_mirror = _u53(_slot_hash_vec(keys, S_MIRROR)) < 0.5
        if h != th and self.crop_y_start != -1:
            yy[:] = self.crop_y_start
        if w != tw and self.crop_x_start != -1:
            xx[:] = self.crop_x_start

        # crop + mirror in ONE cast-copy per record: the mirrored
        # records read their window with a reversed W stride, so the
        # uint8→float32 conversion, the crop copy, and the flip are a
        # single pass (an in-place ``out[m] = out[m, :, ::-1]`` is ~6x
        # slower — overlapping-buffer reversal takes numpy's buffered
        # path).  Mirroring commutes with every elementwise op below,
        # so doing it first is bitwise-identical to the per-record
        # order (jitter, then flip).
        out = np.empty((n, th, tw, cdim), np.float32)
        for i in range(n):
            win = stack[i, yy[i]: yy[i] + th, xx[i]: xx[i] + tw]
            out[i] = win[:, ::-1] if do_mirror[i] else win

        jitter = False
        if apply_mean and self.mean_value is not None:
            out -= self.mean_value[:cdim]  # per-channel: flip-invariant
            jitter = True
        elif apply_mean and self._meanimg is not None:
            if self._meanimg.shape == stack.shape[1:]:
                # mean is full-size: subtract each record's crop
                # window, mirrored along with the record
                for i in range(n):
                    mwin = self._meanimg[yy[i]: yy[i] + th,
                                         xx[i]: xx[i] + tw]
                    out[i] -= mwin[:, ::-1] if do_mirror[i] else mwin
            elif self._meanimg.shape == out.shape[1:]:
                for i in range(n):
                    out[i] -= (self._meanimg[:, ::-1] if do_mirror[i]
                               else self._meanimg)
            jitter = True
        if jitter:
            # float32-cast per-record scalars: elementwise identical to
            # the serial path's python-float (weak-promotion) arithmetic
            if contrast is not None:
                out *= contrast.astype(np.float32)[:, None, None, None]
            if illum is not None:
                out += illum.astype(np.float32)[:, None, None, None]
        if self.scale != 1.0:  # x * 1.0 is a bitwise identity
            out *= np.float32(self.scale)
        return out

    # ------------------------------------------------------------------
    # split decode-worker fast path: PIL-side crop+mirror, float tail
    # on the consumer (io/pipeline.py).  Rationale: a decode worker that
    # only runs PIL C ops (decode, crop, flip — all GIL-releasing) and
    # hands back the small uint8 window scales across cores; the float32
    # arithmetic runs once, vectorized, on the consumer thread.
    def pil_path_ok(self, apply_mean: bool = True) -> bool:
        """Can a decode worker run :meth:`augment_pil`?  Static per
        config: no affine warp, a real 2-D crop target, and no
        full-image mean (its subtract window needs the pre-crop image
        size, which the split path no longer has)."""
        c, th, tw = self.shape
        if c == 1 and th == 1:
            return False  # flat vectors never touch PIL
        if not self.vectorizable():
            return False
        if (apply_mean and self._meanimg is not None
                and self._meanimg.shape != (th, tw, c)):
            return False
        return True

    def tail_identity(self, apply_mean: bool = True) -> bool:
        """True when the post-crop float tail does nothing: the uint8
        crop IS the augmented record (the batch collator's store-cast
        to float32 is exact), so nobody pays for a float pass."""
        return (self.scale == 1.0
                and self.max_random_contrast <= 0
                and self.max_random_illumination <= 0
                and not (apply_mean and (self.mean_value is not None
                                         or self._meanimg is not None)))

    def augment_pil(self, im, index: int, labels, epoch: int) -> DataInst:
        """Worker half of the split path: crop + mirror as PIL C-level
        ops on the decoded uint8 image (bit-exact vs numpy slicing),
        returning a uint8 ``DataInst``.  Run :meth:`augment_tail` on
        the result unless :meth:`tail_identity`."""
        from PIL import Image

        _, th, tw = self.shape
        w, h = im.size
        if h < th or w < tw:
            raise ValueError("data size must be at least the net input size")
        rng = (self.record_rng(epoch, index) if self._stochastic() else None)
        yy_max, xx_max = h - th, w - tw
        if self.rand_crop and (yy_max or xx_max):
            yy = rng.randint(S_CROP_Y, yy_max + 1)
            xx = rng.randint(S_CROP_X, xx_max + 1)
        else:
            yy, xx = yy_max // 2, xx_max // 2
        if h != th and self.crop_y_start != -1:
            yy = self.crop_y_start
        if w != tw and self.crop_x_start != -1:
            xx = self.crop_x_start
        do_mirror = self.mirror == 1 or (
            self.rand_mirror and rng.rand(S_MIRROR) < 0.5)
        if (yy, xx) != (0, 0) or (h, w) != (th, tw):
            im = im.crop((xx, yy, xx + tw, yy + th))
        if do_mirror:
            im = im.transpose(Image.FLIP_LEFT_RIGHT)
        return DataInst(index, np.asarray(im), labels)

    def augment_tail(self, insts: Sequence[DataInst], epoch: int, *,
                     apply_mean: bool = True) -> List[DataInst]:
        """Consumer half of the split path: the float32 tail
        (mean-subtract, contrast/illumination, scale) vectorized over
        the uniform uint8 crops :meth:`augment_pil` produced.  Bitwise
        equal to the serial ``_augmented`` tail: the crops are already
        mirrored, and every tail op commutes with the flip (the
        crop-sized mean window is flipped to compensate)."""
        if not insts or self.tail_identity(apply_mean):
            return list(insts)
        n = len(insts)
        out = np.stack([d.data for d in insts]).astype(np.float32)
        jitter = False
        if apply_mean and self.mean_value is not None:
            out -= self.mean_value[: out.shape[3]]
            jitter = True
        elif apply_mean and self._meanimg is not None:
            if self._meanimg.shape == out.shape[1:]:
                if self.mirror == 1 or self.rand_mirror:
                    keys = record_key_vec(
                        self._seed_base, epoch,
                        np.asarray([d.index for d in insts],
                                   np.int64).astype(np.uint64),
                    )
                    mirrored = (np.full(n, True) if self.mirror == 1
                                else _u53(_slot_hash_vec(keys, S_MIRROR))
                                < 0.5)
                    for i in range(n):
                        out[i] -= (self._meanimg[:, ::-1] if mirrored[i]
                                   else self._meanimg)
                else:
                    out -= self._meanimg
            jitter = True
        if jitter and (self.max_random_contrast > 0
                       or self.max_random_illumination > 0):
            keys = record_key_vec(
                self._seed_base, epoch,
                np.asarray([d.index for d in insts],
                           np.int64).astype(np.uint64),
            )
            if self.max_random_contrast > 0:
                lo, hi = (1 - self.max_random_contrast,
                          1 + self.max_random_contrast)
                c = lo + (hi - lo) * _u53(_slot_hash_vec(keys, S_CONTRAST))
                out *= c.astype(np.float32)[:, None, None, None]
            if self.max_random_illumination > 0:
                lo, hi = (-self.max_random_illumination,
                          self.max_random_illumination)
                v = lo + (hi - lo) * _u53(_slot_hash_vec(keys, S_ILLUM))
                out += v.astype(np.float32)[:, None, None, None]
        if self.scale != 1.0:
            out *= np.float32(self.scale)
        return [DataInst(d.index, out[i], d.label)
                for i, d in enumerate(insts)]
