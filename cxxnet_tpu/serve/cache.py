"""Shape-bucketed compiled-predict cache.

The cuDNN lesson (Chetlur et al., arXiv:1410.0759): fast inference comes
from a small set of FIXED, reusable compiled primitives, not per-call
specialization.  ``jax.jit`` specializes per input *shape*, so a serving
front-end that forwards raw request sizes compiles a fresh XLA program
for every distinct batch size it ever sees — the first request of size
37 stalls behind a multi-second compile, and the compile cache grows
without bound.

:class:`ShapeBucketCache` coarsens the shape space instead: a request of
``n`` rows is zero-padded up to the next power-of-two bucket (rounded up
to the mesh's data-axis size so sharded predict stays legal), runs
through the trainer's pure predict function for that bucket, and the
padded rows are trimmed off the result.  Mixed request sizes therefore
hit at most ``log2(max size)`` compiled programs, all warm after the
first pass.  Cache keys are
``(net_fingerprint, kind, node, bucket, row_shape, dtype, quant)`` — a
hot model reload (new fingerprint), a different feature node, or a
different weight-precision scheme (the f32 model vs its int8 export in
a rolling comparison) naturally occupies new slots.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["bucket_size", "ShapeBucketCache"]


def bucket_size(n: int, multiple_of: int = 1) -> int:
    """Smallest power of two >= ``n``, rounded up to ``multiple_of``
    (the mesh data-axis size, so every bucket shards evenly)."""
    if n <= 0:
        raise ValueError(f"bucket_size: need at least one row, got {n}")
    b = 1 << (int(n - 1).bit_length())
    if multiple_of > 1:
        b += (-b) % multiple_of
    return b


class ShapeBucketCache:
    """Bucketed eval-forward runner over one :class:`NetTrainer`.

    Thread-safe for stats; concurrent ``predict`` calls are safe (JAX
    dispatch is), though the serving engine funnels execution through
    one batcher thread anyway.  The heavy state — the compiled XLA
    executables — lives in the trainer's jitted functions; this class
    owns the bucketing policy and the hit/miss accounting keyed the way
    the executables are actually specialized.
    """

    def __init__(self, trainer, max_batch_size: int = 0) -> None:
        self._trainer = trainer
        self.max_batch_size = int(max_batch_size)
        self._keys: Dict[tuple, int] = {}  # key -> times used
        self._graph = trainer.graph  # identity snapshot: reset on rebuild
        self._net_fp: Optional[str] = None
        self.hits = 0
        self.misses = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    @property
    def trainer(self):
        return self._trainer

    def net_fp(self) -> str:
        """Cached net fingerprint (recomputed after a net rebuild)."""
        self._check_generation()
        if self._net_fp is None:
            self._net_fp = self._trainer.net_fp()
        return self._net_fp

    def _check_generation(self) -> None:
        """Drop stale keys when the trainer rebuilt its net (load_model /
        init_model clear the jit cache, so 'hits' would lie)."""
        if self._trainer.graph is not self._graph:
            with self._lock:
                self._keys.clear()
                self._graph = self._trainer.graph
                self._net_fp = None

    def quant_scheme(self) -> str:
        """The served weights' precision scheme (cache-key component):
        ``"int8"`` / ``"bf16"`` for quantized artifacts, ``""`` f32."""
        from ..ops import quant as opsq

        return opsq.scheme_of(self._trainer)

    def kernel_fp(self) -> str:
        """The kernel-library selection fingerprint (cache-key
        component, ``ops/kernels/``): the '+'-joined kernel names the
        net's bound selector activates on its backend, ``""`` when none
        — the stock program's key is unchanged from the pre-kernel era,
        and a verdict/conf flip lands in a distinct slot so stock and
        kernel programs of one net serve side by side."""
        net = self._trainer.net
        if net is None:
            return ""
        try:
            return net.bound_kernels().fingerprint()
        except Exception:  # noqa: BLE001 - key must never fail a serve
            return ""

    def _n_data(self) -> int:
        plan = self._trainer.mesh_plan
        return plan.n_data if plan is not None else 1

    def bucket_for(self, n: int) -> int:
        return bucket_size(n, self._n_data())

    # ------------------------------------------------------------------
    def _run(self, kind: str, node_id: Optional[int],
             data: np.ndarray) -> np.ndarray:
        """Pad ``data`` to its bucket, run the compiled predict fn, trim."""
        import jax
        import jax.numpy as jnp

        tr = self._trainer
        assert tr.net is not None, "init_model/load_model first"
        if tr.graph.extra_data_num:
            raise ValueError(
                "serving does not support nets with extra_data nodes"
            )
        data = np.ascontiguousarray(data, np.float32)
        if data.ndim < 2:
            raise ValueError(
                f"predict input must be a (N, ...) batch, got shape "
                f"{data.shape}"
            )
        n = data.shape[0]
        bucket = self.bucket_for(n)
        # the quant scheme rides in the key beside dtype: an f32 model
        # and its int8 export share a net fingerprint, and during a
        # rolling comparison both serve from one process — their
        # compiled programs must occupy distinct slots.  The kernel
        # selection rides beside it for the same reason (stock and
        # Pallas-kernel programs of one net coexist; quant scheme stays
        # the last component)
        key = (self.net_fp(), kind, node_id, bucket,
               data.shape[1:], str(data.dtype), self.kernel_fp(),
               self.quant_scheme())
        with self._lock:
            if key in self._keys:
                self._keys[key] += 1
                self.hits += 1
            else:
                self._keys[key] = 1
                self.misses += 1
        if bucket > n:
            data = np.concatenate(
                [data, np.zeros((bucket - n,) + data.shape[1:], data.dtype)],
                axis=0,
            )
        fn = tr.predict_fn(node_id)
        out = np.asarray(jax.device_get(
            fn(tr.params, tr.aux, jnp.asarray(data), ())
        ))
        return out[:n]

    def scores(self, data: np.ndarray) -> np.ndarray:
        """Raw f32 out-node rows for ``data`` (no argmax).  Shares its
        cache slots (and compiled programs) with :meth:`predict` — the
        argmax happens on host, after the compiled part."""
        return self._run("out", None, data)

    def predict(self, data: np.ndarray) -> np.ndarray:
        """Per-instance predictions (trainer argmax semantics), trimmed
        to exactly ``data.shape[0]`` rows."""
        return self._trainer.predict_from_scores(
            self._run("out", None, data)
        )

    def extract(self, data: np.ndarray, node_name: str) -> np.ndarray:
        node_id = self._trainer.resolve_feature_node(node_name)
        return self._run("extract", node_id, data)

    def keys_snapshot(self) -> list:
        """Consistent copy of the cache keys (for reload warmup —
        request threads keep inserting concurrently)."""
        with self._lock:
            return list(self._keys)

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "buckets": len(self._keys),
                "hits": self.hits,
                "misses": self.misses,
            }
