"""Binary zero-copy wire protocol for the serving data plane.

Every JSON request on the serving hot path pays ``json.loads`` →
Python-list → ``np.asarray`` on the way in and ``tolist()`` →
``json.dumps`` on the way out; at fleet scale the CPU burns on text
codec, not on the model.  The reference cxxnet moved bulk data in
binary CXBP pages for exactly this reason (our feedback log reuses that
page format already) — this module is the REQUEST-path analog: a
versioned little-endian frame negotiated via
``Content-Type: application/x-cxb`` on the existing ``/predict`` /
``/extract`` routes.  JSON stays byte-for-byte unchanged as the
compatibility path.

Request frame (``CXB1``)::

    offset size  field
    0      4     magic  b"CXB1"  (the version lives in the magic)
    4      1     kind       0=predict  1=scores  2=extract
    5      1     dtype      1=float32 (the only dtype this version moves)
    6      1     ndim       1..8 (dim0 = request rows)
    7      1     priority   0=interactive  1=batch
    8      4     deadline_ms  u32, 0 = none  -- FIXED offset: the fleet
                 router patches the REMAINING budget in place
                 (struct.pack_into) without re-encoding the frame
    12     2     model_len  (utf-8 bytes, 0 = default route)
    14     2     node_len   (utf-8 bytes; extract's feature node)
    16     4*ndim  shape dims, u32 each
    ...          model bytes, then node bytes
    ...          payload: prod(shape)*4 raw little-endian f32, C order

The server decodes the payload with ``np.frombuffer`` over a
``memoryview`` — no copy between the socket buffer and the
micro-batcher.  Responses stream raw f32 rows back the same way
(``CXR1``: magic, kind echo, dtype, ndim, rid, shape, payload — no
``tolist()``).  Malformed frames are a client error: the server answers
400 with a machine-stable ``reason`` token (below), NEVER a 500, and
error bodies stay JSON so a failing client can always read them.

Reason tokens (``WireError.reason``): ``wire_disabled``, ``bad_magic``,
``bad_kind``, ``bad_dtype``, ``bad_ndim``, ``bad_priority``,
``oversize_shape``, ``truncated_frame``, ``truncated_body``,
``trailing_bytes``.

See doc/serving.md "Binary wire protocol" for the negotiation and
compatibility guarantees.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "CONTENT_TYPE", "MAGIC_REQUEST", "MAGIC_RESPONSE", "WireError",
    "WireRequest", "encode_request", "decode_request", "peek_header",
    "patch_deadline", "encode_response", "decode_response",
    "MAX_PAYLOAD_BYTES",
]

CONTENT_TYPE = "application/x-cxb"

MAGIC_REQUEST = b"CXB1"
MAGIC_RESPONSE = b"CXR1"

#: request header: magic, kind, dtype, ndim, priority, deadline_ms,
#: model_len, node_len — deadline_ms sits at a FIXED byte offset so the
#: router can patch the remaining budget without re-encoding
_REQ = struct.Struct("<4sBBBBIHH")
DEADLINE_OFFSET = 8  # byte offset of deadline_ms inside the frame

#: response header: magic, kind, dtype, ndim, flags, rid_len, reserved
_RESP = struct.Struct("<4sBBBBHH")

_KINDS = ("predict", "scores", "extract")
_PRIORITIES = ("interactive", "batch")
_DTYPE_F32 = 1
_MAX_NDIM = 8
_F32 = np.dtype("<f4")

#: a frame's payload may not exceed the HTTP layer's body bound
MAX_PAYLOAD_BYTES = 64 << 20


class WireError(ValueError):
    """Malformed binary frame.  ``reason`` is the stable
    machine-readable token the 400 body carries (clients and the fuzz
    tests key on it; the text is for humans)."""

    def __init__(self, reason: str, detail: str) -> None:
        self.reason = reason
        super().__init__(detail)


@dataclasses.dataclass
class WireRequest:
    """A decoded ``CXB1`` frame.  ``data`` aliases the request buffer
    (read-only, zero-copy) — the batcher's staging copy is the first
    and only copy on the way to the device."""

    kind: str
    data: np.ndarray
    model: str = ""
    node: str = ""
    priority: str = "interactive"
    deadline_ms: Optional[float] = None


def _check_shape(ndim: int, dims: Tuple[int, ...]) -> int:
    """Validated payload byte count of ``dims`` (f32)."""
    if not 1 <= ndim <= _MAX_NDIM:
        raise WireError("bad_ndim", f"ndim {ndim} outside 1..{_MAX_NDIM}")
    n = 4
    for d in dims:
        if d < 1:
            raise WireError("oversize_shape",
                            f"non-positive dim {d} in shape {dims}")
        n *= d
        if n > MAX_PAYLOAD_BYTES:
            raise WireError(
                "oversize_shape",
                f"shape {dims} implies > {MAX_PAYLOAD_BYTES} payload bytes")
    return n


# ----------------------------------------------------------------------
# requests
def encode_request(data, kind: str = "predict", model: str = "",
                   node: str = "", priority: str = "interactive",
                   deadline_ms: Optional[float] = None) -> bytearray:
    """Client-side encoder (also what the bench's pooled client uses).
    Returns a mutable ``bytearray`` so a router holding the frame can
    :func:`patch_deadline` in place before relaying."""
    if kind not in _KINDS:
        raise WireError("bad_kind", f"unknown kind {kind!r}")
    if priority not in _PRIORITIES:
        raise WireError("bad_priority", f"unknown priority {priority!r}")
    arr = np.ascontiguousarray(data, _F32)
    if arr.ndim < 1 or arr.ndim > _MAX_NDIM:
        raise WireError("bad_ndim", f"cannot frame ndim {arr.ndim}")
    mb = model.encode("utf-8")
    nb = node.encode("utf-8")
    dl = 0
    if deadline_ms is not None and deadline_ms > 0:
        # u32 milliseconds; a sub-millisecond remainder still has to
        # reach the replica as a live (nonzero) budget
        dl = max(1, min(int(deadline_ms), 0xFFFFFFFF))
    out = bytearray(_REQ.pack(
        MAGIC_REQUEST, _KINDS.index(kind), _DTYPE_F32, arr.ndim,
        _PRIORITIES.index(priority), dl, len(mb), len(nb)))
    out += struct.pack(f"<{arr.ndim}I", *arr.shape)
    out += mb
    out += nb
    out += memoryview(arr).cast("B")
    return out


def peek_header(buf) -> Tuple[str, str, str, Optional[float], int]:
    """Validate a frame's header WITHOUT touching the payload and
    return ``(kind, model, priority, deadline_ms, payload_bytes)`` —
    what the fleet router needs for admission/classification/deadline
    before relaying the frame opaquely.  Raises :class:`WireError` on
    anything malformed, including a buffer whose length disagrees with
    the shape it declares."""
    view = memoryview(buf)
    if len(view) < _REQ.size:
        raise WireError("truncated_frame",
                        f"{len(view)} bytes cannot hold a frame header")
    magic, kind_b, dtype, ndim, prio_b, dl, mlen, nlen = \
        _REQ.unpack_from(view, 0)
    if magic != MAGIC_REQUEST:
        raise WireError("bad_magic",
                        f"bad frame magic {bytes(magic)!r}")
    if kind_b >= len(_KINDS):
        raise WireError("bad_kind", f"unknown kind byte {kind_b}")
    if dtype != _DTYPE_F32:
        raise WireError("bad_dtype",
                        f"unsupported dtype code {dtype} (want "
                        f"{_DTYPE_F32} = float32)")
    if prio_b >= len(_PRIORITIES):
        raise WireError("bad_priority",
                        f"unknown priority byte {prio_b}")
    dims_end = _REQ.size + 4 * ndim
    if not 1 <= ndim <= _MAX_NDIM:
        raise WireError("bad_ndim", f"ndim {ndim} outside 1..{_MAX_NDIM}")
    if len(view) < dims_end + mlen + nlen:
        raise WireError("truncated_frame",
                        "frame ends inside shape/name fields")
    dims = struct.unpack_from(f"<{ndim}I", view, _REQ.size)
    payload = _check_shape(ndim, dims)
    body_end = dims_end + mlen + nlen + payload
    if len(view) < body_end:
        raise WireError(
            "truncated_body",
            f"payload needs {payload} bytes, frame has "
            f"{len(view) - dims_end - mlen - nlen}")
    if len(view) > body_end:
        raise WireError("trailing_bytes",
                        f"{len(view) - body_end} bytes past the payload")
    try:
        model = str(view[dims_end:dims_end + mlen], "utf-8")
    except UnicodeDecodeError:
        raise WireError("truncated_frame", "model name is not utf-8")
    return (_KINDS[kind_b], model, _PRIORITIES[prio_b],
            float(dl) if dl else None, payload)


def decode_request(buf) -> WireRequest:
    """Full zero-copy decode: the returned array is an
    ``np.frombuffer`` view over ``buf`` (read-only)."""
    view = memoryview(buf)
    kind, model, priority, deadline_ms, _payload = peek_header(view)
    _magic, _k, _d, ndim, _p, _dl, mlen, nlen = _REQ.unpack_from(view, 0)
    dims = struct.unpack_from(f"<{ndim}I", view, _REQ.size)
    dims_end = _REQ.size + 4 * ndim
    try:
        node = str(view[dims_end + mlen:dims_end + mlen + nlen], "utf-8")
    except UnicodeDecodeError:
        raise WireError("truncated_frame", "node name is not utf-8")
    data = np.frombuffer(view, _F32,
                         offset=dims_end + mlen + nlen).reshape(dims)
    return WireRequest(kind=kind, data=data, model=model, node=node,
                       priority=priority, deadline_ms=deadline_ms)


def patch_deadline(frame: bytearray, deadline_ms: float) -> None:
    """Overwrite the frame's deadline with the REMAINING budget —
    the router's per-attempt update, no re-encode, no payload touch."""
    dl = max(1, min(int(deadline_ms), 0xFFFFFFFF)) if deadline_ms > 0 \
        else 0
    struct.pack_into("<I", frame, DEADLINE_OFFSET, dl)


# ----------------------------------------------------------------------
# responses
def encode_response_header(arr: np.ndarray, kind: str,
                           rid: str) -> Tuple[bytes, np.ndarray]:
    """``(header_bytes, payload_array)`` for a result — the server
    writes the two straight to the socket (header, then the array's
    memoryview) so the scores are never copied into a joined body."""
    out = np.ascontiguousarray(arr, _F32)
    if out.ndim < 1:
        out = out.reshape(1)
    rb = rid.encode("utf-8")
    head = _RESP.pack(MAGIC_RESPONSE, _KINDS.index(kind), _DTYPE_F32,
                      out.ndim, 0, len(rb), 0)
    head += struct.pack(f"<{out.ndim}I", *out.shape)
    head += rb
    return head, out


def encode_response(arr, kind: str, rid: str) -> bytes:
    head, out = encode_response_header(np.asarray(arr), kind, rid)
    return head + memoryview(out).cast("B").tobytes()


def decode_response(buf) -> Tuple[str, str, np.ndarray]:
    """``(kind, rid, rows)`` from a ``CXR1`` frame (client side)."""
    view = memoryview(buf)
    if len(view) < _RESP.size:
        raise WireError("truncated_frame",
                        f"{len(view)} bytes cannot hold a response header")
    magic, kind_b, dtype, ndim, _flags, rlen, _res = \
        _RESP.unpack_from(view, 0)
    if magic != MAGIC_RESPONSE:
        raise WireError("bad_magic",
                        f"bad response magic {bytes(magic)!r}")
    if dtype != _DTYPE_F32 or kind_b >= len(_KINDS):
        raise WireError("bad_dtype", "unsupported response encoding")
    if not 1 <= ndim <= _MAX_NDIM:
        raise WireError("bad_ndim", f"response ndim {ndim}")
    dims_end = _RESP.size + 4 * ndim
    if len(view) < dims_end + rlen:
        raise WireError("truncated_frame",
                        "response ends inside shape/rid fields")
    dims = struct.unpack_from(f"<{ndim}I", view, _RESP.size)
    payload = _check_shape(ndim, dims)
    if len(view) != dims_end + rlen + payload:
        raise WireError("truncated_body",
                        f"response payload needs {payload} bytes")
    rid = str(view[dims_end:dims_end + rlen], "utf-8")
    data = np.frombuffer(view, _F32, offset=dims_end + rlen).reshape(dims)
    return _KINDS[kind_b], rid, data
