"""Dynamic micro-batching with backpressure.

The serving analog of the training side's ``scan_steps`` insight
(doc/performance.md): per-dispatch host cost dominates small programs,
so work must be coalesced before it reaches the device.  Training can
stage a fixed K ahead of time; serving cannot — requests arrive when
they arrive — so the batcher coalesces *dynamically*: the worker picks
the oldest request, then holds the batch open for at most
``batch_timeout_ms`` while compatible requests (same kind / node / row
shape / dtype) join, up to ``max_batch_size`` rows, and executes them
as ONE compiled-program call.  Results are split back per request.

Backpressure is explicit rather than emergent (TensorFlow's production
lesson, arXiv:1605.08695: unbounded queues turn overload into latency
collapse):

* the queue is bounded (``queue_limit`` requests) — a full queue sheds
  the new request immediately with :class:`OverloadError` (HTTP 429),
  keeping queueing delay bounded for the requests already admitted;
* each request may carry a deadline — requests whose deadline passes
  while still queued are expired with :class:`DeadlineError` instead of
  wasting device time on an answer nobody is waiting for (the deadline
  is checked at dequeue time; a request that starts executing runs to
  completion).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..utils.faults import Watchdog

__all__ = [
    "ServeError", "OverloadError", "DeadlineError", "ClosedError",
    "MicroBatcher",
]


class ServeError(RuntimeError):
    """Base class for serving-path failures; carries an HTTP status."""

    http_status = 500


class OverloadError(ServeError):
    """Load shed: the request queue is full."""

    http_status = 429


class DeadlineError(ServeError):
    """The request's deadline passed before execution started."""

    http_status = 504


class ClosedError(ServeError):
    """The engine is shutting down."""

    http_status = 503


@dataclasses.dataclass
class _Request:
    kind: str                      # "out" | "extract"
    node: Optional[str]            # feature node name for extract
    data: np.ndarray               # (n, ...) rows
    enqueue_t: float
    deadline_t: Optional[float]    # absolute monotonic deadline, or None
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    result: Optional[np.ndarray] = None
    error: Optional[BaseException] = None

    def group_key(self) -> Tuple:
        return (self.kind, self.node, self.data.shape[1:],
                str(self.data.dtype))

    def resolve(self, result=None, error=None) -> None:
        self.result, self.error = result, error
        self.done.set()


class MicroBatcher:
    """Coalesces concurrent requests into bucket-sized device calls.

    ``runner(kind, node, data)`` executes one coalesced batch (the
    engine binds this to its bucket cache) and returns the result rows
    aligned with ``data``.  One worker thread owns all execution, so
    compiled-program calls are naturally serialized.
    """

    def __init__(
        self,
        runner: Callable[[str, Optional[str], np.ndarray], np.ndarray],
        max_batch_size: int = 64,
        batch_timeout_ms: float = 2.0,
        queue_limit: int = 128,
        stats=None,
        watchdog_timeout_s: float = 600.0,
    ) -> None:
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._runner = runner
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout = max(0.0, float(batch_timeout_ms)) / 1e3
        self.queue_limit = int(queue_limit)
        self._stats = stats
        self._queue: List[_Request] = []
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False
        if stats is not None:
            stats.bind_queue_depth(self.pending_count)
        # batch-assembly staging buffers, one per (row shape, dtype):
        # coalesced requests are sliced into a preallocated buffer
        # instead of np.concatenate allocating a fresh batch array per
        # dispatch.  Owned exclusively by the worker thread; reuse
        # across batches is safe because the runner (the engine's
        # bucket cache) blocks on device_get before returning, so the
        # device has consumed the rows before the next batch assembles.
        self._staging: dict = {}
        self._worker = threading.Thread(
            target=self._loop, name="cxxnet-serve-batcher", daemon=True
        )
        # a worker hung inside the runner (device stall, injected hang)
        # would otherwise leave every submitter blocked forever; the
        # watchdog turns that into a fail-fast WatchdogError carrying
        # the worker's stack.  0 disables.  The timeout is generous by
        # default because the first batch of a cold bucket legitimately
        # sits behind an XLA compile.
        self.watchdog = Watchdog(
            what="serve batcher worker",
            timeout_s=watchdog_timeout_s,
            thread=self._worker,
        )
        self._worker.start()

    # ------------------------------------------------------------------
    # live knobs (the self-tuning controller's setters; both are read
    # fresh by the worker at the top of every coalesced batch, so a
    # resize applies on the next batch without pausing traffic)
    def set_max_batch_size(self, n: int) -> int:
        """Resize the coalescing limit at runtime.  The in-progress
        batch finishes under the old limit; a request larger than the
        new limit still executes alone (the oldest request is always
        taken unconditionally), so nothing already admitted can wedge."""
        self.max_batch_size = max(1, int(n))
        return self.max_batch_size

    def set_batch_timeout_ms(self, ms: float) -> float:
        """Retune the batch-open window at runtime (next batch on)."""
        self.batch_timeout = max(0.0, float(ms)) / 1e3
        return self.batch_timeout * 1e3

    def pending_count(self) -> int:
        with self._lock:
            return len(self._queue)

    def submit(
        self,
        data: np.ndarray,
        kind: str = "out",
        node: Optional[str] = None,
        deadline_ms: Optional[float] = None,
    ) -> np.ndarray:
        """Enqueue ``data`` and block until its rows come back.

        Raises :class:`OverloadError` immediately when the queue is
        full, :class:`DeadlineError` when the deadline expired before
        execution, :class:`ClosedError` on shutdown; any exception the
        model raised is re-raised here."""
        now = time.monotonic()
        req = _Request(
            kind=kind, node=node, data=data, enqueue_t=now,
            deadline_t=(now + deadline_ms / 1e3)
            if deadline_ms and deadline_ms > 0 else None,
        )
        with self._nonempty:
            if self._closed:
                raise ClosedError("serving engine is shut down")
            if len(self._queue) >= self.queue_limit:
                raise OverloadError(
                    f"request queue full ({self.queue_limit} pending); "
                    "load shed — retry with backoff"
                )
            self._queue.append(req)
            self._nonempty.notify()
        # stall window anchored at THIS request's enqueue: an idle-
        # before-this worker isn't mistaken for hung, and (critically)
        # submitters never touch the worker's beat clock — steady
        # traffic must not mask a genuinely hung worker
        self.watchdog.wait(req.done, since=req.enqueue_t)
        if req.error is not None:
            raise req.error
        return req.result

    # ------------------------------------------------------------------
    def _take_batch(self) -> List[_Request]:
        """Pop the oldest request plus every compatible one that arrives
        within the batch window, dropping expired requests as seen."""
        with self._nonempty:
            while not self._queue and not self._closed:
                self._nonempty.wait()
            if not self._queue:
                return []
            first = self._queue.pop(0)
        if (first.deadline_t is not None
                and time.monotonic() > first.deadline_t):
            first.resolve(error=DeadlineError(
                "deadline expired while queued"
            ))
            return []
        batch = [first]
        key = first.group_key()
        rows = first.data.shape[0]
        # snapshot the live knobs once per batch: a concurrent resize
        # (self-tuning controller) applies atomically at the next batch
        limit = self.max_batch_size
        window_end = time.monotonic() + self.batch_timeout
        while rows < limit:
            with self._nonempty:
                # sweep the queue for compatible, unexpired requests
                i = 0
                while i < len(self._queue) and rows < limit:
                    r = self._queue[i]
                    if (r.deadline_t is not None
                            and time.monotonic() > r.deadline_t):
                        self._queue.pop(i)
                        r.resolve(error=DeadlineError(
                            "deadline expired while queued"
                        ))
                        continue
                    if (r.group_key() == key
                            and rows + r.data.shape[0] <= limit):
                        self._queue.pop(i)
                        batch.append(r)
                        rows += r.data.shape[0]
                        continue
                    i += 1
                if rows >= limit or self._closed:
                    break
                remain = window_end - time.monotonic()
                if remain <= 0:
                    break
                self._nonempty.wait(timeout=remain)
        return batch

    def _assemble(self, batch: List[_Request]) -> np.ndarray:
        """Copy each request's rows into the per-shape staging buffer
        (worker-thread only).  A single-request batch never reaches
        here — it passes its array through untouched."""
        first = batch[0].data
        rows = sum(r.data.shape[0] for r in batch)
        key = (first.shape[1:], first.dtype.str)
        buf = self._staging.get(key)
        if buf is None or buf.shape[0] < rows:
            cap = max(rows, self.max_batch_size)
            buf = np.empty((cap,) + first.shape[1:], dtype=first.dtype)
            self._staging[key] = buf
        ofs = 0
        for r in batch:
            n = r.data.shape[0]
            buf[ofs:ofs + n] = r.data
            ofs += n
        return buf[:rows]

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._closed and not self._queue:
                    return
            batch = self._take_batch()
            if not batch:
                continue
            self.watchdog.beat()
            try:
                data = (batch[0].data if len(batch) == 1
                        else self._assemble(batch))
                out = self._runner(batch[0].kind, batch[0].node, data)
            except BaseException as e:  # noqa: BLE001 - relayed per request
                for r in batch:
                    r.resolve(error=e)
                continue
            finally:
                self.watchdog.beat()
            ofs = 0
            for r in batch:
                n = r.data.shape[0]
                r.resolve(result=out[ofs:ofs + n])
                ofs += n

    # ------------------------------------------------------------------
    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting work, fail pending requests, join the worker."""
        with self._nonempty:
            self._closed = True
            pending, self._queue = self._queue, []
            self._nonempty.notify_all()
        for r in pending:
            r.resolve(error=ClosedError("serving engine is shut down"))
        self._worker.join(timeout=timeout)
