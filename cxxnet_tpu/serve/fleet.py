"""Serving fleet: replica supervision, rolling reload, int8 canaries.

One ``task=serve`` process is one crash away from an empty front door.
This module generalizes the single-engine serving stack to the
TensorFlow-systems shape (arXiv 1605.08695): N engine **replicas** —
each a full ``task=serve`` subprocess with its own engine, batcher and
compiled-program cache — behind one front-end
(:mod:`~cxxnet_tpu.serve.router`), with:

* **supervision** — :class:`ReplicaSupervisor` probes every replica's
  ``/healthz`` on a fixed cadence and classifies it the way the elastic
  mesh classifies peers (``parallel/elastic.py``): answering → HEALTHY,
  a few missed probes → SLOW (still in rotation — a transient blip must
  not empty the front door), missed probes past ``fleet_slow_probes``
  → WEDGED (ejected from rotation, killed, restarted), process exit →
  GONE (restarted).  Restarts back off exponentially
  (``fleet_restart_backoff_s`` … ``fleet_restart_backoff_max_s``) and
  are capped by ``fleet_max_restarts`` (0 = unlimited).  Losing k of N
  replicas shrinks admission capacity and throughput — never
  availability, as long as one replica answers.
* **rolling reload** — :meth:`ServingFleet.rolling_reload` walks the
  rotation ONE replica at a time, triggering each engine's breaker-
  gated hot reload through the ``POST /reloadz`` admin route and
  waiting for the replica to probe healthy on the new round before
  touching the next; a fleet-level :class:`~cxxnet_tpu.utils.faults.
  CircuitBreaker` aborts the rollout on repeated failures, so a bad
  round can wedge at most ``threshold`` replicas while the rest keep
  serving the old one.  The rotation is never empty: each engine's
  hot swap is itself zero-downtime, and only one replica reloads at a
  time.
* **int8 canary** — with ``canary = int8``, ``canary_replicas`` of the
  fleet are launched with ``quant=int8`` (they prefer the PR-10 gated
  ``.quant.model`` sibling); the router sends a ``canary_slice`` of
  live predict traffic to them and MIRRORS a ``canary_sample`` of
  baseline traffic for row-level agreement measurement.  Agreement and
  latency land in the shared registry families (``canary_agreement``,
  ``canary_latency_ratio``, ``canary_requests_total{leg}``), an alert
  rule on ``canary_agreement`` is armed automatically, and
  :class:`CanaryController` promotes (publish pointer → the quant
  artifact, canary joins the rotation at full weight) or rolls back
  (publish pointer restored, canary relaunched at f32) — the rollback
  trigger is the ``/alertz`` evaluator firing, so the same SLO brain
  that degrades ``/healthz`` cancels a bad rollout.

The chaos site for all of this is ``serve.replica`` (``hang`` wedges a
replica's health plane, ``ioerror`` crashes the process —
doc/robustness.md); ``tools/fleet_smoke.py`` is the end-to-end
kill-one-of-three acceptance lane.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import events as obs_events
from ..obs.registry import registry as obs_registry
from ..parallel.elastic import free_port
from ..utils.faults import CircuitBreaker

ConfigEntry = Tuple[str, str]

__all__ = [
    "FleetOptions",
    "Replica",
    "ReplicaSupervisor",
    "CanaryController",
    "ServingFleet",
    "fleet_metrics",
    "cli_spawn_fn",
    "stub_spawn_fn",
]

#: replica states.  HEALTHY and SLOW are in rotation; everything else
#: is not.  SLOW = missed probes below the wedge threshold (transient
#: blips must not empty the front door); WEDGED = ejected + restarting;
#: QUARANTINED = answering but integrity-degraded (golden canary
#: mismatch) — ejected from rotation, NOT killed, readmitted by the
#: next clean probe.
STATES = ("starting", "healthy", "slow", "quarantined", "wedged",
          "gone", "backoff", "failed", "stopped")
IN_ROTATION = ("healthy", "slow")


# ----------------------------------------------------------------------
@dataclasses.dataclass
class FleetOptions:
    """The ``replicas`` / ``fleet_*`` / ``canary_*`` config surface
    (doc/conf.md)."""

    replicas: int = 1
    probe_period_s: float = 1.0
    probe_timeout_s: float = 2.0
    slow_probes: int = 3           # consecutive missed probes => wedged
    start_timeout_s: float = 180.0
    restart_backoff_s: float = 0.5
    restart_backoff_max_s: float = 15.0
    max_restarts: int = 0          # per replica; 0 = unlimited
    replica_inflight: int = 64     # admission: in-flight cap per healthy replica
    batch_shed_ratio: float = 0.5  # batch sheds above this capacity fraction
    dispatch_retries: int = 2      # failovers per request beyond the first try
    dispatch_timeout_s: float = 60.0
    pool_size: int = 8             # idle keep-alive connections kept per replica
    log_dir: str = ""              # replica stdout/stderr logs
    reload_timeout_s: float = 120.0
    reload_breaker_threshold: int = 3
    canary: str = ""               # quant scheme for canary replicas; "" = off
    canary_replicas: int = 1
    canary_slice: float = 0.1      # live-traffic fraction routed to the canary
    canary_sample: float = 0.25    # baseline fraction mirrored for agreement
    canary_min_requests: int = 50  # compared rows before any decision
    canary_min_agreement: float = 0.99
    canary_decision_period_s: float = 1.0

    @classmethod
    def from_cfg(cls, cfg: Sequence[ConfigEntry]) -> "FleetOptions":
        o = cls()
        for name, val in cfg:
            if name == "replicas":
                o.replicas = int(val)
            elif name == "fleet_probe_period_s":
                o.probe_period_s = float(val)
            elif name == "fleet_probe_timeout_s":
                o.probe_timeout_s = float(val)
            elif name == "fleet_slow_probes":
                o.slow_probes = int(val)
            elif name == "fleet_start_timeout_s":
                o.start_timeout_s = float(val)
            elif name == "fleet_restart_backoff_s":
                o.restart_backoff_s = float(val)
            elif name == "fleet_restart_backoff_max_s":
                o.restart_backoff_max_s = float(val)
            elif name == "fleet_max_restarts":
                o.max_restarts = int(val)
            elif name == "fleet_replica_inflight":
                o.replica_inflight = int(val)
            elif name == "fleet_batch_shed_ratio":
                o.batch_shed_ratio = float(val)
            elif name == "fleet_dispatch_retries":
                o.dispatch_retries = int(val)
            elif name == "fleet_dispatch_timeout_s":
                o.dispatch_timeout_s = float(val)
            elif name == "fleet_pool_size":
                o.pool_size = int(val)
            elif name == "fleet_log_dir":
                o.log_dir = val
            elif name == "fleet_reload_timeout_s":
                o.reload_timeout_s = float(val)
            elif name == "fleet_reload_breaker_threshold":
                o.reload_breaker_threshold = int(val)
            elif name == "canary":
                o.canary = "" if val in ("", "0", "off", "none") else val
            elif name == "canary_replicas":
                o.canary_replicas = int(val)
            elif name == "canary_slice":
                o.canary_slice = float(val)
            elif name == "canary_sample":
                o.canary_sample = float(val)
            elif name == "canary_min_requests":
                o.canary_min_requests = int(val)
            elif name == "canary_min_agreement":
                o.canary_min_agreement = float(val)
            elif name == "canary_decision_period_s":
                o.canary_decision_period_s = float(val)
        if o.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if o.slow_probes < 1:
            raise ValueError("fleet_slow_probes must be >= 1")
        if o.replica_inflight < 1:
            raise ValueError("fleet_replica_inflight must be >= 1")
        if o.pool_size < 1:
            raise ValueError("fleet_pool_size must be >= 1")
        if not 0.0 < o.batch_shed_ratio <= 1.0:
            raise ValueError("fleet_batch_shed_ratio must be in (0, 1]")
        if o.canary:
            if not 0 < o.canary_replicas < o.replicas:
                raise ValueError(
                    "canary_replicas must leave at least one baseline "
                    "replica (0 < canary_replicas < replicas)")
            for frac_name in ("canary_slice", "canary_sample"):
                v = getattr(o, frac_name)
                if not 0.0 <= v <= 1.0:
                    raise ValueError(f"{frac_name} must be in [0, 1]")
            if not 0.0 < o.canary_min_agreement <= 1.0:
                raise ValueError(
                    "canary_min_agreement must be in (0, 1]")
        return o


# ----------------------------------------------------------------------
class _FleetMetrics:
    """Process-wide registry families for the fleet front-end
    (doc/observability.md "Fleet metrics").  The canary agreement /
    latency gauges are deliberately NOT created here: a zero-valued
    ``canary_agreement`` existing before any comparison would instantly
    fire the auto-armed rollback alert — they materialize on the first
    recorded comparison (:meth:`CanaryController.record_compare`)."""

    def __init__(self) -> None:
        reg = obs_registry()
        self.replicas = reg.gauge(
            "fleet_replicas", "Fleet replica counts by state.",
            labelnames=("state",))
        self.restarts = reg.counter(
            "fleet_restarts_total",
            "Replica restarts by reason: crash / wedged / canary_rollback.",
            labelnames=("reason",))
        self.requests = reg.counter(
            "fleet_requests_total",
            "Requests ARRIVING at the fleet front-end by priority "
            "class, before admission (shed arrivals included; admitted "
            "= requests - shed).",
            labelnames=("priority",))
        self.shed = reg.counter(
            "fleet_shed_total",
            "Requests shed by admission control (429), by priority class.",
            labelnames=("priority",))
        self.dispatch = reg.counter(
            "fleet_dispatch_total",
            "Requests dispatched, by replica index.",
            labelnames=("replica",))
        self.failovers = reg.counter(
            "fleet_failovers_total",
            "Dispatches retried on another replica after a network "
            "failure (the killed-replica in-flight path).")
        self.inflight = reg.gauge(
            "fleet_inflight", "Requests currently admitted at the router.")
        self.restart_seconds = reg.histogram(
            "fleet_restart_seconds",
            "Wall-clock from replica-down detection to healthy again.")
        self.reloads = reg.counter(
            "fleet_reloads_total",
            "Rolling-reload outcomes per replica: swapped / noop / "
            "failed / aborted.",
            labelnames=("result",))
        self.canary_total = reg.counter(
            "canary_total",
            "Canary lifecycle decisions: promote / rollback.",
            labelnames=("decision",))
        self.canary_requests = reg.counter(
            "canary_requests_total",
            "Canary traffic by leg: slice (live) / mirror (shadow "
            "comparison).",
            labelnames=("leg",))
        # router→replica persistent-connection pool (doc/serving.md
        # "Pooled dispatch"): a connects rate far below the dispatch
        # rate is the pool doing its job
        self.pool_connects = reg.counter(
            "fleet_pool_connects_total",
            "New router-to-replica keep-alive connections opened.")
        self.pool_retired = reg.counter(
            "fleet_pool_retired_total",
            "Pooled connections retired (error / replica eject / "
            "reload / server-requested close).")
        self.pool_idle = reg.gauge(
            "fleet_pool_idle_connections",
            "Idle keep-alive connections parked at the router.")


_METRICS: Optional[_FleetMetrics] = None
_METRICS_LOCK = threading.Lock()


def fleet_metrics() -> _FleetMetrics:
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            _METRICS = _FleetMetrics()
        return _METRICS


# ----------------------------------------------------------------------
class Replica:
    """One supervised engine replica (usually a subprocess)."""

    def __init__(self, idx: int, port: int, role: str = "serve",
                 host: str = "127.0.0.1") -> None:
        self.idx = idx
        self.port = port
        self.role = role              # "serve" | "canary"
        self.host = host
        self.proc: Optional[subprocess.Popen] = None
        self.log_handle = None
        self.state = "starting"
        self.consecutive_fail = 0
        self.restarts = 0
        self.backoff_s = 0.0          # set by the supervisor
        self.restart_at = 0.0
        self.down_since: Optional[float] = None
        self.down_reason = ""
        self.inflight = 0             # router-maintained, under its lock
        self.dispatched = 0
        self.spawned_at = time.monotonic()
        self.last_round = -1
        self.last_model: Optional[str] = None
        self.last_status = ""
        self.reasons: List[str] = []

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def in_rotation(self) -> bool:
        return self.state in IN_ROTATION

    def snapshot(self) -> Dict[str, object]:
        return {
            "idx": self.idx, "port": self.port, "role": self.role,
            "pid": self.pid, "state": self.state,
            "restarts": self.restarts, "inflight": self.inflight,
            "dispatched": self.dispatched, "round": self.last_round,
            "reasons": list(self.reasons),
        }


def _http_get_json(addr: str, path: str, timeout_s: float) -> dict:
    with urllib.request.urlopen(f"http://{addr}{path}",
                                timeout=timeout_s) as r:
        return json.loads(r.read().decode("utf-8"))


def _http_post_json(addr: str, path: str, obj: dict,
                    timeout_s: float) -> dict:
    req = urllib.request.Request(
        f"http://{addr}{path}",
        data=json.dumps(obj).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return json.loads(r.read().decode("utf-8"))


class ReplicaSupervisor:
    """Launches, probes, classifies, and restarts the replica set.

    ``spawn_fn(replica) -> subprocess.Popen`` owns process creation —
    the CLI binds :func:`cli_spawn_fn` (a full ``task=serve`` child),
    tests bind :func:`stub_spawn_fn`.  ``spawn_fn=None`` supervises
    EXTERNAL replicas (probe/classify/eject only, no restart)."""

    def __init__(self, opts: FleetOptions,
                 spawn_fn: Optional[Callable[[Replica],
                                             subprocess.Popen]] = None,
                 host: str = "127.0.0.1") -> None:
        self.opts = opts
        self.spawn_fn = spawn_fn
        self.host = host
        self.replicas: List[Replica] = []
        self.last_restart_wall_s = 0.0
        self.restarts_total = 0
        # eject notification (the router binds this to retire its
        # keep-alive pool, so no request rides a socket into a corpse)
        self.on_down: Optional[Callable[[Replica], None]] = None
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def add_replica(self, role: str = "serve",
                    port: Optional[int] = None) -> Replica:
        r = Replica(len(self.replicas), port or free_port(), role=role,
                    host=self.host)
        r.backoff_s = self.opts.restart_backoff_s
        self.replicas.append(r)
        return r

    def start(self) -> "ReplicaSupervisor":
        """Create the configured replica set (``replicas`` total, the
        last ``canary_replicas`` of them canaries when armed), spawn
        every process, and start the probe loop."""
        if not self.replicas:
            n_canary = (self.opts.canary_replicas if self.opts.canary
                        else 0)
            for i in range(self.opts.replicas):
                role = ("canary" if i >= self.opts.replicas - n_canary
                        else "serve")
                self.add_replica(role=role)
        for r in self.replicas:
            self._spawn(r)
        obs_events.emit("fleet.start", replicas=len(self.replicas),
                        canary=self.opts.canary or None)
        self._thread = threading.Thread(
            target=self._probe_loop, name="cxxnet-fleet-probe", daemon=True)
        self._thread.start()
        return self

    def _spawn(self, r: Replica) -> None:
        r.spawned_at = time.monotonic()
        if self.spawn_fn is None:
            r.state = "starting"  # external replica: probe-only
            return
        r.proc = self.spawn_fn(r)
        r.state = "starting"
        r.consecutive_fail = 0

    # ------------------------------------------------------------------
    def wait_ready(self, timeout_s: Optional[float] = None,
                   min_healthy: Optional[int] = None) -> bool:
        """Block until ``min_healthy`` (default: all) replicas probe
        healthy; False on timeout."""
        want = min_healthy if min_healthy is not None else len(self.replicas)
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.opts.start_timeout_s)
        while time.monotonic() < deadline:
            if len(self.healthy()) >= want:
                return True
            time.sleep(min(0.05, self.opts.probe_period_s))
        return len(self.healthy()) >= want

    def rotation(self) -> List[Replica]:
        with self._lock:
            return [r for r in self.replicas if r.in_rotation()]

    def healthy(self) -> List[Replica]:
        with self._lock:
            return [r for r in self.replicas if r.state == "healthy"]

    def state_counts(self) -> Dict[str, int]:
        with self._lock:
            counts = {s: 0 for s in STATES}
            for r in self.replicas:
                counts[r.state] = counts.get(r.state, 0) + 1
            return counts

    def note_dispatch_failure(self, r: Replica) -> None:
        """Router feedback: a dispatch hit a connection failure.  Count
        it like a missed probe and wake the probe loop so a dead
        replica is confirmed within one probe round-trip instead of a
        full period."""
        with self._lock:
            if r.state in ("healthy", "slow"):
                r.consecutive_fail += 1
                if r.state == "healthy":
                    r.state = "slow"
        self._wake.set()

    # ------------------------------------------------------------------
    # probe loop
    def _probe_loop(self) -> None:
        while True:
            self._wake.wait(self.opts.probe_period_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            self.probe_once()

    def probe_once(self) -> None:
        """One supervision sweep over every replica (the loop body;
        tests may call it directly for deterministic stepping)."""
        now = time.monotonic()
        for r in list(self.replicas):
            if r.state in ("failed", "stopped"):
                continue
            proc = r.proc
            if (r.state not in ("backoff",) and proc is not None
                    and proc.poll() is not None):
                self._on_down(r, "crash",
                              f"process exited rc={proc.returncode}")
            elif r.state == "backoff":
                pass
            else:
                ok, body, err = self._probe_http(r)
                if ok:
                    self._on_probe_ok(r, body)
                else:
                    self._on_probe_fail(r, err)
            if (r.state == "backoff"
                    and time.monotonic() >= r.restart_at):
                self._respawn(r)
        self._export_gauges()

    def _probe_http(self, r: Replica):
        try:
            body = _http_get_json(r.address, "/healthz",
                                  self.opts.probe_timeout_s)
        except Exception as e:  # noqa: BLE001 - any failure is a miss
            return False, None, f"{type(e).__name__}: {e}"
        if not isinstance(body, dict):
            return False, None, "bad body (not a JSON object)"
        if body.get("status") not in ("ok", "degraded"):
            return False, body, f"status={body.get('status')!r}"
        return True, body, None

    def _on_probe_ok(self, r: Replica, body: dict) -> None:
        with self._lock:
            was = r.state
            reasons = [str(x) for x in (body.get("reasons") or ())]
            # integrity quarantine (doc/robustness.md "Integrity
            # plane"): a replica whose golden canary failed still
            # ANSWERS, but its compute cannot be trusted — eject it
            # from rotation WITHOUT killing it (its canary keeps
            # running and a later clean score readmits it; a restart
            # would land on the same possibly-bad device anyway)
            quarantined = "integrity_failed" in reasons
            r.state = "quarantined" if quarantined else "healthy"
            r.consecutive_fail = 0
            r.last_status = str(body.get("status", "ok"))
            if body.get("round") is not None:
                r.last_round = int(body["round"])
            r.last_model = body.get("model")
            r.reasons = reasons
            came_back = r.down_since is not None
            if came_back:
                wall = time.monotonic() - r.down_since
                r.down_since = None
                self.last_restart_wall_s = wall
            r.backoff_s = self.opts.restart_backoff_s
        if quarantined and was != "quarantined":
            obs_events.emit("fleet.replica_quarantined", replica=r.idx,
                            role=r.role, port=r.port,
                            round=r.last_round, reasons=reasons)
        elif not quarantined and was == "quarantined":
            obs_events.emit("fleet.replica_readmitted", replica=r.idx,
                            role=r.role, port=r.port, round=r.last_round)
        elif not quarantined and was != "healthy":
            obs_events.emit("fleet.replica_up", replica=r.idx,
                            role=r.role, port=r.port, round=r.last_round,
                            restarts=r.restarts)
        if came_back:
            try:
                fleet_metrics().restart_seconds.observe(wall)
            except Exception:  # noqa: BLE001 - telemetry must never raise
                pass

    def _on_probe_fail(self, r: Replica, err: Optional[str]) -> None:
        with self._lock:
            if r.state == "starting":
                # a replica that has never answered is still booting;
                # the wedge counter does not apply (a JAX import
                # legitimately takes tens of seconds) — but the boot
                # budget does: a child wedged BEFORE its first healthy
                # answer must still be ejected and restarted, or it
                # escapes supervision forever
                if (time.monotonic() - r.spawned_at
                        <= self.opts.start_timeout_s):
                    return
                wedged = True
            else:
                r.consecutive_fail += 1
                wedged = r.consecutive_fail >= self.opts.slow_probes
            if not wedged:
                if r.state == "healthy":
                    r.state = "slow"
                    obs_events.emit("fleet.replica_slow", replica=r.idx,
                                    misses=r.consecutive_fail, error=err)
                return
        # ejected: kill the wedged process and schedule a restart
        self._on_down(r, "wedged", err or "probe deadline exceeded")

    def _on_down(self, r: Replica, reason: str, detail: str) -> None:
        with self._lock:
            if r.state in ("backoff", "failed", "stopped"):
                return
            r.state = "wedged" if reason == "wedged" else "gone"
            if r.down_since is None:
                r.down_since = time.monotonic()
            r.down_reason = reason
        obs_events.emit(
            "fleet.replica_wedged" if reason == "wedged"
            else "fleet.replica_gone",
            replica=r.idx, role=r.role, port=r.port, detail=detail)
        if self.on_down is not None:
            try:
                self.on_down(r)
            except Exception:  # noqa: BLE001 - eject must never wedge
                pass
        self._kill(r)
        with self._lock:
            if self.spawn_fn is None:
                return  # external replica: ejected, nothing to restart
            r.state = "backoff"
            r.restart_at = time.monotonic() + r.backoff_s

    def _respawn(self, r: Replica) -> None:
        with self._lock:
            if (self.opts.max_restarts
                    and r.restarts >= self.opts.max_restarts):
                # given up: no phantom restart in the counters
                r.state = "failed"
                obs_events.emit("fleet.replica_failed", replica=r.idx,
                                restarts=r.restarts)
                return
            r.restarts += 1
            self.restarts_total += 1
            reason = r.down_reason or "crash"
            r.backoff_s = min(r.backoff_s * 2,
                              self.opts.restart_backoff_max_s)
        try:
            fleet_metrics().restarts.labels(reason=reason).inc()
        except Exception:  # noqa: BLE001 - telemetry must never raise
            pass
        obs_events.emit("fleet.restart", replica=r.idx, role=r.role,
                        reason=reason, attempt=r.restarts,
                        next_backoff_s=round(r.backoff_s, 3))
        self._spawn(r)

    def restart_replica(self, r: Replica, reason: str,
                        role: Optional[str] = None) -> None:
        """Kill and relaunch one replica deliberately (the canary
        rollback path; ``role`` flips e.g. canary → serve so the spawn
        function drops the quant override)."""
        with self._lock:
            if role is not None:
                r.role = role
            r.state = "gone"
            r.down_reason = reason
            if r.down_since is None:
                r.down_since = time.monotonic()
        self._kill(r)
        try:
            fleet_metrics().restarts.labels(reason=reason).inc()
        except Exception:  # noqa: BLE001
            pass
        with self._lock:
            r.restarts += 1
            self.restarts_total += 1
        self._spawn(r)

    def _kill(self, r: Replica) -> None:
        proc = r.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
                proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                pass
        if r.log_handle is not None:
            try:
                r.log_handle.close()
            except OSError:
                pass
            r.log_handle = None

    def _export_gauges(self) -> None:
        try:
            m = fleet_metrics()
            counts = self.state_counts()
            for state in STATES:
                m.replicas.labels(state=state).set(counts.get(state, 0))
        except Exception:  # noqa: BLE001 - telemetry must never raise
            pass

    # ------------------------------------------------------------------
    def stop(self, term_timeout_s: float = 15.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        procs = []
        for r in self.replicas:
            r.state = "stopped"
            if r.proc is not None and r.proc.poll() is None:
                try:
                    r.proc.terminate()
                    procs.append(r)
                except OSError:
                    pass
        deadline = time.monotonic() + term_timeout_s
        for r in procs:
            try:
                r.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                r.proc.kill()
                try:
                    r.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
        for r in self.replicas:
            if r.log_handle is not None:
                try:
                    r.log_handle.close()
                except OSError:
                    pass
                r.log_handle = None


# ----------------------------------------------------------------------
#: config keys the fleet must pin on its replica children — a replica
#: re-reading the parent's conf must come up as a SINGLE-engine server
#: on the assigned port (``replicas=1`` appended last wins over a conf
#: that armed the fleet, so a fleet conf can never fork-bomb).  Any
#: OTHER override (``quant=``, ``alert=``, ...) passes through to the
#: children untouched — except ``quant`` while a canary is armed,
#: because then the canary controller owns per-role precision.
_REPLICA_PINNED_KEYS = ("replicas", "task", "serve_port", "serve_host",
                        "serve_reload_period", "controller")


def cli_spawn_fn(conf_path: str, overrides: Sequence[str],
                 host: str, opts: FleetOptions,
                 log_dir: str = "") -> Callable[[Replica], subprocess.Popen]:
    """Spawn function for REAL replicas: a full ``task=serve`` CLI
    child on the replica's port, re-reading the fleet's conf plus the
    fleet's own CLI overrides (minus the fleet-controlling keys, which
    are pinned).  Canary replicas get ``quant=<scheme>``; baseline
    replicas are pinned to f32 while a canary is armed so the
    comparison legs actually differ."""
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pinned = set(_REPLICA_PINNED_KEYS)
    if opts.canary:
        pinned.add("quant")  # per-role precision belongs to the canary
    keep = [o for o in overrides
            if o.split("=", 1)[0] not in pinned]

    def spawn(r: Replica) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "cxxnet_tpu", conf_path]
        cmd += keep
        cmd += [
            "task=serve", f"serve_host={host}",
            f"serve_port={r.port}", "serve_reload_period=0",
            "controller=0", "replicas=1",
        ]
        if opts.canary:
            cmd.append(f"quant={opts.canary}" if r.role == "canary"
                       else "quant=0")
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        stdout = subprocess.DEVNULL
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            r.log_handle = open(
                os.path.join(log_dir, f"replica-{r.idx}.log"), "ab")
            stdout = r.log_handle
        return subprocess.Popen(cmd, stdout=stdout,
                                stderr=subprocess.STDOUT, env=env)

    return spawn


def stub_spawn_fn(extra: Sequence[str] = (),
                  per_replica: Optional[Callable[[Replica],
                                                 Sequence[str]]] = None,
                  ) -> Callable[[Replica], subprocess.Popen]:
    """Spawn function for the stdlib stub replica (``serve/stub.py``,
    run as a file so nothing imports JAX) — the fast supervision /
    routing / canary tests.  ``per_replica(replica)`` appends
    per-instance args (e.g. ``--disagree`` for the canary)."""
    stub = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "stub.py")

    def spawn(r: Replica) -> subprocess.Popen:
        cmd = [sys.executable, stub, "--port", str(r.port)]
        cmd += list(extra)
        if per_replica is not None:
            cmd += list(per_replica(r))
        return subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    return spawn


# ----------------------------------------------------------------------
class CanaryController:
    """Measures the canary legs and decides promote vs rollback.

    The router feeds it: every mirrored comparison lands in
    :meth:`record_compare` (row-level equality of baseline vs canary
    predictions), every timed leg in :meth:`record_latency`.  The
    controller exports ``canary_agreement`` / ``canary_latency_ratio``
    gauges (created on FIRST data — a premature zero would instantly
    fire the rollback alert), auto-arms the
    ``canary_agreement < canary_min_agreement`` alert rule, and once
    ``canary_min_requests`` rows compared:

    * rule firing (the ``/alertz`` trigger) → **rollback**: publish
      pointer restored to the baseline round, ``canary.rollback``
      event, ``canary_total{decision="rollback"}``, canary replicas
      relaunched at f32;
    * otherwise (agreement at/above the bar) → **promote**: publish
      pointer flipped to the canary's artifact, ``canary.promote``,
      ``canary_total{decision="promote"}``, canary replicas join the
      rotation at full weight.
    """

    RULE_NAME = "canary_agreement"

    def __init__(self, supervisor: ReplicaSupervisor, opts: FleetOptions,
                 model_dir: Optional[str] = None,
                 silent: bool = True) -> None:
        self.sup = supervisor
        self.opts = opts
        self.model_dir = model_dir
        self.silent = silent
        self.state = "evaluating"   # evaluating | promoted | rolled_back
        self.decision_reason = ""
        self.compared = 0
        self.agreed = 0
        self._lat = {"baseline": [], "canary": []}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._agreement_gauge = None
        self._latency_gauge = None

    # ------------------------------------------------------------------
    def canaries(self) -> List[Replica]:
        return [r for r in self.sup.replicas if r.role == "canary"]

    def start(self) -> "CanaryController":
        self._arm_rule()
        self._thread = threading.Thread(
            target=self._loop, name="cxxnet-fleet-canary", daemon=True)
        self._thread.start()
        obs_events.emit("canary.start", scheme=self.opts.canary,
                        replicas=len(self.canaries()),
                        slice=self.opts.canary_slice,
                        sample=self.opts.canary_sample,
                        min_agreement=self.opts.canary_min_agreement)
        return self

    def _arm_rule(self) -> None:
        from ..obs import alerts as obs_alerts

        ev = obs_alerts.evaluator()
        if not any(r.name == self.RULE_NAME for r in ev.rules()):
            ev.add_rule(obs_alerts.parse_rule(
                f"{self.RULE_NAME}:canary_agreement:<:"
                f"{self.opts.canary_min_agreement:g}"))
        ev.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------------
    # measurement (router-fed)
    def record_compare(self, rows_equal: int, rows_total: int) -> None:
        with self._lock:
            self.compared += rows_total
            self.agreed += rows_equal
            agreement = self.agreed / self.compared if self.compared else 1.0
        self._gauges()[0].set(agreement)

    def record_latency(self, leg: str, dt_s: float) -> None:
        """Append-only — this runs on the live /predict path; the
        median ratio is computed once per decision period
        (:meth:`_update_latency_gauge`), not per request."""
        with self._lock:
            buf = self._lat[leg]
            buf.append(dt_s)
            if len(buf) > 512:
                del buf[: len(buf) - 512]

    def _update_latency_gauge(self) -> None:
        with self._lock:
            base = list(self._lat["baseline"])
            can = list(self._lat["canary"])
        if not base or not can:
            return
        med_b = sorted(base)[len(base) // 2]
        med_c = sorted(can)[len(can) // 2]
        if med_b > 0:
            self._gauges()[1].set(med_c / med_b)

    def _gauges(self):
        if self._agreement_gauge is None:
            reg = obs_registry()
            self._agreement_gauge = reg.gauge(
                "canary_agreement",
                "Row-level prediction agreement of the canary vs the "
                "baseline over mirrored traffic.")
            self._latency_gauge = reg.gauge(
                "canary_latency_ratio",
                "Canary / baseline median request latency over the "
                "compared legs.")
        return self._agreement_gauge, self._latency_gauge

    def agreement(self) -> Optional[float]:
        with self._lock:
            return (self.agreed / self.compared) if self.compared else None

    # ------------------------------------------------------------------
    # decision
    def _loop(self) -> None:
        while not self._stop.wait(self.opts.canary_decision_period_s):
            try:
                self.decide()
            except Exception as e:  # noqa: BLE001 - keep deciding
                obs_events.log_exception_once(
                    "fleet.canary_decide", e, kind="fleet.error")
            if self.state != "evaluating":
                return

    def decide(self) -> Optional[str]:
        """One decision pass (the loop body; tests drive it directly).
        Returns the decision when one was made."""
        if self.state != "evaluating":
            return None
        self._update_latency_gauge()
        with self._lock:
            compared = self.compared
        if compared < self.opts.canary_min_requests:
            return None
        from ..obs import alerts as obs_alerts

        ev = obs_alerts.evaluator()
        ev.evaluate_once()
        agreement = self.agreement()
        if self.RULE_NAME in ev.firing():
            self._rollback(f"alert {self.RULE_NAME} firing "
                           f"(agreement {agreement:.4f} < "
                           f"{self.opts.canary_min_agreement:g})")
            return "rollback"
        if agreement is not None \
                and agreement >= self.opts.canary_min_agreement:
            self._promote(agreement)
            return "promote"
        return None

    def _metric(self) -> dict:
        with self._lock:
            return {
                "canary_agreement": (self.agreed / self.compared
                                     if self.compared else None),
                "compared_rows": self.compared,
                "scheme": self.opts.canary,
            }

    def _baseline_replica(self) -> Optional[Replica]:
        cands = [r for r in self.sup.healthy() if r.role == "serve"]
        return cands[0] if cands else None

    def _write_pointer(self, round_: int, path: Optional[str],
                       metric: dict) -> None:
        """Promote/rollback both land through the existing publish-
        pointer machinery (doc/continuous_training.md) — the pointer is
        the fleet's 'currently blessed artifact' record."""
        if not self.model_dir or path is None or round_ < 0:
            return
        from ..utils import checkpoint as ckpt

        try:
            prev = ckpt.read_publish_pointer(self.model_dir)
            ckpt.write_publish_pointer(
                self.model_dir, round_, path, metric=metric,
                prev_round=prev.get("round") if prev else None)
        except Exception as e:  # noqa: BLE001 - decision still stands
            obs_events.log_exception_once(
                "fleet.canary_pointer", e, kind="fleet.error")

    def _promote(self, agreement: float) -> None:
        canary = next((r for r in self.canaries()
                       if r.state == "healthy"), None)
        self.state = "promoted"
        self.decision_reason = f"agreement {agreement:.4f}"
        try:
            fleet_metrics().canary_total.labels(decision="promote").inc()
        except Exception:  # noqa: BLE001
            pass
        obs_events.emit("canary.promote", scheme=self.opts.canary,
                        agreement=round(agreement, 6),
                        compared=self.compared,
                        round=canary.last_round if canary else None,
                        path=canary.last_model if canary else None)
        if canary is not None:
            self._write_pointer(canary.last_round, canary.last_model,
                                self._metric())
        # full weight: the router includes promoted canaries in the
        # baseline pool (it checks controller.state)
        if not self.silent:
            print(f"fleet: canary PROMOTED ({self.decision_reason})",
                  flush=True)

    def _rollback(self, reason: str) -> None:
        self.state = "rolled_back"
        self.decision_reason = reason
        try:
            fleet_metrics().canary_total.labels(decision="rollback").inc()
        except Exception:  # noqa: BLE001
            pass
        agreement = self.agreement()
        obs_events.emit("canary.rollback", scheme=self.opts.canary,
                        reason=reason,
                        agreement=(round(agreement, 6)
                                   if agreement is not None else None),
                        compared=self.compared)
        base = self._baseline_replica()
        if base is not None:
            self._write_pointer(base.last_round, base.last_model,
                                self._metric())
        # relaunch the canary replicas as plain f32 members
        for r in self.canaries():
            self.sup.restart_replica(r, reason="canary_rollback",
                                     role="serve")
        # the comparison is over — clear the trigger gauge so /alertz
        # does not report the dead canary's agreement forever (the
        # durable record is canary_total{decision} + the event above)
        self._gauges()[0].set(1.0)
        if not self.silent:
            print(f"fleet: canary ROLLED BACK ({reason})", flush=True)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "scheme": self.opts.canary,
                "state": self.state,
                "reason": self.decision_reason,
                "compared": self.compared,
                "agreed": self.agreed,
                "agreement": (self.agreed / self.compared
                              if self.compared else None),
                "slice": self.opts.canary_slice,
                "sample": self.opts.canary_sample,
                "min_agreement": self.opts.canary_min_agreement,
            }


# ----------------------------------------------------------------------
class ServingFleet:
    """Supervisor + router + canary + rolling reload, composed.

    The CLI's ``task=serve`` with ``replicas >= 2`` builds one of these
    (``cli.py::task_serve_fleet``); tests compose the pieces directly
    with stub spawn functions."""

    def __init__(self, opts: FleetOptions,
                 spawn_fn: Optional[Callable] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 model_dir: Optional[str] = None,
                 default_deadline_ms: float = 0.0,
                 reload_period_s: float = 0.0,
                 silent: bool = True) -> None:
        from .router import FleetRouter

        self.opts = opts
        self.host = host
        self.port = port
        self.model_dir = model_dir
        self.reload_period_s = float(reload_period_s)
        self.silent = silent
        self.supervisor = ReplicaSupervisor(opts, spawn_fn=spawn_fn,
                                            host=host)
        self.canary: Optional[CanaryController] = (
            CanaryController(self.supervisor, opts, model_dir=model_dir,
                             silent=silent)
            if opts.canary else None)
        self.router = FleetRouter(self, default_deadline_ms=
                                  default_deadline_ms)
        self.supervisor.on_down = (
            lambda r: self.router.retire_replica_pool(r.address))
        self.reload_breaker = CircuitBreaker(
            failure_threshold=opts.reload_breaker_threshold,
            cooldown_s=60.0)
        self._reload_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.httpd = None

    # ------------------------------------------------------------------
    def start(self, min_healthy: Optional[int] = None):
        """Spawn replicas, wait for readiness, bind the front door.
        Returns the router's HTTP server (caller runs
        ``serve_forever``)."""
        self.supervisor.start()
        want = min_healthy if min_healthy is not None else len(
            self.supervisor.replicas)
        if not self.supervisor.wait_ready(min_healthy=want):
            if not self.supervisor.wait_ready(timeout_s=0.0,
                                              min_healthy=1):
                self.supervisor.stop()
                raise RuntimeError(
                    f"fleet: no replica became healthy within "
                    f"{self.opts.start_timeout_s:g}s")
            if not self.silent:
                print("fleet: starting DEGRADED (not all replicas "
                      "healthy in time)", flush=True)
        if self.canary is not None:
            self.canary.start()
        self.httpd = self.router.make_httpd(self.host, self.port)
        if self.reload_period_s > 0 and self.model_dir:
            self._reload_thread = threading.Thread(
                target=self._reload_loop, name="cxxnet-fleet-reload",
                daemon=True)
            self._reload_thread.start()
        return self.httpd

    # ------------------------------------------------------------------
    # rolling reload
    def _reload_loop(self) -> None:
        from ..utils import checkpoint as ckpt

        while not self._stop.wait(self.reload_period_s):
            try:
                found = ckpt.find_latest_valid(self.model_dir, silent=True)
            except Exception:  # noqa: BLE001 - keep polling
                continue
            if found is None:
                continue
            rounds = [r.last_round for r in self.supervisor.rotation()]
            if rounds and found[0] > min(rounds):
                self.rolling_reload(target_round=found[0])

    def rolling_reload(self, target_round: Optional[int] = None) -> dict:
        """Walk the rotation one replica at a time, reloading each
        through ``POST /reloadz`` and waiting for it to probe healthy
        on the new round before the next.  Breaker-gated: repeated
        failures abort the rollout and the remaining replicas keep the
        old model."""
        results = []
        aborted = False
        obs_events.emit("fleet.rollout_start", target_round=target_round)
        m = fleet_metrics()
        for r in list(self.supervisor.replicas):
            if not r.in_rotation():
                continue
            if not self.reload_breaker.allow():
                aborted = True
                m.reloads.labels(result="aborted").inc()
                obs_events.emit("fleet.rollout_abort", replica=r.idx,
                                breaker=self.reload_breaker.state)
                break
            ok, swapped, round_, err = self._reload_one(r, target_round)
            # the swapped engine invalidates any parked connection's
            # implicit model identity — start the replica's pool fresh
            self.router.retire_replica_pool(r.address)
            results.append({"replica": r.idx, "ok": ok,
                            "swapped": swapped, "round": round_,
                            "error": err})
            if ok:
                self.reload_breaker.record_success()
                m.reloads.labels(
                    result="swapped" if swapped else "noop").inc()
            else:
                self.reload_breaker.record_failure()
                m.reloads.labels(result="failed").inc()
                obs_events.emit("fleet.reload_failed", replica=r.idx,
                                error=err)
        out = {"aborted": aborted, "replicas": results,
               "target_round": target_round}
        obs_events.emit("fleet.rollout_done", aborted=aborted,
                        reloaded=sum(1 for x in results if x["ok"]))
        return out

    def _reload_one(self, r: Replica, target_round: Optional[int]):
        try:
            resp = _http_post_json(r.address, "/reloadz", {},
                                   self.opts.reload_timeout_s)
        except Exception as e:  # noqa: BLE001 - reported per replica
            return False, False, r.last_round, f"{type(e).__name__}: {e}"
        if not resp.get("ok"):
            return False, False, resp.get("round", r.last_round), \
                f"reload failed (breaker {resp.get('breaker')})"
        swapped = bool(resp.get("swapped"))
        round_ = resp.get("round", r.last_round)
        # wait for the replica to probe healthy on the new round before
        # touching the next one — the "one at a time" guarantee
        deadline = time.monotonic() + self.opts.reload_timeout_s
        while time.monotonic() < deadline:
            okp, body, _err = self.supervisor._probe_http(r)
            if okp and (target_round is None
                        or int(body.get("round", -1)) >= target_round
                        or not swapped):
                self.supervisor._on_probe_ok(r, body)
                return True, swapped, body.get("round", round_), None
            time.sleep(min(0.2, self.opts.probe_period_s))
        return False, swapped, round_, "not healthy after reload"

    # ------------------------------------------------------------------
    # aggregation (served by the router)
    def healthz(self) -> Dict[str, object]:
        counts = self.supervisor.state_counts()
        rotation = self.supervisor.rotation()
        reasons: List[str] = []
        with self.supervisor._lock:
            for r in self.supervisor.replicas:
                if r.state == "stopped":
                    continue
                if r.state != "healthy":
                    reasons.append(f"replica{r.idx}:{r.state}")
                else:
                    for why in r.reasons:
                        reasons.append(f"replica{r.idx}:{why}")
        status = ("down" if not rotation
                  else "degraded" if reasons else "ok")
        out: Dict[str, object] = {
            "status": status,
            "fleet": True,
            "replicas": {
                "total": len(self.supervisor.replicas),
                **{s: counts.get(s, 0) for s in STATES},
            },
            "rotation": len(rotation),
            "round": (min(r.last_round for r in rotation)
                      if rotation else -1),
            "reasons": reasons,
        }
        if self.canary is not None:
            out["canary"] = {"state": self.canary.state,
                             "agreement": self.canary.agreement()}
        return out

    def statsz(self) -> Dict[str, object]:
        out = self.router.stats.snapshot()
        out["replicas"] = [r.snapshot() for r in self.supervisor.replicas]
        out["last_restart_wall_s"] = self.supervisor.last_restart_wall_s
        out["restarts_total"] = self.supervisor.restarts_total
        out["reload_breaker"] = self.reload_breaker.snapshot()
        if self.canary is not None:
            out["canary"] = self.canary.snapshot()
        return out

    # ------------------------------------------------------------------
    def close(self, drain_timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._reload_thread is not None:
            self._reload_thread.join(timeout=5.0)
            self._reload_thread = None
        if self.canary is not None:
            self.canary.stop()
        self.router.close(drain_timeout_s)
        if self.httpd is not None:
            try:
                self.httpd.server_close()
            except OSError:
                pass
            self.httpd = None
        self.supervisor.stop()

    def __enter__(self) -> "ServingFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
