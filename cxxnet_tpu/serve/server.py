"""HTTP front-end: a stdlib JSON endpoint over :class:`Engine`.

``ThreadingHTTPServer`` gives one thread per connection; every handler
thread blocks in ``Engine.submit`` while the micro-batcher coalesces the
concurrent requests into shared device calls — the threading model IS
the batching opportunity.  Endpoints:

* ``POST /predict``  — ``{"data": [[...], ...]}`` → ``{"pred": [...]}``
  (add ``"raw": true`` for the full score rows)
* ``POST /extract``  — ``{"data": ..., "node": "fc1"}`` →
  ``{"features": [[...], ...]}``

Both data routes also negotiate the binary zero-copy wire
(``serve/wire.py``; doc/serving.md "Binary wire protocol"): a request
with ``Content-Type: application/x-cxb`` carries a ``CXB1`` frame whose
payload is decoded with ``np.frombuffer`` straight into the
micro-batcher, and the response streams raw f32 rows back as a ``CXR1``
frame — no ``tolist()``, no ``json.dumps``.  JSON requests are
byte-for-byte unchanged; malformed frames are 400 with a stable
``reason`` token, and error bodies are always JSON.
* ``POST /feedback`` — ``{"data": [[...], ...], "label": [...]}`` →
  ``{"appended": n}``: append labeled instances to the closed-loop
  feedback log (``task=serve_train``; doc/continuous_training.md).
  Append failures DEGRADE — records drop and are counted
  (``loop_feedback_dropped_total``), the request still succeeds.
  With capture mode armed (``capture_predict = 1``) every successful
  ``/predict`` also logs its inputs with the model's own predictions
  as labels (self-training capture).
* ``GET  /healthz``  — liveness + model identity (round, fingerprint);
  degrades while any alert rule is firing, the reload breaker is open,
  or a colocated trainer is mid mesh-rebuild — with every degrade
  condition spelled out in a machine-readable ``reasons`` list (what
  the fleet supervisor's probe parses; doc/serving.md)
* ``POST /reloadz``  — admin: trigger one breaker-gated hot-reload
  attempt (``Engine.try_reload``) and report
  ``{ok, swapped, round, breaker}`` — the fleet's rolling-reload
  rendezvous (``serve/fleet.py``); empty body allowed
* ``GET  /statsz``   — serving metrics (see ``metrics.py``)
* ``GET  /metricsz`` — Prometheus text exposition of the process-wide
  metrics registry (``cxxnet_tpu/obs/registry.py``): request outcomes,
  batch fill/coalescing, latency histogram, reload counters, pipeline
  stages, device-plane families — the scrape target
  (doc/observability.md)
* ``GET  /alertz``   — the alert evaluator's rules + live firing state
  as JSON (``alert=`` config rules; ``cxxnet_tpu/obs/alerts.py``)

Every POST response carries a minted correlation id (``rid``), and a
``/feedback`` response additionally carries the durable lineage id
range its accepted records were assigned (``seq: [first, last]``) —
the handle ``PUBLISHED.json``'s lineage block later refers back to
(doc/continuous_training.md).

Errors map to JSON bodies with meaningful statuses: 400 malformed
request, 404 unknown route, 429 load shed, 503 shutting down, 504
deadline expired, 500 model failure.

Lifecycle (doc/robustness.md): every request is tracked by an in-flight
gauge; on shutdown the server stops accepting, then **drains** — waits
up to ``drain_timeout_s`` for in-flight requests to finish writing their
responses — before the engine closes, so a SIGTERM under load never
drops a request whose handler has begun executing.  (A connection still
parsing its request line/headers at shutdown is not yet counted; if it
reaches the engine after the drain it gets a clean 503, not a hang.)  The hot-reload poll thread routes through
``Engine.try_reload`` (circuit breaker + ``reload_failures`` /
``last_reload_ok`` in ``/statsz``) instead of printing and retrying a
broken reload at full poll rate.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from ..utils import faults
from . import wire
from .batcher import ServeError
from .engine import Engine
from .metrics import serve_metrics

__all__ = ["make_server", "serve_forever", "replica_fault_probe"]

MAX_BODY_BYTES = 64 << 20  # reject absurd request bodies outright


def replica_fault_probe() -> None:
    """The ``serve.replica`` chaos site (doc/robustness.md), fired on
    every ``/healthz`` probe of this replica:

    * ``hang`` — the probe response blocks: this replica is WEDGED.
      The fleet supervisor's probe deadline classifies it SLOW →
      ejected from rotation; a standalone server just looks unhealthy
      to its load balancer.
    * ``ioerror`` — the replica CRASHES (exit code 13), the abrupt
      process loss a real fault produces; the fleet supervisor must
      restart it with backoff.

    No-op while the site is disarmed (the common case)."""
    try:
        faults.fault_point("serve.replica")
    except faults.InjectedFault:
        from ..obs import events as obs_events

        obs_events.emit("serve.replica_crash", injected=True)
        os._exit(13)


class _InflightGauge:
    """Counts requests between accept and response-written, and lets
    shutdown wait for the count to reach zero (the drain)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self.count = 0

    def __enter__(self) -> "_InflightGauge":
        with self._lock:
            self.count += 1
        return self

    def __exit__(self, *exc) -> None:
        with self._idle:
            self.count -= 1
            if self.count == 0:
                self._idle.notify_all()

    def wait_idle(self, timeout_s: float) -> bool:
        """Block until no request is in flight; False on timeout."""
        deadline = time.monotonic() + timeout_s
        with self._idle:
            while self.count > 0:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._idle.wait(timeout=remain)
        return True


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    engine: Engine = None  # bound by make_server via subclassing
    inflight: _InflightGauge = None
    verbose = False
    feedback = None  # FeedbackWriter when the loop is armed
    capture_predict = False  # log /predict inputs + predictions
    # per-model routing (serve/router.py ModelRouter): when armed, a
    # request's "model" field selects the tenant engine + feedback log;
    # model-less requests take the default route, unknown models get a
    # 404 with the machine-readable "unknown_model" reason token
    router = None
    # correlation ids: a short per-server token + a monotonic counter,
    # minted per POST and echoed in the response as "rid" so a client
    # can tie its request to server-side events and feedback lineage
    rid_token = "srv"
    rid_counter = None  # itertools.count, bound by make_server

    def _mint_rid(self) -> str:
        return f"{self.rid_token}-{next(self.rid_counter)}"

    # ------------------------------------------------------------------
    def log_message(self, fmt, *args):  # noqa: N802 - stdlib name
        if self.verbose:
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _reply(self, status: int, payload: dict) -> None:
        self._reply_text(status, json.dumps(payload), "application/json")

    def _reply_text(self, status: int, text: str, content_type: str) -> None:
        body = text.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self, rid: str) -> Optional[bytes]:
        """Read the request body under the size bound, or reply 400 and
        return None.  Every reject that leaves bytes unread (oversized,
        or a framing we cannot drain) also closes the connection so the
        unread bytes can never desync the next request on a kept-alive
        HTTP/1.1 socket."""
        if self.headers.get("Transfer-Encoding"):
            # stdlib handlers do not decode chunked bodies; an undrained
            # chunked stream would wedge keep-alive framing
            self.close_connection = True
            self._reply(400, {"error": "chunked bodies are not supported",
                              "rid": rid})
            return None
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length > MAX_BODY_BYTES:
            self.close_connection = True
            self._reply(400, {"error": "oversized body", "rid": rid})
            return None
        if length <= 0:
            self._reply(400, {"error": "missing body", "rid": rid})
            return None
        return self.rfile.read(length)

    def _read_json(self, rid: str) -> Optional[dict]:
        body = self._read_body(rid)
        if body is None:
            return None
        try:
            obj = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            self._reply(400, {"error": f"bad JSON: {e}", "rid": rid})
            return None
        if not isinstance(obj, dict) or "data" not in obj:
            self._reply(400, {"error": 'body must be {"data": [...]}',
                              "rid": rid})
            return None
        return obj

    # ------------------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 - stdlib name
        with self.inflight:
            if self.path == "/healthz":
                replica_fault_probe()  # serve.replica chaos site
                h = self.engine.healthz()
                if self.router is not None:
                    h["models"] = self.router.healthz_models()
                self._reply(200, h)
            elif self.path == "/statsz":
                st = self.engine.snapshot_stats()
                if self.router is not None:
                    st["models"] = self.router.models()
                self._reply(200, st)
            elif self.path == "/metricsz":
                from ..obs import registry as obs_registry

                self._reply_text(
                    200, obs_registry().render_prometheus(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif self.path == "/alertz":
                from ..obs import alerts as obs_alerts

                self._reply(200, obs_alerts.evaluator().status())
            else:
                self._reply(404, {"error": f"unknown route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 - stdlib name
        with self.inflight:
            self._do_post()

    def _do_post(self) -> None:
        rid = self._mint_rid()
        if self.path == "/reloadz":
            # admin route (no body needed): one breaker-gated reload
            # attempt — the fleet's rolling-reload rendezvous.  Any
            # body sent must still be drained, or its bytes desync the
            # next request on a kept-alive HTTP/1.1 connection
            try:
                length = int(self.headers.get("Content-Length", 0) or 0)
            except ValueError:
                length = 0
            if length > MAX_BODY_BYTES:
                # cannot drain it: close the connection so the unread
                # bytes can never desync a follow-up request
                self.close_connection = True
                self._reply(400, {"error": "oversized body", "rid": rid})
                return
            body = self.rfile.read(length) if length > 0 else b""
            engine = self.engine
            if self.router is not None and body:
                # model-aware reload: {"model": <name>} picks the
                # tenant whose engine should attempt the swap
                try:
                    req = json.loads(body.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    req = {}
                if isinstance(req, dict) and req.get("model"):
                    from .router import UnknownModelError

                    try:
                        _n, engine, _fb = self.router.resolve(
                            req["model"])
                    except UnknownModelError as e:
                        self._reply(404, {"error": str(e),
                                          "reason": e.reason,
                                          "models": e.known,
                                          "rid": rid})
                        return
            swapped = engine.try_reload()
            self._reply(200, {
                "ok": engine.stats.last_reload_ok is not False,
                "swapped": bool(swapped),
                "round": engine.round,
                "breaker": engine.reload_breaker.state,
                "rid": rid,
            })
            return
        if self.path not in ("/predict", "/extract", "/feedback"):
            self._reply(404, {"error": f"unknown route {self.path}",
                              "rid": rid})
            return
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        if ctype.strip().lower() == wire.CONTENT_TYPE:
            self._do_post_wire(rid)
            return
        obj = self._read_json(rid)
        if obj is None:
            return
        if self.path != "/feedback":
            serve_metrics().wire_requests.labels(wire="json").inc()
        engine, feedback = self.engine, self.feedback
        if self.router is not None:
            from .router import UnknownModelError

            try:
                _name, engine, feedback = self.router.resolve(
                    obj.get("model"))
            except UnknownModelError as e:
                self._reply(404, {"error": str(e), "reason": e.reason,
                                  "models": e.known, "rid": rid})
                return
        deadline = obj.get("deadline_ms")
        try:
            if self.path == "/feedback":
                self._do_feedback(obj, rid, feedback)
            elif self.path == "/extract":
                node = obj.get("node")
                if not node:
                    self._reply(400, {"error": "extract needs a node name",
                                      "rid": rid})
                    return
                out = engine.extract(obj["data"], node,
                                     deadline_ms=deadline)
                self._reply(200, {"features": out.tolist(), "rid": rid})
            else:
                kind = "scores" if obj.get("raw") else "predict"
                out = engine.submit(obj["data"], kind=kind,
                                    deadline_ms=deadline)
                key = "scores" if kind == "scores" else "pred"
                self._reply(200, {key: np.asarray(out).tolist(),
                                  "rid": rid})
                # capture AFTER the reply: a page commit's fsyncs must
                # never sit inside the client's request latency
                if (self.capture_predict and feedback is not None
                        and kind == "predict"):
                    self._capture(obj["data"], out, feedback)
        except ServeError as e:
            self._reply(e.http_status, {"error": str(e), "rid": rid})
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e), "rid": rid})
        except Exception as e:  # noqa: BLE001 - served as a 500
            self._reply(500, {"error": f"{type(e).__name__}: {e}",
                              "rid": rid})

    def _do_post_wire(self, rid: str) -> None:
        """The ``application/x-cxb`` data plane (doc/serving.md
        "Binary wire protocol"): decode the frame with ``np.frombuffer``
        straight over the request body (zero-copy into the
        micro-batcher), stream raw f32 rows back.  Malformed frames are
        400 with a stable ``reason`` token, never a 500; error bodies
        stay JSON so a failing client can always read them."""
        m = serve_metrics()
        # drain the body BEFORE any reject: unread bytes would desync
        # the next request on this kept-alive socket
        body = self._read_body(rid)
        if body is None:
            return
        m.wire_requests.labels(wire="binary").inc()
        m.wire_bytes.labels(dir="in").inc(len(body))
        if self.path == "/feedback":
            self._reply(400, {
                "error": "binary wire covers /predict and /extract; "
                         "/feedback stays JSON",
                "reason": "wire_unsupported_route", "rid": rid})
            return
        try:
            req = wire.decode_request(body)
        except wire.WireError as e:
            self._reply(400, {"error": str(e), "reason": e.reason,
                              "rid": rid})
            return
        engine, feedback = self.engine, self.feedback
        if self.router is not None:
            from .router import UnknownModelError

            try:
                _name, engine, feedback = self.router.resolve(
                    req.model or None)
            except UnknownModelError as e:
                self._reply(404, {"error": str(e), "reason": e.reason,
                                  "models": e.known, "rid": rid})
                return
        if getattr(engine, "wire", "binary") != "binary":
            self._reply(400, {
                "error": "binary wire is disabled (wire = json)",
                "reason": "wire_disabled", "rid": rid})
            return
        try:
            if self.path == "/extract":
                if req.kind != "extract" or not req.node:
                    self._reply(400, {
                        "error": "extract frames need kind=extract and "
                                 "a node name", "reason": "bad_kind",
                        "rid": rid})
                    return
                kind = "extract"
                out = engine.extract(req.data, req.node,
                                     deadline_ms=req.deadline_ms)
            else:
                if req.kind not in ("predict", "scores"):
                    self._reply(400, {
                        "error": f"/predict frames carry kind predict "
                                 f"or scores, not {req.kind}",
                        "reason": "bad_kind", "rid": rid})
                    return
                kind = req.kind
                out = engine.submit(req.data, kind=kind,
                                    deadline_ms=req.deadline_ms)
            head, payload = wire.encode_response_header(
                np.asarray(out), kind, rid)
            self.send_response(200)
            self.send_header("Content-Type", wire.CONTENT_TYPE)
            self.send_header("Content-Length",
                             str(len(head) + payload.nbytes))
            self.end_headers()
            # header then the array's own buffer: the scores leave the
            # process without a tolist() or a joined-body copy
            self.wfile.write(head)
            self.wfile.write(memoryview(payload).cast("B"))
            m.wire_bytes.labels(dir="out").inc(len(head) + payload.nbytes)
            if (self.capture_predict and feedback is not None
                    and kind == "predict"):
                self._capture(req.data, out, feedback)
        except ServeError as e:
            self._reply(e.http_status, {"error": str(e), "rid": rid})
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e), "rid": rid})
        except Exception as e:  # noqa: BLE001 - served as a 500
            self._reply(500, {"error": f"{type(e).__name__}: {e}",
                              "rid": rid})

    @staticmethod
    def _feedback_arrays(obj: dict):
        """Normalize a feedback body to ``(data (N, ...), label (N, L))``."""
        data = np.ascontiguousarray(obj["data"], np.float32)
        if data.ndim == 1:
            data = data[None, :]
        if "label" not in obj:
            raise ValueError('feedback needs {"data": ..., "label": ...}')
        label = np.atleast_1d(
            np.ascontiguousarray(obj["label"], np.float32))
        if label.ndim == 1:
            label = label[:, None]
        if label.shape[0] != data.shape[0]:
            raise ValueError(
                f"feedback: {data.shape[0]} data rows vs "
                f"{label.shape[0]} labels")
        return data, label

    def _do_feedback(self, obj: dict, rid: str, feedback) -> None:
        if feedback is None:
            self._reply(404, {
                "error": "no feedback log armed (run task=serve_train "
                         "or task=loop_fleet)",
                "rid": rid,
            })
            return
        data, label = self._feedback_arrays(obj)
        n, first, last = feedback.append_batch_ids(data, label)
        self._reply(200, {"appended": n,
                          "dropped": data.shape[0] - n,
                          "seq": ([first, last] if first is not None
                                  else None),
                          "rid": rid})

    def _capture(self, data, preds, feedback) -> None:
        """Opt-in /predict capture: inputs + model predictions into the
        feedback log.  Never fails the request — the log's degrade
        discipline applies to capture too."""
        try:
            arr = np.ascontiguousarray(data, np.float32)
            if arr.ndim == 1:
                arr = arr[None, :]
            feedback.append_batch(
                arr, np.asarray(preds, np.float32).reshape(arr.shape[0], -1))
        except Exception as e:  # noqa: BLE001 - capture is best-effort
            from ..obs import log_exception_once

            log_exception_once("serve.capture", e,
                               kind="loop.append_error", capture=True)


def make_server(
    engine: Engine,
    host: str = "127.0.0.1",
    port: int = 0,
    verbose: bool = False,
    feedback=None,
    capture_predict: bool = False,
    router=None,
) -> ThreadingHTTPServer:
    """Bind (but do not run) the HTTP server; ``port=0`` picks an
    ephemeral port — read it back from ``server.server_port``.  The
    in-flight gauge hangs off the server as ``httpd.inflight``.
    ``feedback`` (a :class:`~cxxnet_tpu.loop.feedback_log.
    FeedbackWriter`) arms the ``/feedback`` route; ``capture_predict``
    additionally logs every successful ``/predict``.  ``router`` (a
    :class:`~cxxnet_tpu.serve.router.ModelRouter`) arms per-model
    dispatch: the request's ``model`` field picks the engine + feedback
    log, ``engine`` remains the identity/default route."""
    gauge = _InflightGauge()
    handler = type(
        "BoundHandler", (_Handler,),
        {"engine": engine, "verbose": verbose, "inflight": gauge,
         "feedback": feedback, "capture_predict": capture_predict,
         "router": router,
         "rid_token": os.urandom(3).hex(),
         "rid_counter": itertools.count(1)},
    )
    class _ServeHTTPServer(ThreadingHTTPServer):
        daemon_threads = True
        # survive a client fleet connecting at once (the stdlib
        # default listen backlog of 5 refuses the overflow)
        request_queue_size = 128

    httpd = _ServeHTTPServer((host, port), handler)
    httpd.inflight = gauge
    return httpd


def serve_forever(
    engine: Engine,
    host: str = "127.0.0.1",
    port: int = 0,
    reload_period_s: float = 0.0,
    drain_timeout_s: float = 5.0,
    verbose: bool = False,
    ready_fn=None,
    feedback=None,
    capture_predict: bool = False,
    router=None,
) -> Tuple[ThreadingHTTPServer, Optional[threading.Thread]]:
    """Run the server until ``httpd.shutdown()`` (blocking).

    ``reload_period_s > 0`` starts a background thread polling
    ``engine.try_reload()`` — hot model reload behind the circuit
    breaker, without dropping a request.  ``ready_fn(httpd)`` is called
    once the socket is bound, before serving (the CLI prints the actual
    port there).

    Shutdown is a graceful drain: after ``httpd.shutdown()`` stops the
    accept loop, in-flight requests get up to ``drain_timeout_s`` to
    finish writing their responses before this function returns (the
    caller then closes the engine, which 503s anything still queued)."""
    httpd = make_server(engine, host, port, verbose=verbose,
                        feedback=feedback,
                        capture_predict=capture_predict,
                        router=router)
    stop = threading.Event()
    reloader = None
    # the poll covers every routed engine (multi-tenant servers reload
    # each tenant's model_dir), falling back to the identity engine
    all_engines = (router.engines() if router is not None else [engine])
    poll_engines = [e for e in all_engines if e.model_dir is not None]
    # the integrity canary (doc/robustness.md "Integrity plane") rides
    # the same cadence: re-score the golden probe between reload polls
    canary_engines = [e for e in all_engines
                      if getattr(e, "integrity_probe", 0)]
    if reload_period_s > 0 and (poll_engines or canary_engines):
        def _poll():
            while not stop.wait(reload_period_s):
                for e in poll_engines:
                    e.try_reload()  # breaker-gated; never raises
                for e in canary_engines:
                    e.check_canary()  # latches /healthz; never raises

        reloader = threading.Thread(
            target=_poll, name="cxxnet-serve-reload", daemon=True
        )
        reloader.start()
    if ready_fn is not None:
        ready_fn(httpd)
    try:
        httpd.serve_forever(poll_interval=0.2)
    finally:
        stop.set()
        if drain_timeout_s > 0 and not httpd.inflight.wait_idle(
                drain_timeout_s):
            print(
                f"serve: drain timed out after {drain_timeout_s:g}s with "
                f"{httpd.inflight.count} request(s) still in flight",
                flush=True,
            )
        httpd.server_close()
    return httpd, reloader
