"""HTTP front-end: a stdlib JSON endpoint over :class:`Engine`.

``ThreadingHTTPServer`` gives one thread per connection; every handler
thread blocks in ``Engine.submit`` while the micro-batcher coalesces the
concurrent requests into shared device calls — the threading model IS
the batching opportunity.  Endpoints:

* ``POST /predict``  — ``{"data": [[...], ...]}`` → ``{"pred": [...]}``
  (add ``"raw": true`` for the full score rows)
* ``POST /extract``  — ``{"data": ..., "node": "fc1"}`` →
  ``{"features": [[...], ...]}``
* ``GET  /healthz``  — liveness + model identity (round, fingerprint)
* ``GET  /statsz``   — serving metrics (see ``metrics.py``)

Errors map to JSON bodies with meaningful statuses: 400 malformed
request, 404 unknown route, 429 load shed, 503 shutting down, 504
deadline expired, 500 model failure.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from .batcher import ServeError
from .engine import Engine

__all__ = ["make_server", "serve_forever"]

MAX_BODY_BYTES = 64 << 20  # reject absurd request bodies outright


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    engine: Engine = None  # bound by make_server via subclassing
    verbose = False

    # ------------------------------------------------------------------
    def log_message(self, fmt, *args):  # noqa: N802 - stdlib name
        if self.verbose:
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _reply(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> Optional[dict]:
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length <= 0 or length > MAX_BODY_BYTES:
            self._reply(400, {"error": "missing or oversized body"})
            return None
        try:
            obj = json.loads(self.rfile.read(length).decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            self._reply(400, {"error": f"bad JSON: {e}"})
            return None
        if not isinstance(obj, dict) or "data" not in obj:
            self._reply(400, {"error": 'body must be {"data": [...]}'})
            return None
        return obj

    # ------------------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 - stdlib name
        if self.path == "/healthz":
            self._reply(200, self.engine.healthz())
        elif self.path == "/statsz":
            self._reply(200, self.engine.snapshot_stats())
        else:
            self._reply(404, {"error": f"unknown route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 - stdlib name
        if self.path not in ("/predict", "/extract"):
            self._reply(404, {"error": f"unknown route {self.path}"})
            return
        obj = self._read_json()
        if obj is None:
            return
        deadline = obj.get("deadline_ms")
        try:
            if self.path == "/extract":
                node = obj.get("node")
                if not node:
                    self._reply(400, {"error": "extract needs a node name"})
                    return
                out = self.engine.extract(obj["data"], node,
                                          deadline_ms=deadline)
                self._reply(200, {"features": out.tolist()})
            else:
                kind = "scores" if obj.get("raw") else "predict"
                out = self.engine.submit(obj["data"], kind=kind,
                                         deadline_ms=deadline)
                key = "scores" if kind == "scores" else "pred"
                self._reply(200, {key: np.asarray(out).tolist()})
        except ServeError as e:
            self._reply(e.http_status, {"error": str(e)})
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 - served as a 500
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})


def make_server(
    engine: Engine,
    host: str = "127.0.0.1",
    port: int = 0,
    verbose: bool = False,
) -> ThreadingHTTPServer:
    """Bind (but do not run) the HTTP server; ``port=0`` picks an
    ephemeral port — read it back from ``server.server_port``."""
    handler = type(
        "BoundHandler", (_Handler,), {"engine": engine, "verbose": verbose}
    )
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_threads = True
    return httpd


def serve_forever(
    engine: Engine,
    host: str = "127.0.0.1",
    port: int = 0,
    reload_period_s: float = 0.0,
    verbose: bool = False,
    ready_fn=None,
) -> Tuple[ThreadingHTTPServer, Optional[threading.Thread]]:
    """Run the server until ``httpd.shutdown()`` (blocking).

    ``reload_period_s > 0`` starts a background thread polling
    ``engine.reload_if_newer()`` — hot model reload without dropping a
    request.  ``ready_fn(httpd)`` is called once the socket is bound,
    before serving (the CLI prints the actual port there)."""
    httpd = make_server(engine, host, port, verbose=verbose)
    stop = threading.Event()
    reloader = None
    if reload_period_s > 0 and engine.model_dir is not None:
        def _poll():
            while not stop.wait(reload_period_s):
                try:
                    engine.reload_if_newer()
                except Exception as e:  # noqa: BLE001 - keep serving
                    print(f"serve: reload failed: {e}", flush=True)

        reloader = threading.Thread(
            target=_poll, name="cxxnet-serve-reload", daemon=True
        )
        reloader.start()
    if ready_fn is not None:
        ready_fn(httpd)
    try:
        httpd.serve_forever(poll_interval=0.2)
    finally:
        stop.set()
        httpd.server_close()
    return httpd, reloader
