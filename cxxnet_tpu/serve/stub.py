#!/usr/bin/env python
"""Lightweight replica stand-in for serving-fleet tests.

A real fleet replica is a full ``task=serve`` CLI process — a JAX
import, a checkpoint load, and compiled predict programs, i.e. tens of
seconds of startup.  The supervision/routing/canary logic in
``serve/fleet.py`` and ``serve/router.py`` does not care what is behind
the replica's HTTP surface, so the fast tier-1 tests drive it against
this stub: a **stdlib-only** script (no package import, no numpy, no
JAX) that answers the same endpoints the fleet speaks to a real
replica, starts in ~100 ms, and can be told to misbehave in the exact
ways the supervisor must survive:

* ``--delay-ms`` — every ``/predict`` takes this long (saturation and
  deadline tests); a request whose forwarded ``deadline_ms`` budget is
  smaller than the delay gets the honest 504.
* ``--disagree`` — predictions are offset by this value (a degraded
  canary for the rollback acceptance; 0 = agrees with every other stub
  on the same input).
* ``POST /wedge`` (or ``--wedge``) — every subsequent request blocks
  forever: the wedged-replica shape the supervisor must eject within
  the probe deadline.
* ``--round-file`` — ``POST /reloadz`` re-reads the round from this
  file (the rolling-reload rendezvous without a real checkpoint).
* ``POST /integrity`` — ``{"failed": true|false}`` toggles the golden-
  canary failure latch: ``/healthz`` degrades with the
  ``integrity_failed`` reason token (the supervisor quarantines the
  replica — ejected, not killed — and readmits it once cleared).

Predictions are a pure function of the input row (sum of the row,
scaled, mod 7, plus the disagree offset) so two healthy stubs always
agree and a ``--disagree`` stub never does.

``/predict`` also speaks the binary wire format (``Content-Type:
application/x-cxb``, doc/serving.md "Binary wire protocol") with a
stdlib mirror of ``serve/wire.py`` (``struct`` + ``array`` — still no
numpy), so router relay/failover/canary tests can exercise binary
frames without a real replica.

Run directly (NOT ``-m``): ``python cxxnet_tpu/serve/stub.py --port N``.
"""

import argparse
import json
import struct
import sys
import threading
import time
from array import array
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# CXB1 / CXR1 header layouts (keep in lock-step with serve/wire.py)
_REQ = struct.Struct("<4sBBBBIHH")
_RESP = struct.Struct("<4sBBBBHH")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--round", type=int, default=1)
    ap.add_argument("--round-file", default="",
                    help="/reloadz re-reads the served round from here")
    ap.add_argument("--model", default="stub.model",
                    help="model path reported by /healthz")
    ap.add_argument("--quant", default="f32",
                    help="precision scheme reported by /healthz")
    ap.add_argument("--delay-ms", type=float, default=0.0)
    ap.add_argument("--disagree", type=int, default=0,
                    help="prediction offset (0 = agree with other stubs)")
    ap.add_argument("--wedge", action="store_true",
                    help="start wedged (every request blocks forever)")
    args = ap.parse_args()

    lock = threading.Lock()
    state = {
        "round": args.round,
        "wedged": bool(args.wedge),
        "integrity_failed": False,
        "requests": 0,
        "predicts": 0,
        "reloads": 0,
    }

    def read_round_file():
        if args.round_file:
            try:
                with open(args.round_file, "r", encoding="utf-8") as f:
                    return int(f.read().strip())
            except (OSError, ValueError):
                pass
        return None

    init = read_round_file()
    if init is not None:
        state["round"] = init

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # noqa: N802 - stdlib name
            pass

        def _reply(self, status, obj):
            body = json.dumps(obj).encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _enter(self):
            with lock:
                state["requests"] += 1
                wedged = state["wedged"]
            if wedged:
                time.sleep(3600.0)

        def do_GET(self):  # noqa: N802 - stdlib name
            self._enter()
            if self.path == "/healthz":
                with lock:
                    reasons = (["integrity_failed"]
                               if state["integrity_failed"] else [])
                    self._reply(200, {
                        "status": "degraded" if reasons else "ok",
                        "round": state["round"],
                        "model": args.model,
                        "model_crc32": 0,
                        "net_fp": "stub",
                        "quant": args.quant,
                        "reload_breaker": "closed",
                        "reasons": reasons,
                    })
            elif self.path == "/statsz":
                with lock:
                    self._reply(200, dict(state))
            else:
                self._reply(404, {"error": f"unknown route {self.path}"})

        def _predict_wire(self, raw):
            """Binary /predict: parse a CXB1 frame, answer a CXR1
            frame with the same sum-mod-7 prediction as JSON."""
            if len(raw) < _REQ.size:
                self._reply(400, {"error": "short frame",
                                  "reason": "truncated_frame"})
                return
            magic, kind, dtype, ndim, _prio, deadline, mlen, nlen = \
                _REQ.unpack_from(raw, 0)
            if magic != b"CXB1":
                self._reply(400, {"error": "bad frame magic",
                                  "reason": "bad_magic"})
                return
            if dtype != 1 or not 1 <= ndim <= 8:
                self._reply(400, {"error": "unsupported frame encoding",
                                  "reason": "bad_dtype"})
                return
            dims = struct.unpack_from("<%dI" % ndim, raw, _REQ.size)
            ofs = _REQ.size + 4 * ndim + mlen + nlen
            count = 1
            for d in dims:
                count *= d
            if len(raw) != ofs + 4 * count:
                self._reply(400, {"error": "payload length mismatch",
                                  "reason": "truncated_body"})
                return
            deadline_ms = float(deadline) if deadline else None
            if args.delay_ms > 0:
                time.sleep(args.delay_ms / 1e3)
            if (deadline_ms is not None
                    and args.delay_ms >= deadline_ms):
                self._reply(504, {"error": "deadline expired"})
                return
            vals = array("f")
            vals.frombytes(raw[ofs:])
            if sys.byteorder == "big":
                vals.byteswap()
            rows = dims[0]
            per = count // rows if rows else 0
            pred = array("f", (
                float((int(round(sum(vals[i * per:(i + 1) * per])
                                 * 1e3)) % 7) + args.disagree)
                for i in range(rows)))
            if sys.byteorder == "big":
                pred.byteswap()
            with lock:
                state["predicts"] += 1
            rid = b"stub"
            head = _RESP.pack(b"CXR1", kind, 1, 1, 0, len(rid), 0)
            head += struct.pack("<I", rows) + rid
            body = head + pred.tobytes()
            self.send_response(200)
            self.send_header("Content-Type", "application/x-cxb")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802 - stdlib name
            self._enter()
            try:
                n = int(self.headers.get("Content-Length", 0))
            except ValueError:
                n = 0
            raw = self.rfile.read(n) if n > 0 else b""
            ctype = (self.headers.get("Content-Type") or "") \
                .split(";")[0].strip().lower()
            if ctype == "application/x-cxb":
                if self.path != "/predict":
                    self._reply(400, {
                        "error": "binary frames only on /predict",
                        "reason": "wire_unsupported_route"})
                    return
                self._predict_wire(raw)
                return
            try:
                obj = json.loads(raw or b"{}")
            except ValueError:
                obj = {}
            if self.path == "/wedge":
                with lock:
                    state["wedged"] = True
                self._reply(200, {"ok": True})
            elif self.path == "/integrity":
                # fleet tests: toggle the golden-canary failure latch —
                # /healthz then degrades (or clears) integrity_failed,
                # the eject-without-kill + readmit path
                with lock:
                    state["integrity_failed"] = bool(
                        obj.get("failed", True))
                self._reply(200, {"ok": True,
                                  "failed": state["integrity_failed"]})
            elif self.path == "/reloadz":
                new = read_round_file()
                with lock:
                    old = state["round"]
                    if new is not None:
                        state["round"] = new
                    state["reloads"] += 1
                    cur = state["round"]
                self._reply(200, {"ok": True, "swapped": cur != old,
                                  "round": cur, "breaker": "closed"})
            elif self.path == "/predict":
                deadline = obj.get("deadline_ms")
                if args.delay_ms > 0:
                    time.sleep(args.delay_ms / 1e3)
                if (deadline is not None
                        and args.delay_ms >= float(deadline)):
                    self._reply(504, {"error": "deadline expired"})
                    return
                data = obj.get("data") or []
                if data and not isinstance(data[0], list):
                    data = [data]
                pred = [
                    (int(round(sum(float(v) for v in row) * 1e3)) % 7)
                    + args.disagree
                    for row in data
                ]
                with lock:
                    state["predicts"] += 1
                    rnd = state["round"]
                self._reply(200, {"pred": pred, "rid": "stub",
                                  "deadline_ms": deadline, "round": rnd})
            else:
                self._reply(404, {"error": f"unknown route {self.path}"})

    class _StubHTTPServer(ThreadingHTTPServer):
        daemon_threads = True
        request_queue_size = 128

    httpd = _StubHTTPServer(("127.0.0.1", args.port), Handler)
    print(f"STUB READY {httpd.server_port}", flush=True)
    try:
        httpd.serve_forever(poll_interval=0.5)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
