"""Serving metrics: request/batch counters + latency percentiles.

One :class:`ServingStats` per engine, shared by the batcher (queue and
batch accounting), the request paths (latency, outcome counters), and
the HTTP front-end (``/statsz`` renders :meth:`snapshot`).  Latency uses
:class:`~cxxnet_tpu.utils.profiler.PercentileTracker` — the serving-side
sibling of the train loop's ``StepTimer``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..utils.profiler import PercentileTracker

__all__ = ["ServingStats"]


class ServingStats:
    """Thread-safe counters for the serving subsystem.

    * request outcomes: ``ok`` / ``shed`` (queue full) / ``expired``
      (deadline passed before execution) / ``error``
    * batch shape: executed batches, rows, padded bucket rows — the
      batch-fill ratio (real rows / bucket rows actually computed) says
      how much of each compiled program's work was useful, the
      coalescing ratio (rows per batch) says how well the micro-batcher
      amortizes dispatch
    * end-to-end request latency percentiles (enqueue → result)
    """

    def __init__(self, latency_window: int = 2048) -> None:
        self._lock = threading.Lock()
        self.started = time.time()
        self.requests = 0
        self.rows_in = 0
        self.ok = 0
        self.shed = 0
        self.expired = 0
        self.errors = 0
        self.batches = 0
        self.batch_rows = 0
        self.bucket_rows = 0
        # hot-reload lifecycle (poll thread / try_reload): attempts,
        # failures, completed swaps, and the last poll's verdict — the
        # /statsz surface for "is the reload path healthy"
        self.reload_attempts = 0
        self.reload_failures = 0
        self.reload_swaps = 0
        self.last_reload_ok: Optional[bool] = None
        self.latency = PercentileTracker(latency_window)
        self._queue_depth: Optional[Callable[[], int]] = None

    # ------------------------------------------------------------------
    def bind_queue_depth(self, fn: Callable[[], int]) -> None:
        """Register the live queue-depth gauge (the batcher's)."""
        self._queue_depth = fn

    def record_request(self, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows_in += rows

    def record_outcome(self, outcome: str,
                       latency_s: Optional[float] = None) -> None:
        with self._lock:
            if outcome == "ok":
                self.ok += 1
            elif outcome == "shed":
                self.shed += 1
            elif outcome == "expired":
                self.expired += 1
            else:
                self.errors += 1
        if latency_s is not None:
            self.latency.add(latency_s)

    def record_batch(self, rows: int, bucket_rows: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += rows
            self.bucket_rows += bucket_rows

    def record_reload(self, ok: bool, swapped: bool = False) -> None:
        with self._lock:
            self.reload_attempts += 1
            self.last_reload_ok = ok
            if not ok:
                self.reload_failures += 1
            elif swapped:
                self.reload_swaps += 1

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {
                "uptime_sec": time.time() - self.started,
                "requests": self.requests,
                "rows_in": self.rows_in,
                "ok": self.ok,
                "shed": self.shed,
                "expired": self.expired,
                "errors": self.errors,
                "batches": self.batches,
                "batch_rows": self.batch_rows,
                "bucket_rows": self.bucket_rows,
                "batch_fill_ratio": (
                    self.batch_rows / self.bucket_rows
                    if self.bucket_rows else 0.0
                ),
                "rows_per_batch": (
                    self.batch_rows / self.batches if self.batches else 0.0
                ),
                "reload_attempts": self.reload_attempts,
                "reload_failures": self.reload_failures,
                "reload_swaps": self.reload_swaps,
                "last_reload_ok": self.last_reload_ok,
            }
        out["latency_ms"] = self.latency.summary(scale=1e3)
        if self._queue_depth is not None:
            try:
                out["queue_depth"] = int(self._queue_depth())
            except Exception:
                out["queue_depth"] = -1
        return out
