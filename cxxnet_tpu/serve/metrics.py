"""Serving metrics: request/batch counters + latency percentiles.

One :class:`ServingStats` per engine, shared by the batcher (queue and
batch accounting), the request paths (latency, outcome counters), and
the HTTP front-end (``/statsz`` renders :meth:`snapshot`).

Since the observability subsystem (doc/observability.md) this is a thin
facade over two sinks kept in lock-step:

* **per-engine fields** — what they always were; ``/statsz`` keeps its
  shape, with three deliberate changes (doc/serving.md):
  ``latency_ms["mean"]`` is now the WINDOW mean (consistent with the
  percentiles beside it; the old lifetime mean moved to an explicit
  ``lifetime_mean``), ``queue_depth`` is absent (not ``-1``) when the
  gauge fails, and ``queue_depth_errors`` counts those failures;
* **process-wide registry metrics** — every ``record_*`` call also
  bumps the shared :mod:`cxxnet_tpu.obs.registry` counters/histograms
  (``serve_requests_total``, ``serve_request_outcomes_total{outcome}``,
  ``serve_request_latency_seconds`` buckets,
  ``serve_model_reloads_total{result}``, ...), which is what
  ``GET /metricsz`` scrapes as Prometheus text.

The live queue-depth gauge is sampled at snapshot/scrape time; a
raising gauge callable no longer yields the ``-1`` sentinel — the
exception is event-logged once (``serve.queue_depth`` key) and counted
in ``queue_depth_errors`` / ``serve_queue_depth_errors_total`` instead,
and the ``queue_depth`` key is simply absent from that snapshot.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..obs import events as obs_events
from ..obs.registry import DEFAULT_BUCKETS, registry as obs_registry
from ..utils.profiler import PercentileTracker

__all__ = ["ServingStats", "serve_metrics"]

#: request-latency buckets (seconds): the registry default already
#: spans the 1ms-1s micro-batched predict band plus cold-compile tails
LATENCY_BUCKETS = DEFAULT_BUCKETS


class _ServeMetrics:
    """The process-wide registry families for the serving subsystem
    (shared across engines in one process — Prometheus counters are
    per-process facts; per-engine detail stays in ``/statsz``)."""

    def __init__(self) -> None:
        reg = obs_registry()
        self.requests = reg.counter(
            "serve_requests_total", "Requests accepted into the engine.")
        self.rows_in = reg.counter(
            "serve_request_rows_total", "Instance rows across requests.")
        self.outcomes = reg.counter(
            "serve_request_outcomes_total",
            "Request outcomes: ok / shed (429) / expired (504) / error.",
            labelnames=("outcome",),
        )
        self.batches = reg.counter(
            "serve_batches_total", "Coalesced batches executed.")
        self.batch_rows = reg.counter(
            "serve_batch_rows_total", "Real rows in executed batches.")
        self.bucket_rows = reg.counter(
            "serve_bucket_rows_total",
            "Padded bucket rows computed (fill ratio denominator).",
        )
        self.latency = reg.histogram(
            "serve_request_latency_seconds",
            "End-to-end request latency (enqueue to result).",
            buckets=LATENCY_BUCKETS,
        )
        self.reloads = reg.counter(
            "serve_model_reloads_total",
            "Hot-reload attempts by result: swapped / noop / failed.",
            labelnames=("result",),
        )
        self.queue_depth = reg.gauge(
            "serve_queue_depth", "Live micro-batcher queue depth.")
        # identity of the SERVED model (set on load + every hot reload)
        # — the observable proof that a gated publish landed
        self.model_round = reg.gauge(
            "serve_model_round",
            "Checkpoint round of the currently served model.")
        self.model_crc = reg.gauge(
            "serve_model_crc32",
            "Manifest CRC32 of the served checkpoint payload (weights "
            "fingerprint; -1 when unknown).")
        self.queue_depth_errors = reg.counter(
            "serve_queue_depth_errors_total",
            "Queue-depth gauge sampling failures.",
        )
        # quantized-inference identity (doc/performance.md "Quantized
        # inference"): weight bytes at rest as served vs their dense-f32
        # cost — the ~4x int8 win as a scrapeable ratio — plus the
        # active precision scheme as a one-hot labeled gauge
        self.weight_bytes = reg.gauge(
            "serve_weight_bytes",
            "Model weight bytes at rest in the serving engine (as "
            "stored: int8 codes + f32 scales for quantized models).")
        self.weight_bytes_f32 = reg.gauge(
            "serve_weight_bytes_f32",
            "Dense-f32 cost of the same weight tensors (the "
            "quantization win's denominator).")
        self.quant_scheme = reg.gauge(
            "serve_quant_scheme",
            "Served weight precision (1 on the active scheme label).",
            labelnames=("scheme",),
        )
        # request-shape histogram (pow2 bucket of each request's row
        # count) — what the speculative bucket prewarm and the tuning
        # controller read to anticipate compiled-program demand
        self.request_buckets = reg.counter(
            "serve_request_bucket_total",
            "Requests by pow2 row-count bucket (shape histogram).",
            labelnames=("bucket",),
        )
        # wire-format split (doc/serving.md "Binary wire protocol"):
        # which codec the data plane actually speaks, and how many
        # binary-frame bytes move each way — the denominator for the
        # codec-share story the zero-copy path exists to shrink
        self.wire_requests = reg.counter(
            "serve_wire_requests_total",
            "Data-plane requests by wire format.",
            labelnames=("wire",),
        )
        self.wire_bytes = reg.counter(
            "serve_wire_bytes_total",
            "Binary-frame bytes moved, by direction (in / out).",
            labelnames=("dir",),
        )


_METRICS: Optional[_ServeMetrics] = None
_METRICS_LOCK = threading.Lock()


def serve_metrics() -> _ServeMetrics:
    """Lazily build (once) the serving metric families."""
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            _METRICS = _ServeMetrics()
        return _METRICS


class ServingStats:
    """Thread-safe counters for the serving subsystem.

    * request outcomes: ``ok`` / ``shed`` (queue full) / ``expired``
      (deadline passed before execution) / ``error``
    * batch shape: executed batches, rows, padded bucket rows — the
      batch-fill ratio (real rows / bucket rows actually computed) says
      how much of each compiled program's work was useful, the
      coalescing ratio (rows per batch) says how well the micro-batcher
      amortizes dispatch
    * end-to-end request latency percentiles (enqueue → result)
    """

    def __init__(self, latency_window: int = 2048) -> None:
        self._lock = threading.Lock()
        self._m = serve_metrics()
        self.started = time.time()
        self.requests = 0
        self.rows_in = 0
        self.ok = 0
        self.shed = 0
        self.expired = 0
        self.errors = 0
        self.batches = 0
        self.batch_rows = 0
        self.bucket_rows = 0
        # hot-reload lifecycle (poll thread / try_reload): attempts,
        # failures, completed swaps, and the last poll's verdict — the
        # /statsz surface for "is the reload path healthy"
        self.reload_attempts = 0
        self.reload_failures = 0
        self.reload_swaps = 0
        self.last_reload_ok: Optional[bool] = None
        self.queue_depth_errors = 0
        self.latency = PercentileTracker(latency_window)
        self._queue_depth: Optional[Callable[[], int]] = None

    # ------------------------------------------------------------------
    def bind_queue_depth(self, fn: Callable[[], int]) -> None:
        """Register the live queue-depth gauge (the batcher's).  Also
        bound into the registry gauge so ``/metricsz`` samples it live
        (last engine bound wins in a multi-engine process)."""
        self._queue_depth = fn
        self._m.queue_depth.set_function(fn)

    def record_request(self, rows: int,
                       bucket: Optional[int] = None) -> None:
        with self._lock:
            self.requests += 1
            self.rows_in += rows
        self._m.requests.inc()
        self._m.rows_in.inc(rows)
        if bucket is not None:
            self._m.request_buckets.labels(bucket=bucket).inc()

    def record_outcome(self, outcome: str,
                       latency_s: Optional[float] = None) -> None:
        with self._lock:
            if outcome == "ok":
                self.ok += 1
            elif outcome == "shed":
                self.shed += 1
            elif outcome == "expired":
                self.expired += 1
            else:
                self.errors += 1
        label = outcome if outcome in ("ok", "shed", "expired") else "error"
        self._m.outcomes.labels(outcome=label).inc()
        if latency_s is not None:
            self.latency.add(latency_s)
            self._m.latency.observe(latency_s)

    def record_batch(self, rows: int, bucket_rows: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += rows
            self.bucket_rows += bucket_rows
        self._m.batches.inc()
        self._m.batch_rows.inc(rows)
        self._m.bucket_rows.inc(bucket_rows)

    def record_reload(self, ok: bool, swapped: bool = False) -> None:
        with self._lock:
            self.reload_attempts += 1
            self.last_reload_ok = ok
            if not ok:
                self.reload_failures += 1
            elif swapped:
                self.reload_swaps += 1
        result = "failed" if not ok else "swapped" if swapped else "noop"
        self._m.reloads.labels(result=result).inc()

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {
                "uptime_sec": time.time() - self.started,
                "requests": self.requests,
                "rows_in": self.rows_in,
                "ok": self.ok,
                "shed": self.shed,
                "expired": self.expired,
                "errors": self.errors,
                "batches": self.batches,
                "batch_rows": self.batch_rows,
                "bucket_rows": self.bucket_rows,
                "batch_fill_ratio": (
                    self.batch_rows / self.bucket_rows
                    if self.bucket_rows else 0.0
                ),
                "rows_per_batch": (
                    self.batch_rows / self.batches if self.batches else 0.0
                ),
                "reload_attempts": self.reload_attempts,
                "reload_failures": self.reload_failures,
                "reload_swaps": self.reload_swaps,
                "last_reload_ok": self.last_reload_ok,
            }
        out["latency_ms"] = self.latency.summary(scale=1e3)
        if self._queue_depth is not None:
            try:
                out["queue_depth"] = int(self._queue_depth())
            except Exception as e:  # noqa: BLE001 - counted, not sentineled
                with self._lock:
                    self.queue_depth_errors += 1
                self._m.queue_depth_errors.inc()
                obs_events.log_exception_once(
                    "serve.queue_depth", e, kind="serve.gauge_error",
                    gauge="queue_depth",
                )
        out["queue_depth_errors"] = self.queue_depth_errors
        return out
