"""Fleet front-end: priority admission control + least-loaded dispatch.

The single front door over a :class:`~cxxnet_tpu.serve.fleet.
ServingFleet`.  Every request flows: **classify** (priority
``interactive`` | ``batch``, from the JSON ``priority`` field or the
``X-Priority`` header) → **admit** (the admission-control layer over
the existing 429 machinery — see below) → **dispatch** (least-loaded
healthy replica, with failover) → **relay** (the replica's status and
body pass through unchanged).

Admission control (arXiv 1605.08695's production lesson, layered on
the per-engine queue bound): capacity is ``fleet_replica_inflight ×
replicas-in-rotation`` — it SHRINKS when replicas die, so overload
surfaces as explicit 429 shed instead of queueing collapse.  Batch
traffic sheds first: above ``fleet_batch_shed_ratio`` of capacity,
``batch`` requests get 429 while ``interactive`` requests are still
admitted up to the full bound.

Deadline budget: a request's ``deadline_ms`` covers route AND execute.
The router tracks the absolute deadline from arrival; at each dispatch
attempt it forwards only the REMAINING budget to the replica (whose
engine 504s work it cannot finish in time) and 504s locally when the
budget is gone before any replica could be reached — so routing time,
failover time and execute time all draw from the one budget the client
set.

Failover: predict/extract are idempotent, so a dispatch that dies at
the network layer (the replica was SIGKILLed mid-flight) retries on a
DIFFERENT replica up to ``fleet_dispatch_retries`` times within the
deadline — this is what makes kill-one-of-N invisible to non-shed
requests.  ``/feedback`` appends are NOT retried (a retry could
double-append); they relay a 502 and the client's own retry applies.

Canary routing: while a canary is evaluating, a ``canary_slice``
fraction of live ``/predict`` traffic is served BY the canary (its
latency leg), and a ``canary_sample`` fraction of baseline responses
is mirrored to it in the background for row-level agreement — the
measurement the promote/rollback decision reads
(``serve/fleet.py::CanaryController``).

Transport: dispatch runs over a bounded per-replica pool of
persistent HTTP/1.1 connections (:class:`_ReplicaPool`) instead of a
fresh TCP handshake per request — at data-plane rates the 3-packet
setup cost per hop was a measurable share of p50.  A pooled
connection that went stale while idle (the replica closed it) gets
exactly one fresh-connection retry against the SAME replica — except
``/feedback``, whose send may already have landed and is therefore
never replayed, stale socket or not.  Pools are retired wholesale
when a replica is ejected or reloaded (``serve/fleet.py`` hooks
:meth:`FleetRouter.retire_replica_pool`).

Binary wire frames (doc/serving.md "Binary wire protocol") take the
same front door: ``Content-Type: application/x-cxb`` requests are
classified from the frame header (``wire.peek_header``), pass the
identical admission/priority/deadline machinery, and relay OPAQUELY —
the router patches the remaining deadline budget in place
(``wire.patch_deadline``) per dispatch attempt and never decodes the
payload.  Canary mirroring compares raw response payloads row-wise.
"""

from __future__ import annotations

import http.client
import json
import queue
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs import events as obs_events
from . import wire
from .fleet import Replica, fleet_metrics

__all__ = ["FleetRouter", "FleetStats", "ModelRouter",
           "UnknownModelError", "PRIORITIES"]

PRIORITIES = ("interactive", "batch")
MAX_BODY_BYTES = 64 << 20


class UnknownModelError(KeyError):
    """A request named a model no route serves.  ``reason`` is the
    stable machine-readable token clients and supervisors key on (the
    HTTP layer maps this to a 404 carrying it)."""

    reason = "unknown_model"

    def __init__(self, model, known) -> None:
        self.model = model
        self.known = sorted(known)
        super().__init__(
            f"unknown model {model!r}; serving: "
            f"{', '.join(self.known) or '(none)'}")

    def __str__(self) -> str:  # KeyError.__str__ repr()s its arg
        return self.args[0]


class ModelRouter:
    """Per-model dispatch: a request's ``model`` field → the named
    tenant's engine + feedback log.

    The in-process half of per-model routing (ROADMAP item 1): the
    single-engine HTTP front-end (``serve/server.py``) and the
    multi-tenant loop manager (``loop/tenant.py``) both resolve
    through one of these.  A model-less request takes the DEFAULT
    route — the first model registered, or the explicitly flagged one
    — so single-model clients keep working unchanged against a
    multi-model server.  Routes are fixed after startup, so resolution
    is lock-free on the hot path."""

    def __init__(self) -> None:
        self._routes: Dict[str, Tuple[object, object]] = {}
        self._default: Optional[str] = None

    def add(self, name: str, engine, feedback=None,
            default: bool = False) -> "ModelRouter":
        if not name:
            raise ValueError("a model route needs a non-empty name")
        if name in self._routes:
            raise ValueError(f"duplicate model route {name!r}")
        self._routes[name] = (engine, feedback)
        if default or self._default is None:
            self._default = name
        return self

    def resolve(self, model=None) -> Tuple[str, object, object]:
        """``(name, engine, feedback)`` for a request's ``model`` field
        (None/empty → the default route).  Raises
        :class:`UnknownModelError` for a name no route serves."""
        if model in (None, ""):
            model = self._default
        if model not in self._routes:
            raise UnknownModelError(model, self._routes.keys())
        engine, feedback = self._routes[model]
        return str(model), engine, feedback

    def models(self) -> List[str]:
        return sorted(self._routes)

    def engines(self) -> List[object]:
        return [e for e, _fb in self._routes.values()]

    def healthz_models(self) -> Dict[str, dict]:
        """Per-model identity block for the front-end's ``/healthz``."""
        out = {}
        for name, (engine, _fb) in sorted(self._routes.items()):
            h = engine.healthz()
            out[name] = {"status": h.get("status"),
                         "round": h.get("round"),
                         "model_crc32": h.get("model_crc32"),
                         "default": name == self._default}
        return out

#: network-layer dispatch failures that trigger failover (a replica
#: HTTP error response is NOT one of these — it relays)
_DISPATCH_ERRORS = (http.client.HTTPException, ConnectionError, OSError,
                    TimeoutError)


def _jbody(obj) -> bytes:
    return json.dumps(obj).encode("utf-8")


class _ReplicaPool:
    """Bounded idle-connection pool to ONE replica address.

    ``acquire`` hands back an idle keep-alive connection when one is
    parked (``reused=True``) or a fresh unconnected one otherwise;
    ``release`` parks it again up to ``size`` idle connections (beyond
    that the connection closes — the bound is on PARKED sockets, not
    concurrency, which the admission layer already caps).  All methods
    are thread-safe; the connections themselves are owned by exactly
    one dispatch between acquire and release."""

    def __init__(self, address: str, size: int) -> None:
        self.address = address
        host, _, port = address.rpartition(":")
        self.host = host
        self.port = int(port)
        self.size = max(1, int(size))
        self._lock = threading.Lock()
        self._idle: List[http.client.HTTPConnection] = []

    def acquire(self, timeout_s: float
                ) -> Tuple[http.client.HTTPConnection, bool]:
        with self._lock:
            conn = self._idle.pop() if self._idle else None
        if conn is not None:
            # per-request timeout on a long-lived socket
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
            return conn, True
        return http.client.HTTPConnection(
            self.host, self.port, timeout=timeout_s), False

    def release(self, conn: http.client.HTTPConnection) -> bool:
        """Park ``conn`` for reuse; False when the pool is full (the
        connection is closed instead)."""
        with self._lock:
            if len(self._idle) < self.size:
                self._idle.append(conn)
                return True
        conn.close()
        return False

    def retire_all(self) -> int:
        """Close every parked connection (replica ejected/reloaded)."""
        with self._lock:
            idle, self._idle = self._idle, []
        for c in idle:
            c.close()
        return len(idle)

    def idle_count(self) -> int:
        with self._lock:
            return len(self._idle)


class FleetStats:
    """Thread-safe request accounting for the front-end (``/statsz``)
    plus the drain condition shutdown waits on.  ``requests`` counts
    ARRIVALS by priority (shed included — the same semantics as the
    ``fleet_requests_total`` family; admitted = requests - shed)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self.inflight = 0
        self.requests: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self.shed: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self.expired = 0
        self.failovers = 0
        self.unroutable = 0
        self.relayed_5xx = 0

    def try_enter(self, priority: str, capacity: int,
                  shed_ratio: float) -> Optional[str]:
        """Atomic admit-or-shed: the occupancy check and the slot
        reservation happen under ONE lock, so concurrent arrivals can
        never all pass a stale check and overshoot the capacity bound
        (which would also invert batch-sheds-first ordering).  Returns
        None when a slot was reserved, else the shed reason."""
        with self._lock:
            self.requests[priority] = self.requests.get(priority, 0) + 1
            cur = self.inflight
            if cur >= capacity:
                self.shed[priority] = self.shed.get(priority, 0) + 1
                return f"at capacity ({cur}/{capacity} in flight)"
            if priority == "batch" and cur >= shed_ratio * capacity:
                self.shed[priority] = self.shed.get(priority, 0) + 1
                return (f"batch shed under pressure ({cur}/{capacity} "
                        f"in flight, batch sheds above {shed_ratio:g} "
                        f"of capacity)")
            self.inflight += 1
            return None

    def leave(self) -> None:
        with self._idle:
            self.inflight -= 1
            if self.inflight == 0:
                self._idle.notify_all()

    def wait_idle(self, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._idle:
            while self.inflight > 0:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._idle.wait(timeout=remain)
        return True

    def count(self, field: str) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "inflight": self.inflight,
                "requests": dict(self.requests),
                "shed": dict(self.shed),
                "expired": self.expired,
                "failovers": self.failovers,
                "unroutable": self.unroutable,
                "relayed_5xx": self.relayed_5xx,
            }


class FleetRouter:
    """The dispatch brain; ``make_httpd`` binds the HTTP surface."""

    def __init__(self, fleet, default_deadline_ms: float = 0.0) -> None:
        self.fleet = fleet
        self.opts = fleet.opts
        self.sup = fleet.supervisor
        self.default_deadline_ms = float(default_deadline_ms)
        self.stats = FleetStats()
        self._metrics = fleet_metrics()  # hot path: no singleton lock
        self._lock = threading.Lock()       # replica inflight counters
        self._rng = random.Random(0xF1EE7)  # slice/sample draws
        self._rng_lock = threading.Lock()
        # persistent-connection pools, one per replica address (created
        # lazily on first dispatch, retired on eject/reload)
        self._pools: Dict[str, _ReplicaPool] = {}
        self._pools_lock = threading.Lock()
        self.pool_size = int(getattr(self.opts, "pool_size", 8))
        # live idle-connection gauge (last router bound wins, matching
        # the serve-side queue_depth convention)
        self._metrics.pool_idle.set_function(
            lambda: sum(p.idle_count() for p in self._pool_list()))
        # mirror lane: bounded + lossy — shadow comparisons must never
        # apply backpressure to live traffic
        self._mirror_q: "queue.Queue[tuple]" = queue.Queue(maxsize=256)
        self._mirror_stop = threading.Event()
        self._mirror_thread: Optional[threading.Thread] = None
        if self.fleet.canary is not None:
            self._mirror_thread = threading.Thread(
                target=self._mirror_loop, name="cxxnet-fleet-mirror",
                daemon=True)
            self._mirror_thread.start()

    # ------------------------------------------------------------------
    # admission control
    def capacity(self) -> int:
        return self.opts.replica_inflight * max(
            1, len(self.sup.rotation()))

    def admit(self, priority: str) -> Optional[str]:
        """Admit-or-shed (atomic — see :meth:`FleetStats.try_enter`);
        an admitted caller owns a slot and must ``stats.leave()``.
        Batch sheds first: the 429 surface under pressure, interactive
        up to the full capacity bound."""
        return self.stats.try_enter(priority, self.capacity(),
                                    self.opts.batch_shed_ratio)

    # ------------------------------------------------------------------
    # replica selection
    def _canary_live(self) -> bool:
        c = self.fleet.canary
        return c is not None and c.state == "evaluating"

    def pick_replica(self, exclude=(),
                     want_canary: bool = False) -> Optional[Replica]:
        """Least-loaded healthy replica (ties break on index).  While a
        canary is evaluating it only receives its slice
        (``want_canary``); once promoted it serves at full weight."""
        rotation = self.sup.rotation()
        evaluating = self._canary_live()
        if want_canary:
            pool = [r for r in rotation if r.role == "canary"]
        elif evaluating:
            pool = [r for r in rotation if r.role != "canary"]
        else:
            pool = rotation
        pool = [r for r in pool if r not in exclude]
        if not pool:
            return None
        with self._lock:
            return min(pool, key=lambda r: (r.inflight, r.idx))

    def _draw(self, prob: float) -> bool:
        if prob <= 0.0:
            return False
        with self._rng_lock:
            return self._rng.random() < prob

    # ------------------------------------------------------------------
    # connection pools
    def _pool(self, r: Replica) -> _ReplicaPool:
        with self._pools_lock:
            p = self._pools.get(r.address)
            if p is None:
                p = _ReplicaPool(r.address, self.pool_size)
                self._pools[r.address] = p
            return p

    def _pool_list(self) -> List[_ReplicaPool]:
        with self._pools_lock:
            return list(self._pools.values())

    def retire_replica_pool(self, address: str) -> int:
        """Close every parked connection to ``address`` — the fleet
        calls this when the replica is ejected or reloaded, so no
        dispatch ever rides a socket into a dead or swapped process."""
        with self._pools_lock:
            p = self._pools.get(address)
        if p is None:
            return 0
        n = p.retire_all()
        if n:
            self._metrics.pool_retired.inc(n)
        return n

    def pool_stats(self) -> Dict[str, int]:
        """Idle keep-alive connections per replica address."""
        return {p.address: p.idle_count() for p in self._pool_list()}

    # ------------------------------------------------------------------
    # dispatch
    def _post_replica(
        self, r: Replica, path: str, body, timeout_s: float,
        content_type: str = "application/json",
    ) -> Tuple[int, bytes, str]:
        """POST ``body`` bytes over a pooled keep-alive connection;
        returns ``(status, raw_body, content_type)``.  A replica ERROR
        RESPONSE (429/500/504...) relays as-is — only network-layer
        failures raise (and trigger failover in the caller).  A pooled
        connection that went stale while parked gets ONE retry on a
        fresh connection to the same replica; ``/feedback`` never does
        (the stale send may have reached the replica)."""
        pool = self._pool(r)
        for attempt in (0, 1):
            conn, reused = pool.acquire(timeout_s)
            if not reused:
                self._metrics.pool_connects.inc()
            try:
                conn.request("POST", path, body=body,
                             headers={"Content-Type": content_type})
                resp = conn.getresponse()
                raw = resp.read()
            except _DISPATCH_ERRORS:
                conn.close()
                self._metrics.pool_retired.inc()
                if reused and attempt == 0 and path != "/feedback":
                    continue  # stale keep-alive: one fresh retry
                raise
            rtype = (resp.getheader("Content-Type") or
                     "application/json").split(";")[0].strip()
            if resp.will_close:
                conn.close()
                self._metrics.pool_retired.inc()
            else:
                pool.release(conn)
            return resp.status, raw, rtype
        raise ConnectionError("unreachable")  # loop always returns/raises

    def route(self, path: str, obj: dict,
              priority: str = "interactive") -> Tuple[int, dict]:
        """Admission + dispatch + failover for one request; returns
        ``(http_status, body)``.  The embeddable API the HTTP handler
        (and the tests) call."""
        m = self._metrics
        m.requests.labels(priority=priority).inc()
        reason = self.admit(priority)
        if reason is not None:
            m.shed.labels(priority=priority).inc()
            return 429, {"error": f"load shed: {reason}",
                         "priority": priority}
        m.inflight.set(self.stats.inflight)
        try:
            return self._dispatch(path, obj)
        finally:
            self.stats.leave()
            m.inflight.set(self.stats.inflight)

    def route_wire(self, path: str, frame, priority: str = "interactive",
                   deadline_ms: float = 0.0) -> Tuple[int, bytes, str]:
        """Binary twin of :meth:`route`: identical admission, priority
        shedding, deadline budget, failover and canary accounting; the
        frame relays opaquely (only its deadline field is patched per
        attempt).  Returns ``(status, body_bytes, content_type)`` —
        success bodies are ``CXR1`` frames straight off the replica,
        error bodies stay JSON so any client can read them."""
        m = self._metrics
        m.requests.labels(priority=priority).inc()
        reason = self.admit(priority)
        if reason is not None:
            m.shed.labels(priority=priority).inc()
            return 429, _jbody({"error": f"load shed: {reason}",
                                "priority": priority}), "application/json"
        m.inflight.set(self.stats.inflight)
        try:
            buf = frame if isinstance(frame, bytearray) else \
                bytearray(frame)
            return self._dispatch_wire(path, buf, deadline_ms)
        finally:
            self.stats.leave()
            m.inflight.set(self.stats.inflight)

    def _dispatch(self, path: str, obj: dict) -> Tuple[int, dict]:
        deadline_ms = obj.get("deadline_ms")
        if deadline_ms is None and self.default_deadline_ms > 0:
            deadline_ms = self.default_deadline_ms
        try:
            deadline_val = (float(deadline_ms)
                            if deadline_ms is not None else 0.0)
        except (TypeError, ValueError):
            # client-input error: 400, matching the single-engine server
            return 400, {"error": f"bad deadline_ms: {deadline_ms!r}"}
        fwd = dict(obj)
        fwd.pop("priority", None)

        def make_body(remaining_ms: Optional[float]) -> bytes:
            if remaining_ms is not None:
                # the execute share of the budget: whatever routing and
                # failover have not already consumed
                fwd["deadline_ms"] = remaining_ms
            return _jbody(fwd)

        def account(r: Replica, raw: bytes, dt: float) -> None:
            self._canary_account(r, dt, lambda: (
                "json", obj.get("data"),
                json.loads(raw.decode("utf-8")).get("pred")))

        status, raw, _rtype = self._dispatch_loop(
            path, deadline_val, make_body, "application/json", account)
        try:
            body = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            body = {"error": "replica returned a non-JSON body"}
        return status, body

    def _dispatch_wire(self, path: str, frame: bytearray,
                       deadline_ms: float) -> Tuple[int, bytes, str]:
        deadline_val = float(deadline_ms or 0.0)
        if deadline_val <= 0 and self.default_deadline_ms > 0:
            deadline_val = self.default_deadline_ms

        def make_body(remaining_ms: Optional[float]):
            if remaining_ms is not None:
                wire.patch_deadline(frame, remaining_ms)
            return frame

        def account(r: Replica, raw: bytes, dt: float) -> None:
            self._canary_account(
                r, dt, lambda: ("wire", bytes(frame), bytes(raw)))

        return self._dispatch_loop(path, deadline_val, make_body,
                                   wire.CONTENT_TYPE, account)

    def _dispatch_loop(
        self, path: str, deadline_val: float,
        make_body: Callable[[Optional[float]], object],
        content_type: str,
        account: Callable[[Replica, bytes, float], None],
    ) -> Tuple[int, bytes, str]:
        """The shared least-loaded + failover loop under both wire
        formats.  ``make_body(remaining_ms)`` builds each attempt's
        request body (JSON re-encodes the forwarded object; binary
        patches the frame's deadline field in place); ``account`` runs
        on a 200 ``/predict`` relay for canary latency/mirroring."""
        t0 = time.monotonic()
        m = self._metrics
        deadline_t = (t0 + deadline_val / 1e3
                      if deadline_val > 0 else None)
        is_predict = path == "/predict"
        want_canary = (is_predict and self._canary_live()
                       and self._draw(self.opts.canary_slice))
        tried: set = set()
        failures = 0
        while True:
            remaining_ms = None
            if deadline_t is not None:
                remaining_ms = (deadline_t - time.monotonic()) * 1e3
                if remaining_ms <= 0:
                    self.stats.count("expired")
                    return 504, _jbody(
                        {"error": "deadline expired before a replica "
                                  "could answer"}), "application/json"
            r = self.pick_replica(exclude=tried, want_canary=want_canary)
            if r is None and want_canary:
                want_canary = False  # canary unavailable: baseline serves
                continue
            if r is None:
                self.stats.count("unroutable")
                return 503, _jbody(
                    {"error": "no healthy replica available"}), \
                    "application/json"
            timeout_s = self.opts.dispatch_timeout_s
            if remaining_ms is not None:
                timeout_s = min(timeout_s, remaining_ms / 1e3 + 1.0)
            body = make_body(remaining_ms)
            with self._lock:
                r.inflight += 1
            t_send = time.monotonic()
            try:
                status, raw, rtype = self._post_replica(
                    r, path, body, timeout_s, content_type)
            except _DISPATCH_ERRORS as e:
                tried.add(r)
                failures += 1
                self.sup.note_dispatch_failure(r)
                if path == "/feedback":
                    # appends are not idempotent — never replayed
                    return 502, _jbody(
                        {"error": f"replica dispatch failed "
                                  f"({type(e).__name__}: {e}); "
                                  "feedback is not retried"}), \
                        "application/json"
                if failures > self.opts.dispatch_retries:
                    return 502, _jbody(
                        {"error": f"dispatch failed on {failures} "
                                  f"replica(s) "
                                  f"({type(e).__name__}: {e})"}), \
                        "application/json"
                # only an actual retry counts as a failover
                self.stats.count("failovers")
                m.failovers.inc()
                continue
            finally:
                with self._lock:
                    r.inflight -= 1
            dt = time.monotonic() - t_send
            with self._lock:
                r.dispatched += 1
            m.dispatch.labels(replica=str(r.idx)).inc()
            if status >= 500:
                self.stats.count("relayed_5xx")
            if is_predict and status == 200:
                account(r, raw, dt)
            return status, raw, rtype

    # ------------------------------------------------------------------
    # canary measurement
    def _canary_account(self, r: Replica, dt_s: float,
                        item_fn: Callable[[], Optional[tuple]]) -> None:
        """Latency legs + mirror sampling for one 200 ``/predict``.
        ``item_fn`` lazily builds the mirror-queue entry — ``("json",
        data, base_pred)`` or ``("wire", frame_bytes, base_response)``
        — so the baseline body is only decoded on a sampled draw."""
        c = self.fleet.canary
        if c is None or c.state != "evaluating":
            return
        m = self._metrics
        if r.role == "canary":
            m.canary_requests.labels(leg="slice").inc()
            c.record_latency("canary", dt_s)
            return
        c.record_latency("baseline", dt_s)
        if self._draw(self.opts.canary_sample):
            try:
                item = item_fn()
            except Exception:  # noqa: BLE001 - shadow path never raises
                return
            if item is None:
                return
            try:
                self._mirror_q.put_nowait(item)
            except queue.Full:
                pass  # lossy by design: shadow work never backpressures

    def _mirror_loop(self) -> None:
        while not self._mirror_stop.is_set():
            try:
                item = self._mirror_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._mirror_once(item)
            except Exception:  # noqa: BLE001 - shadow lane never dies
                pass

    def _mirror_once(self, item: tuple) -> None:
        """Replay one sampled baseline request against the canary and
        record row-level agreement.  JSON entries compare ``pred``
        lists; wire entries re-post the original frame (deadline
        zeroed — shadow work has no budget) and compare the raw f32
        response payloads row-wise."""
        leg, payload, base = item
        c = self.fleet.canary
        if c is None or c.state != "evaluating" or base is None:
            return
        canary = self.pick_replica(want_canary=True)
        if canary is None:
            return
        m = self._metrics
        if leg == "wire":
            frame = bytearray(payload)
            wire.patch_deadline(frame, 0)
            body, ctype = frame, wire.CONTENT_TYPE
        else:
            body, ctype = _jbody({"data": payload}), "application/json"
        t0 = time.monotonic()
        try:
            status, raw, rtype = self._post_replica(
                canary, "/predict", body, self.opts.dispatch_timeout_s,
                content_type=ctype)
        except _DISPATCH_ERRORS:
            self.sup.note_dispatch_failure(canary)
            return
        m.canary_requests.labels(leg="mirror").inc()
        if status != 200:
            return
        c.record_latency("canary", time.monotonic() - t0)
        if leg == "wire":
            try:
                _k, _rid, can_rows = wire.decode_response(raw)
                _k, _rid, base_rows = wire.decode_response(base)
            except wire.WireError:
                return
            total = min(base_rows.shape[0], can_rows.shape[0])
            b = np.asarray(base_rows[:total]).reshape(total, -1)
            cn = np.asarray(can_rows[:total]).reshape(total, -1)
            equal = int((b == cn).all(axis=1).sum())
        else:
            try:
                can_pred = json.loads(raw.decode("utf-8")).get("pred")
            except (ValueError, UnicodeDecodeError):
                return
            if not isinstance(can_pred, list):
                return
            base_l = list(base) if isinstance(base, list) else [base]
            total = min(len(base_l), len(can_pred))
            equal = sum(1 for a, b in zip(base_l, can_pred) if a == b)
        c.record_compare(equal, total)

    # ------------------------------------------------------------------
    # HTTP surface
    def make_httpd(self, host: str, port: int) -> ThreadingHTTPServer:
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: N802 - stdlib name
                pass

            def _reply(self, status: int, payload) -> None:
                body = (payload if isinstance(payload, bytes)
                        else json.dumps(payload).encode("utf-8"))
                self.send_response(status)
                ctype = ("text/plain; version=0.0.4; charset=utf-8"
                         if isinstance(payload, bytes)
                         else "application/json")
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib name
                if self.path == "/healthz":
                    self._reply(200, router.fleet.healthz())
                elif self.path == "/statsz":
                    self._reply(200, router.fleet.statsz())
                elif self.path == "/metricsz":
                    from ..obs import registry as obs_registry

                    self._reply(200, obs_registry()
                                .render_prometheus().encode("utf-8"))
                elif self.path == "/alertz":
                    from ..obs import alerts as obs_alerts

                    self._reply(200, obs_alerts.evaluator().status())
                else:
                    self._reply(404,
                                {"error": f"unknown route {self.path}"})

            def _post_wire(self, length: int) -> None:
                """Binary-frame data plane: read the frame into ONE
                mutable buffer, classify from its header, relay through
                the same admission machinery as JSON."""
                frame = bytearray(length)
                got, view = 0, memoryview(frame)
                while got < length:
                    n = self.rfile.readinto(view[got:])
                    if not n:
                        break
                    got += n
                del view
                if got < length:
                    # can't resync a half-read keep-alive stream
                    self.close_connection = True
                    self._reply(400, {"error": "frame body shorter than "
                                               "Content-Length",
                                      "reason": "truncated_body"})
                    return
                if self.path == "/feedback":
                    self._reply(400, {
                        "error": "binary frames are not accepted on "
                                 "/feedback; use JSON",
                        "reason": "wire_unsupported_route"})
                    return
                try:
                    _kind, _model, priority, deadline_ms, _nbytes = \
                        wire.peek_header(frame)
                except wire.WireError as e:
                    self._reply(400, {"error": str(e),
                                      "reason": e.reason})
                    return
                try:
                    status, body, rctype = router.route_wire(
                        self.path, frame, priority, deadline_ms or 0.0)
                except Exception as e:  # noqa: BLE001 - served as a 500
                    status, body, rctype = 500, _jbody(
                        {"error": f"{type(e).__name__}: {e}"}), \
                        "application/json"
                self.send_response(status)
                self.send_header("Content-Type", rctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 - stdlib name
                if self.path not in ("/predict", "/extract", "/feedback"):
                    self._reply(404,
                                {"error": f"unknown route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    length = 0
                if length <= 0 or length > MAX_BODY_BYTES:
                    # the unread body would desync the keep-alive stream
                    self.close_connection = True
                    self._reply(400,
                                {"error": "missing or oversized body"})
                    return
                ctype = (self.headers.get("Content-Type") or "") \
                    .split(";")[0].strip().lower()
                if ctype == wire.CONTENT_TYPE:
                    self._post_wire(length)
                    return
                try:
                    obj = json.loads(self.rfile.read(length)
                                     .decode("utf-8"))
                except (ValueError, UnicodeDecodeError) as e:
                    self._reply(400, {"error": f"bad JSON: {e}"})
                    return
                if not isinstance(obj, dict) or "data" not in obj:
                    self._reply(400,
                                {"error": 'body must be {"data": [...]}'})
                    return
                priority = str(obj.get("priority")
                               or self.headers.get("X-Priority")
                               or "interactive")
                if priority not in PRIORITIES:
                    self._reply(400, {
                        "error": f"unknown priority {priority!r}; want "
                                 f"one of {'/'.join(PRIORITIES)}"})
                    return
                try:
                    status, body = router.route(self.path, obj, priority)
                except Exception as e:  # noqa: BLE001 - served as a 500
                    status, body = 500, {
                        "error": f"{type(e).__name__}: {e}"}
                self._reply(status, body)

        class _FrontDoor(ThreadingHTTPServer):
            daemon_threads = True
            # a client fleet opening hundreds of keep-alive
            # connections at once overflows the stdlib default listen
            # backlog of 5 into connection-refused errors
            request_queue_size = 128

        httpd = _FrontDoor((host, port), Handler)
        obs_events.emit("fleet.router_up", host=host,
                        port=httpd.server_port)
        return httpd

    def close(self, drain_timeout_s: float = 5.0) -> None:
        if drain_timeout_s > 0 and not self.stats.wait_idle(
                drain_timeout_s):
            obs_events.emit("fleet.drain_timeout",
                            inflight=self.stats.inflight)
        self._mirror_stop.set()
        if self._mirror_thread is not None:
            self._mirror_thread.join(timeout=5.0)
            self._mirror_thread = None
        for p in self._pool_list():
            p.retire_all()
