"""Fleet front-end: priority admission control + least-loaded dispatch.

The single front door over a :class:`~cxxnet_tpu.serve.fleet.
ServingFleet`.  Every request flows: **classify** (priority
``interactive`` | ``batch``, from the JSON ``priority`` field or the
``X-Priority`` header) → **admit** (the admission-control layer over
the existing 429 machinery — see below) → **dispatch** (least-loaded
healthy replica, with failover) → **relay** (the replica's status and
body pass through unchanged).

Admission control (arXiv 1605.08695's production lesson, layered on
the per-engine queue bound): capacity is ``fleet_replica_inflight ×
replicas-in-rotation`` — it SHRINKS when replicas die, so overload
surfaces as explicit 429 shed instead of queueing collapse.  Batch
traffic sheds first: above ``fleet_batch_shed_ratio`` of capacity,
``batch`` requests get 429 while ``interactive`` requests are still
admitted up to the full bound.

Deadline budget: a request's ``deadline_ms`` covers route AND execute.
The router tracks the absolute deadline from arrival; at each dispatch
attempt it forwards only the REMAINING budget to the replica (whose
engine 504s work it cannot finish in time) and 504s locally when the
budget is gone before any replica could be reached — so routing time,
failover time and execute time all draw from the one budget the client
set.

Failover: predict/extract are idempotent, so a dispatch that dies at
the network layer (the replica was SIGKILLed mid-flight) retries on a
DIFFERENT replica up to ``fleet_dispatch_retries`` times within the
deadline — this is what makes kill-one-of-N invisible to non-shed
requests.  ``/feedback`` appends are NOT retried (a retry could
double-append); they relay a 502 and the client's own retry applies.

Canary routing: while a canary is evaluating, a ``canary_slice``
fraction of live ``/predict`` traffic is served BY the canary (its
latency leg), and a ``canary_sample`` fraction of baseline responses
is mirrored to it in the background for row-level agreement — the
measurement the promote/rollback decision reads
(``serve/fleet.py::CanaryController``).
"""

from __future__ import annotations

import json
import queue
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ..obs import events as obs_events
from .fleet import Replica, fleet_metrics

__all__ = ["FleetRouter", "FleetStats", "ModelRouter",
           "UnknownModelError", "PRIORITIES"]

PRIORITIES = ("interactive", "batch")
MAX_BODY_BYTES = 64 << 20


class UnknownModelError(KeyError):
    """A request named a model no route serves.  ``reason`` is the
    stable machine-readable token clients and supervisors key on (the
    HTTP layer maps this to a 404 carrying it)."""

    reason = "unknown_model"

    def __init__(self, model, known) -> None:
        self.model = model
        self.known = sorted(known)
        super().__init__(
            f"unknown model {model!r}; serving: "
            f"{', '.join(self.known) or '(none)'}")

    def __str__(self) -> str:  # KeyError.__str__ repr()s its arg
        return self.args[0]


class ModelRouter:
    """Per-model dispatch: a request's ``model`` field → the named
    tenant's engine + feedback log.

    The in-process half of per-model routing (ROADMAP item 1): the
    single-engine HTTP front-end (``serve/server.py``) and the
    multi-tenant loop manager (``loop/tenant.py``) both resolve
    through one of these.  A model-less request takes the DEFAULT
    route — the first model registered, or the explicitly flagged one
    — so single-model clients keep working unchanged against a
    multi-model server.  Routes are fixed after startup, so resolution
    is lock-free on the hot path."""

    def __init__(self) -> None:
        self._routes: Dict[str, Tuple[object, object]] = {}
        self._default: Optional[str] = None

    def add(self, name: str, engine, feedback=None,
            default: bool = False) -> "ModelRouter":
        if not name:
            raise ValueError("a model route needs a non-empty name")
        if name in self._routes:
            raise ValueError(f"duplicate model route {name!r}")
        self._routes[name] = (engine, feedback)
        if default or self._default is None:
            self._default = name
        return self

    def resolve(self, model=None) -> Tuple[str, object, object]:
        """``(name, engine, feedback)`` for a request's ``model`` field
        (None/empty → the default route).  Raises
        :class:`UnknownModelError` for a name no route serves."""
        if model in (None, ""):
            model = self._default
        if model not in self._routes:
            raise UnknownModelError(model, self._routes.keys())
        engine, feedback = self._routes[model]
        return str(model), engine, feedback

    def models(self) -> List[str]:
        return sorted(self._routes)

    def engines(self) -> List[object]:
        return [e for e, _fb in self._routes.values()]

    def healthz_models(self) -> Dict[str, dict]:
        """Per-model identity block for the front-end's ``/healthz``."""
        out = {}
        for name, (engine, _fb) in sorted(self._routes.items()):
            h = engine.healthz()
            out[name] = {"status": h.get("status"),
                         "round": h.get("round"),
                         "model_crc32": h.get("model_crc32"),
                         "default": name == self._default}
        return out

#: network-layer dispatch failures that trigger failover (a replica
#: HTTP error response is NOT one of these — it relays)
_DISPATCH_ERRORS = (urllib.error.URLError, ConnectionError, OSError,
                    TimeoutError)


class FleetStats:
    """Thread-safe request accounting for the front-end (``/statsz``)
    plus the drain condition shutdown waits on.  ``requests`` counts
    ARRIVALS by priority (shed included — the same semantics as the
    ``fleet_requests_total`` family; admitted = requests - shed)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self.inflight = 0
        self.requests: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self.shed: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self.expired = 0
        self.failovers = 0
        self.unroutable = 0
        self.relayed_5xx = 0

    def try_enter(self, priority: str, capacity: int,
                  shed_ratio: float) -> Optional[str]:
        """Atomic admit-or-shed: the occupancy check and the slot
        reservation happen under ONE lock, so concurrent arrivals can
        never all pass a stale check and overshoot the capacity bound
        (which would also invert batch-sheds-first ordering).  Returns
        None when a slot was reserved, else the shed reason."""
        with self._lock:
            self.requests[priority] = self.requests.get(priority, 0) + 1
            cur = self.inflight
            if cur >= capacity:
                self.shed[priority] = self.shed.get(priority, 0) + 1
                return f"at capacity ({cur}/{capacity} in flight)"
            if priority == "batch" and cur >= shed_ratio * capacity:
                self.shed[priority] = self.shed.get(priority, 0) + 1
                return (f"batch shed under pressure ({cur}/{capacity} "
                        f"in flight, batch sheds above {shed_ratio:g} "
                        f"of capacity)")
            self.inflight += 1
            return None

    def leave(self) -> None:
        with self._idle:
            self.inflight -= 1
            if self.inflight == 0:
                self._idle.notify_all()

    def wait_idle(self, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._idle:
            while self.inflight > 0:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._idle.wait(timeout=remain)
        return True

    def count(self, field: str) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "inflight": self.inflight,
                "requests": dict(self.requests),
                "shed": dict(self.shed),
                "expired": self.expired,
                "failovers": self.failovers,
                "unroutable": self.unroutable,
                "relayed_5xx": self.relayed_5xx,
            }


class FleetRouter:
    """The dispatch brain; ``make_httpd`` binds the HTTP surface."""

    def __init__(self, fleet, default_deadline_ms: float = 0.0) -> None:
        self.fleet = fleet
        self.opts = fleet.opts
        self.sup = fleet.supervisor
        self.default_deadline_ms = float(default_deadline_ms)
        self.stats = FleetStats()
        self._metrics = fleet_metrics()  # hot path: no singleton lock
        self._lock = threading.Lock()       # replica inflight counters
        self._rng = random.Random(0xF1EE7)  # slice/sample draws
        self._rng_lock = threading.Lock()
        # mirror lane: bounded + lossy — shadow comparisons must never
        # apply backpressure to live traffic
        self._mirror_q: "queue.Queue[tuple]" = queue.Queue(maxsize=256)
        self._mirror_stop = threading.Event()
        self._mirror_thread: Optional[threading.Thread] = None
        if self.fleet.canary is not None:
            self._mirror_thread = threading.Thread(
                target=self._mirror_loop, name="cxxnet-fleet-mirror",
                daemon=True)
            self._mirror_thread.start()

    # ------------------------------------------------------------------
    # admission control
    def capacity(self) -> int:
        return self.opts.replica_inflight * max(
            1, len(self.sup.rotation()))

    def admit(self, priority: str) -> Optional[str]:
        """Admit-or-shed (atomic — see :meth:`FleetStats.try_enter`);
        an admitted caller owns a slot and must ``stats.leave()``.
        Batch sheds first: the 429 surface under pressure, interactive
        up to the full capacity bound."""
        return self.stats.try_enter(priority, self.capacity(),
                                    self.opts.batch_shed_ratio)

    # ------------------------------------------------------------------
    # replica selection
    def _canary_live(self) -> bool:
        c = self.fleet.canary
        return c is not None and c.state == "evaluating"

    def pick_replica(self, exclude=(),
                     want_canary: bool = False) -> Optional[Replica]:
        """Least-loaded healthy replica (ties break on index).  While a
        canary is evaluating it only receives its slice
        (``want_canary``); once promoted it serves at full weight."""
        rotation = self.sup.rotation()
        evaluating = self._canary_live()
        if want_canary:
            pool = [r for r in rotation if r.role == "canary"]
        elif evaluating:
            pool = [r for r in rotation if r.role != "canary"]
        else:
            pool = rotation
        pool = [r for r in pool if r not in exclude]
        if not pool:
            return None
        with self._lock:
            return min(pool, key=lambda r: (r.inflight, r.idx))

    def _draw(self, prob: float) -> bool:
        if prob <= 0.0:
            return False
        with self._rng_lock:
            return self._rng.random() < prob

    # ------------------------------------------------------------------
    # dispatch
    def _post_replica(self, r: Replica, path: str, obj: dict,
                      timeout_s: float) -> Tuple[int, dict]:
        req = urllib.request.Request(
            f"http://{r.address}{path}",
            data=json.dumps(obj).encode("utf-8"),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return resp.status, json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            # a replica ERROR RESPONSE (429/500/504...) relays as-is —
            # only network-layer failures trigger failover
            try:
                body = json.loads(e.read().decode("utf-8"))
            except Exception:  # noqa: BLE001 - non-JSON error body
                body = {"error": str(e)}
            return e.code, body

    def route(self, path: str, obj: dict,
              priority: str = "interactive") -> Tuple[int, dict]:
        """Admission + dispatch + failover for one request; returns
        ``(http_status, body)``.  The embeddable API the HTTP handler
        (and the tests) call."""
        m = self._metrics
        m.requests.labels(priority=priority).inc()
        reason = self.admit(priority)
        if reason is not None:
            m.shed.labels(priority=priority).inc()
            return 429, {"error": f"load shed: {reason}",
                         "priority": priority}
        m.inflight.set(self.stats.inflight)
        try:
            return self._dispatch(path, obj)
        finally:
            self.stats.leave()
            m.inflight.set(self.stats.inflight)

    def _dispatch(self, path: str, obj: dict) -> Tuple[int, dict]:
        t0 = time.monotonic()
        m = self._metrics
        deadline_ms = obj.get("deadline_ms")
        if deadline_ms is None and self.default_deadline_ms > 0:
            deadline_ms = self.default_deadline_ms
        try:
            deadline_val = (float(deadline_ms)
                            if deadline_ms is not None else 0.0)
        except (TypeError, ValueError):
            # client-input error: 400, matching the single-engine server
            return 400, {"error": f"bad deadline_ms: {deadline_ms!r}"}
        deadline_t = (t0 + deadline_val / 1e3
                      if deadline_val > 0 else None)
        is_predict = path == "/predict"
        want_canary = (is_predict and self._canary_live()
                       and self._draw(self.opts.canary_slice))
        tried: set = set()
        failures = 0
        while True:
            remaining_ms = None
            if deadline_t is not None:
                remaining_ms = (deadline_t - time.monotonic()) * 1e3
                if remaining_ms <= 0:
                    self.stats.count("expired")
                    return 504, {"error": "deadline expired before a "
                                          "replica could answer"}
            r = self.pick_replica(exclude=tried, want_canary=want_canary)
            if r is None and want_canary:
                want_canary = False  # canary unavailable: baseline serves
                continue
            if r is None:
                self.stats.count("unroutable")
                return 503, {"error": "no healthy replica available"}
            fwd = dict(obj)
            fwd.pop("priority", None)
            if remaining_ms is not None:
                # the execute share of the budget: whatever routing and
                # failover have not already consumed
                fwd["deadline_ms"] = remaining_ms
            timeout_s = self.opts.dispatch_timeout_s
            if remaining_ms is not None:
                timeout_s = min(timeout_s, remaining_ms / 1e3 + 1.0)
            with self._lock:
                r.inflight += 1
            t_send = time.monotonic()
            try:
                status, body = self._post_replica(r, path, fwd, timeout_s)
            except _DISPATCH_ERRORS as e:
                tried.add(r)
                failures += 1
                self.sup.note_dispatch_failure(r)
                if path == "/feedback":
                    # appends are not idempotent — never replayed
                    return 502, {"error": f"replica dispatch failed "
                                          f"({type(e).__name__}: {e}); "
                                          "feedback is not retried"}
                if failures > self.opts.dispatch_retries:
                    return 502, {"error": f"dispatch failed on "
                                          f"{failures} replica(s) "
                                          f"({type(e).__name__}: {e})"}
                # only an actual retry counts as a failover
                self.stats.count("failovers")
                m.failovers.inc()
                continue
            finally:
                with self._lock:
                    r.inflight -= 1
            dt = time.monotonic() - t_send
            with self._lock:
                r.dispatched += 1
            m.dispatch.labels(replica=str(r.idx)).inc()
            if status >= 500:
                self.stats.count("relayed_5xx")
            if is_predict and status == 200:
                self._canary_account(r, obj, body, dt)
            return status, body

    # ------------------------------------------------------------------
    # canary measurement
    def _canary_account(self, r: Replica, obj: dict, body: dict,
                        dt_s: float) -> None:
        c = self.fleet.canary
        if c is None or c.state != "evaluating":
            return
        m = self._metrics
        if r.role == "canary":
            m.canary_requests.labels(leg="slice").inc()
            c.record_latency("canary", dt_s)
            return
        c.record_latency("baseline", dt_s)
        if self._draw(self.opts.canary_sample):
            try:
                self._mirror_q.put_nowait((obj.get("data"),
                                           body.get("pred")))
            except queue.Full:
                pass  # lossy by design: shadow work never backpressures

    def _mirror_loop(self) -> None:
        while not self._mirror_stop.is_set():
            try:
                data, base_pred = self._mirror_q.get(timeout=0.2)
            except queue.Empty:
                continue
            c = self.fleet.canary
            if c is None or c.state != "evaluating" or base_pred is None:
                continue
            canary = self.pick_replica(want_canary=True)
            if canary is None:
                continue
            m = self._metrics
            t0 = time.monotonic()
            try:
                status, body = self._post_replica(
                    canary, "/predict", {"data": data},
                    self.opts.dispatch_timeout_s)
            except _DISPATCH_ERRORS:
                self.sup.note_dispatch_failure(canary)
                continue
            m.canary_requests.labels(leg="mirror").inc()
            if status != 200:
                continue
            c.record_latency("canary", time.monotonic() - t0)
            can_pred = body.get("pred")
            if not isinstance(can_pred, list):
                continue
            base = list(base_pred) if isinstance(base_pred, list) \
                else [base_pred]
            total = min(len(base), len(can_pred))
            equal = sum(1 for a, b in zip(base, can_pred) if a == b)
            c.record_compare(equal, total)

    # ------------------------------------------------------------------
    # HTTP surface
    def make_httpd(self, host: str, port: int) -> ThreadingHTTPServer:
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: N802 - stdlib name
                pass

            def _reply(self, status: int, payload) -> None:
                body = (payload if isinstance(payload, bytes)
                        else json.dumps(payload).encode("utf-8"))
                self.send_response(status)
                ctype = ("text/plain; version=0.0.4; charset=utf-8"
                         if isinstance(payload, bytes)
                         else "application/json")
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib name
                if self.path == "/healthz":
                    self._reply(200, router.fleet.healthz())
                elif self.path == "/statsz":
                    self._reply(200, router.fleet.statsz())
                elif self.path == "/metricsz":
                    from ..obs import registry as obs_registry

                    self._reply(200, obs_registry()
                                .render_prometheus().encode("utf-8"))
                elif self.path == "/alertz":
                    from ..obs import alerts as obs_alerts

                    self._reply(200, obs_alerts.evaluator().status())
                else:
                    self._reply(404,
                                {"error": f"unknown route {self.path}"})

            def do_POST(self):  # noqa: N802 - stdlib name
                if self.path not in ("/predict", "/extract", "/feedback"):
                    self._reply(404,
                                {"error": f"unknown route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    length = 0
                if length <= 0 or length > MAX_BODY_BYTES:
                    self._reply(400,
                                {"error": "missing or oversized body"})
                    return
                try:
                    obj = json.loads(self.rfile.read(length)
                                     .decode("utf-8"))
                except (ValueError, UnicodeDecodeError) as e:
                    self._reply(400, {"error": f"bad JSON: {e}"})
                    return
                if not isinstance(obj, dict) or "data" not in obj:
                    self._reply(400,
                                {"error": 'body must be {"data": [...]}'})
                    return
                priority = str(obj.get("priority")
                               or self.headers.get("X-Priority")
                               or "interactive")
                if priority not in PRIORITIES:
                    self._reply(400, {
                        "error": f"unknown priority {priority!r}; want "
                                 f"one of {'/'.join(PRIORITIES)}"})
                    return
                try:
                    status, body = router.route(self.path, obj, priority)
                except Exception as e:  # noqa: BLE001 - served as a 500
                    status, body = 500, {
                        "error": f"{type(e).__name__}: {e}"}
                self._reply(status, body)

        httpd = ThreadingHTTPServer((host, port), Handler)
        httpd.daemon_threads = True
        obs_events.emit("fleet.router_up", host=host,
                        port=httpd.server_port)
        return httpd

    def close(self, drain_timeout_s: float = 5.0) -> None:
        if drain_timeout_s > 0 and not self.stats.wait_idle(
                drain_timeout_s):
            obs_events.emit("fleet.drain_timeout",
                            inflight=self.stats.inflight)
        self._mirror_stop.set()
        if self._mirror_thread is not None:
            self._mirror_thread.join(timeout=5.0)
            self._mirror_thread = None
