"""Online inference serving subsystem.

The training half of the framework walks an iterator once and exits
(``cli.py`` tasks); this package is the serving half the ROADMAP's
"heavy traffic" north star requires: a model loaded from a validated
checkpoint, a shape-bucketed cache of compiled predict programs, a
dynamic micro-batcher with explicit backpressure, serving metrics, and
a stdlib HTTP front-end — ``task = serve`` in the CLI, or embed
:class:`Engine` directly:

    from cxxnet_tpu import serve
    eng = serve.Engine(cfg=conf_text, model_dir="models")
    pred = eng.submit(rows)            # thread-safe, micro-batched

See ``doc/serving.md`` for configuration and semantics.
"""

from .batcher import (  # noqa: F401
    ClosedError,
    DeadlineError,
    MicroBatcher,
    OverloadError,
    ServeError,
)
from .cache import ShapeBucketCache, bucket_size  # noqa: F401
from .engine import Engine, ModelLoadError  # noqa: F401
from .fleet import (  # noqa: F401
    CanaryController,
    FleetOptions,
    ReplicaSupervisor,
    ServingFleet,
)
from .metrics import ServingStats  # noqa: F401
from .router import FleetRouter  # noqa: F401
from .server import make_server, serve_forever  # noqa: F401
from . import wire  # noqa: F401
from .wire import WireError, WireRequest  # noqa: F401

__all__ = [
    "Engine",
    "MicroBatcher",
    "ShapeBucketCache",
    "ServingStats",
    "ServeError",
    "OverloadError",
    "DeadlineError",
    "ClosedError",
    "ModelLoadError",
    "bucket_size",
    "make_server",
    "serve_forever",
    "FleetOptions",
    "ReplicaSupervisor",
    "CanaryController",
    "ServingFleet",
    "FleetRouter",
    "wire",
    "WireError",
    "WireRequest",
]
