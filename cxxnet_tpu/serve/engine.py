"""Serving engine: checkpoint-validated model + batcher + predict cache.

:class:`Engine` is the embeddable core of the serving subsystem (the
HTTP front-end in ``server.py`` is one thin client of it; tests and the
bench tool drive it directly):

* **model loading** — from an explicit checkpoint path or the newest
  *valid* checkpoint in a model directory, using the fault-tolerant
  manifest machinery from PR 1 (CRC32 + size + net-fingerprint
  validation; corrupt/truncated checkpoints are skipped, never served);
* **compiled-predict cache** — a :class:`~cxxnet_tpu.serve.cache.
  ShapeBucketCache` so mixed request sizes stay within a handful of
  warm XLA programs;
* **dynamic micro-batching** — every request goes through the
  :class:`~cxxnet_tpu.serve.batcher.MicroBatcher`; ``submit`` is the
  direct Python API (numpy in, numpy out, thread-safe);
* **hot reload** — :meth:`reload_if_newer` loads a newer valid
  checkpoint into a FRESH trainer, warms its compile cache on the
  shapes already in service, then swaps it in atomically under the
  model lock; in-flight batches finish on the old model, the next
  batch runs on the new one;
* **metrics** — a :class:`~cxxnet_tpu.serve.metrics.ServingStats`
  shared with the front-end's ``/statsz``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..nnet.trainer import NetTrainer
from ..obs import events as obs_events
from ..obs import trace as obs_trace
from ..utils import checkpoint as ckpt
from ..utils import faults
from ..utils.faults import CircuitBreaker, RetryPolicy
from .batcher import ClosedError, MicroBatcher, ServeError
from .cache import ShapeBucketCache
from .metrics import ServingStats

__all__ = ["Engine", "ModelLoadError"]

ConfigEntry = Tuple[str, str]


class ModelLoadError(ServeError):
    """No usable checkpoint could be loaded."""

    http_status = 503


def _parse_cfg(cfg: Union[str, Sequence[ConfigEntry], None]):
    if cfg is None:
        return []
    if isinstance(cfg, str):
        from .. import config as cfgmod

        return list(cfgmod.parse_pairs(cfg))
    return list(cfg)


class Engine:
    """One served model behind a micro-batcher.

    ``cfg`` carries the netconfig (checkpoints store structure; layer
    settings come from the conf — the same contract as
    ``NetTrainer.load_model``) plus any trainer globals (``dev`` etc.).
    Exactly one model source: ``model_in`` (path), ``model_dir``
    (newest valid checkpoint; also the hot-reload watch directory), or
    ``trainer`` (an already-initialized trainer — embedding/bench use).
    """

    def __init__(
        self,
        cfg: Union[str, Sequence[ConfigEntry], None] = None,
        model_in: Optional[str] = None,
        model_dir: Optional[str] = None,
        trainer: Optional[NetTrainer] = None,
        max_batch_size: int = 0,
        batch_timeout_ms: float = 2.0,
        queue_limit: int = 128,
        default_deadline_ms: float = 0.0,
        silent: bool = True,
        reload_breaker_threshold: int = 3,
        reload_breaker_cooldown_s: float = 30.0,
        watchdog_timeout_s: float = 600.0,
    ) -> None:
        self._cfg = _parse_cfg(cfg)
        self.model_dir = model_dir
        self.silent = silent
        # quantized serving (doc/performance.md "Quantized inference"):
        # `quant = int8|bf16` prefers the gated `.quant.model` sibling
        # of whatever checkpoint discovery picks; absent a sibling the
        # trainer quantizes on load (ungated — the trainer emits the
        # event).  Validation of the value happens in the trainer.
        self.quant = ""
        # serve golden canary (doc/robustness.md "Integrity plane"):
        # integrity_probe = 1 records the probe-score CRC at model load
        # and periodically re-scores it — any drift on a frozen model
        # is memory/compute corruption and degrades /healthz
        self.integrity_probe = 0
        # binary wire protocol (doc/serving.md "Binary wire protocol"):
        # `wire = json` turns the application/x-cxb request path off —
        # binary frames get 400 reason=wire_disabled; `binary` (the
        # default) negotiates per request by Content-Type, with JSON
        # always accepted
        self.wire = "binary"
        for _n, _v in self._cfg:
            if _n == "quant":
                self.quant = ("" if _v in ("", "0", "off", "none")
                              else _v)
            elif _n == "wire":
                if _v not in ("binary", "json"):
                    raise ValueError(
                        f"wire must be binary or json, got {_v!r}")
                self.wire = _v
            elif _n == "integrity_probe":
                try:
                    self.integrity_probe = int(_v)
                except ValueError:
                    pass
        # persistent XLA compile cache BEFORE the warmup compiles (and
        # before any hot-reload's fresh-trainer warm), so serve restarts
        # and reload warms reuse on-disk programs instead of re-jitting
        from ..utils import compile_cache

        compile_cache.configure(self._cfg, silent=silent)
        self.default_deadline_ms = float(default_deadline_ms)
        # unified transient-I/O retry (doc/robustness.md): the old
        # hard-coded retry_io site, now driven by retry_* config keys
        self._retry = RetryPolicy.from_cfg(self._cfg)
        # hot-reload circuit breaker: consecutive reload failures open
        # it; the old model keeps serving and /healthz turns degraded
        self.reload_breaker = CircuitBreaker(
            failure_threshold=reload_breaker_threshold,
            cooldown_s=reload_breaker_cooldown_s,
        )
        self._model_lock = threading.RLock()
        self._round = -1
        self._model_path: Optional[str] = None
        self._model_crc: Optional[int] = None
        if trainer is not None:
            if trainer.net is None:
                raise ValueError("Engine(trainer=...): init/load it first")
            self._trainer = trainer
        elif model_in is not None:
            model_in = self._prefer_quant(model_in)
            reason = ckpt.validate_checkpoint(
                model_in, net_fp=self._conf_net_fp()
            )
            if reason is not None:
                raise ModelLoadError(f"{model_in}: {reason}")
            self._trainer = self._load_trainer(model_in)
            self._set_model(model_in)
        elif model_dir is not None:
            # newest checkpoint that both VALIDATES (manifest CRC) and
            # LOADS — a garbage payload with a self-consistent manifest
            # passes validation but explodes in load_model; fall back
            # past it instead of refusing to serve while an older good
            # checkpoint exists
            net_fp = self._conf_net_fp()
            before, last_err = None, None
            while True:
                found = ckpt.find_latest_valid(
                    model_dir, net_fp=net_fp, silent=silent, before=before
                )
                if found is None:
                    detail = f" (last load failure: {last_err})" if last_err else ""
                    raise ModelLoadError(
                        f"no loadable checkpoint in {model_dir!r}{detail}"
                    )
                load_path = self._prefer_quant(found[1])
                try:
                    trainer_ = self._load_trainer(load_path)
                except Exception as e:  # noqa: BLE001 - fall back past it
                    last_err = e
                    if load_path != found[1]:
                        # the quant SIBLING failed to load — the round's
                        # base f32 checkpoint may still be fine; try it
                        # before skipping the whole round
                        if not silent:
                            print(f"serve: quant artifact {load_path} "
                                  f"failed to load ({type(e).__name__}: "
                                  f"{e}); trying the f32 base",
                                  flush=True)
                        try:
                            trainer_ = self._load_trainer(found[1])
                            load_path = found[1]
                        except Exception as e2:  # noqa: BLE001
                            last_err = e2
                            before = found[0]
                            continue
                    else:
                        if not silent:
                            print(f"serve: checkpoint {load_path} failed "
                                  f"to load ({type(e).__name__}: {e}); "
                                  "falling back to an older round",
                                  flush=True)
                        before = found[0]
                        continue
                self._round = found[0]
                self._trainer = trainer_
                self._set_model(load_path, found[0])
                break
        else:
            raise ValueError(
                "Engine needs one of model_in / model_dir / trainer"
            )
        if self._trainer.graph.extra_data_num:
            raise ValueError(
                "serving does not support nets with extra_data nodes"
            )
        if max_batch_size <= 0:
            max_batch_size = self._trainer.batch_size or 64
        self.max_batch_size = max_batch_size
        self.stats = ServingStats()
        self._cache = ShapeBucketCache(self._trainer, max_batch_size)
        self._row_shapes = self._allowed_row_shapes(self._trainer)
        # request-shape histogram: (pow2 bucket, row shape) -> request
        # count, fed by submit(); the speculative prewarm reads it to
        # compile buckets BEFORE the first coalesced batch of that size
        # stalls on XLA.  The row shape is part of the key because the
        # compiled programs are specialized per row shape too (native
        # 4-D vs the flat wrapper spelling are different programs).
        self._req_buckets: Dict[tuple, int] = {}
        self._req_lock = threading.Lock()
        self.batcher = MicroBatcher(
            self._run_batch,
            max_batch_size=max_batch_size,
            batch_timeout_ms=batch_timeout_ms,
            queue_limit=queue_limit,
            stats=self.stats,
            watchdog_timeout_s=watchdog_timeout_s,
        )
        self._closed = False
        self._export_weight_gauges()
        # golden canary state: the probe batch, the CRC it must keep
        # reproducing, and the sticky failure latch /healthz reports
        self.inject_canary_mismatch = 0  # tests: corrupt the next N CRCs
        self._canary_probe: Optional[np.ndarray] = None
        self._canary_golden: Optional[int] = None
        self._canary_src = ""
        self._canary_failed = False
        self._canary_runs = 0
        self._canary_setup()
        from ..tune.controller import set_effective

        set_effective("max_batch_size", self.batcher.max_batch_size)
        set_effective("batch_timeout_ms", self.batcher.batch_timeout * 1e3)
        obs_events.emit("serve.start", round=self._round,
                        model=self._model_path,
                        max_batch_size=self.max_batch_size)

    # ------------------------------------------------------------------
    # loading
    def _prefer_quant(self, path: str) -> str:
        """Under ``quant = <scheme>``: the checkpoint's ``.quant.model``
        sibling when it exists, validates, and carries the requested
        scheme; else the original path (the trainer then quantizes on
        load — ungated)."""
        if not self.quant:
            return path
        from ..nnet.quant import quant_artifact_path

        qp = quant_artifact_path(path)
        if qp == path or not os.path.exists(qp):
            return path
        if ckpt.validate_checkpoint(qp, net_fp=self._conf_net_fp()) is not None:
            return path
        man = ckpt.read_manifest(qp) or {}
        scheme = (man.get("quant") or {}).get("scheme")
        if scheme != self.quant:
            return path
        return qp

    def _export_weight_gauges(self) -> None:
        """Publish ``serve_weight_bytes`` / ``serve_weight_bytes_f32``
        and the one-hot ``serve_quant_scheme{scheme}`` for the CURRENT
        trainer — the observable proof the int8 export actually shrank
        the served weights (~4x; the QUANT lane asserts >= 3.5x)."""
        from ..ops import quant as opsq
        from .metrics import serve_metrics

        try:
            actual, f32_equiv = opsq.weight_bytes(self._trainer.params)
            scheme = opsq.scheme_of(self._trainer) or "f32"
        except Exception:  # noqa: BLE001 - telemetry must never raise
            return
        m = serve_metrics()
        m.weight_bytes.set(actual)
        m.weight_bytes_f32.set(f32_equiv)
        for s in ("f32", "int8", "bf16"):
            m.quant_scheme.labels(scheme=s).set(1.0 if s == scheme
                                                else 0.0)

    def _conf_net_fp(self) -> Optional[str]:
        """Fingerprint of the conf's netconfig for manifest validation
        (None when the conf carries none — validation then skips the
        fingerprint cross-check, manifest CRC still applies)."""
        from ..nnet.graph import NetGraph

        try:
            g = NetGraph()
            g.configure(self._cfg)
            return ckpt.net_fingerprint(g.structure_to_json())
        except Exception:
            return None

    def _load_trainer(self, path: str) -> NetTrainer:
        tr = NetTrainer()
        tr.set_params(self._cfg)
        self._retry.run(lambda: tr.load_model(path),
                        what=f"loading {path}", silent=self.silent)
        return tr

    def _set_model(self, path: str, round_: Optional[int] = None) -> None:
        self._model_path = path
        man = ckpt.read_manifest(path)
        if round_ is not None:
            self._round = round_
        else:
            r = ckpt.checkpoint_round(path)
            if man is not None and man.get("round") is not None:
                r = int(man["round"])
            self._round = r if r is not None else -1
        # the served WEIGHTS' identity: the checkpoint payload CRC from
        # the manifest (the net fingerprint only identifies structure —
        # every round of one net shares it).  Gauged into /metricsz so
        # a scrape shows gated publishes landing (doc/serving.md).
        self._model_crc = (int(man["crc32"])
                           if man is not None and man.get("crc32")
                           is not None else None)
        from .metrics import serve_metrics

        m = serve_metrics()
        m.model_round.set(self._round)
        m.model_crc.set(self._model_crc if self._model_crc is not None
                        else -1)

    @staticmethod
    def _allowed_row_shapes(tr: NetTrainer) -> List[Tuple[int, ...]]:
        """Row shapes a request may carry: the net's native input row,
        plus its flat spelling (the wrapper contract: flat ``(N, D)``
        is accepted wherever a 4-D tensor is)."""
        row = tuple(tr.net.input_node_shape(1)[1:])
        shapes = [row]
        flat = (int(np.prod(row)),)
        if flat != row:
            shapes.append(flat)
        return shapes

    # ------------------------------------------------------------------
    # request path
    def _validate(self, data) -> np.ndarray:
        arr = np.ascontiguousarray(data, np.float32)
        if arr.ndim == 1 and (arr.shape[0],) in self._row_shapes:
            arr = arr[None, :]  # single flat instance
        if arr.ndim < 2 or arr.shape[0] < 1:
            raise ValueError(
                f"request must be a (N, ...) batch of at least one row, "
                f"got shape {arr.shape}"
            )
        if arr.shape[0] > self.max_batch_size:
            # without this cap a single huge request would bypass both
            # max_batch_size and the queue bound (queue_limit counts
            # requests, not rows) and pad to an even bigger bucket
            raise ValueError(
                f"request has {arr.shape[0]} rows, above the server's "
                f"max_batch_size={self.max_batch_size}; split it into "
                f"smaller requests"
            )
        if tuple(arr.shape[1:]) not in self._row_shapes:
            raise ValueError(
                f"bad input row shape {tuple(arr.shape[1:])}; this model "
                f"accepts rows of shape "
                f"{' or '.join(str(s) for s in self._row_shapes)}"
            )
        return arr

    def _run_batch(self, kind: str, node: Optional[str],
                   data: np.ndarray) -> np.ndarray:
        """Batcher callback: one coalesced batch through the CURRENT
        model's bucket cache (the lock makes the model swap atomic with
        respect to batch execution)."""
        faults.fault_point("serve.batch")
        with self._model_lock:
            cache = self._cache
        n = data.shape[0]
        self.stats.record_batch(n, cache.bucket_for(n))
        with obs_trace.span("serve.batch", kind=kind, rows=n):
            if kind == "extract":
                return cache.extract(data, node)
            if kind == "scores":
                return cache.scores(data)
            return cache.predict(data)

    def submit(
        self,
        data,
        kind: str = "predict",
        node: Optional[str] = None,
        deadline_ms: Optional[float] = None,
    ) -> np.ndarray:
        """The direct (embedding) API: block until the request's rows
        come back through the micro-batcher.  Thread-safe; concurrent
        callers are what the batcher exists to coalesce.

        ``kind``: ``predict`` (argmax/value per instance), ``scores``
        (raw f32 out-node rows), or ``extract`` (features of ``node``).
        Raises ``OverloadError`` / ``DeadlineError`` / ``ValueError``
        on shed, expiry, or malformed input."""
        if self._closed:
            raise ClosedError("engine is closed")
        if kind not in ("predict", "scores", "extract"):
            raise ValueError(f"unknown request kind {kind!r}")
        if kind == "extract" and not node:
            raise ValueError("extract requests need a node name")
        arr = self._validate(data)
        with self._model_lock:
            bucket = self._cache.bucket_for(arr.shape[0])
        hkey = (bucket, tuple(arr.shape[1:]))
        with self._req_lock:
            self._req_buckets[hkey] = self._req_buckets.get(hkey, 0) + 1
        self.stats.record_request(arr.shape[0], bucket=bucket)
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        t0 = time.monotonic()
        try:
            out = self.batcher.submit(
                arr, kind=kind, node=node if kind == "extract" else None,
                deadline_ms=deadline_ms,
            )
        except ServeError as e:
            self.stats.record_outcome(
                "shed" if e.http_status == 429
                else "expired" if e.http_status == 504 else "error"
            )
            raise
        except BaseException:
            self.stats.record_outcome("error")
            raise
        self.stats.record_outcome("ok", time.monotonic() - t0)
        return out

    def predict(self, data, deadline_ms: Optional[float] = None):
        return self.submit(data, kind="predict", deadline_ms=deadline_ms)

    def extract(self, data, node: str,
                deadline_ms: Optional[float] = None):
        return self.submit(data, kind="extract", node=node,
                           deadline_ms=deadline_ms)

    # ------------------------------------------------------------------
    # hot reload
    def reload_if_newer(self) -> bool:
        """Swap to a newer valid checkpoint in ``model_dir`` (no-op and
        False when there is none, when the engine was built without a
        watch directory, or when the newest round is already serving
        from its preferred artifact — under ``quant=`` a gated
        ``.quant.model`` sibling appearing for the CURRENT round does
        swap in; rounds never move backward).

        The new trainer is built and its compile cache warmed on every
        bucket shape currently in service BEFORE the swap, so the first
        requests after a reload do not stall behind XLA compiles; the
        swap itself is a pointer flip under the model lock."""
        if self.model_dir is None:
            return False
        faults.fault_point("serve.reload")
        found = ckpt.find_latest_valid(
            self.model_dir, net_fp=self._conf_net_fp(), silent=self.silent
        )
        if found is None or found[0] < self._round:
            return False
        round_, path = found
        path = self._prefer_quant(path)
        if round_ == self._round and path == self._model_path:
            return False
        # same round, different path: a gated .quant.model sibling
        # appeared for the round already serving (export after serve
        # start) — swap onto it; rounds still never move backward
        tr = self._load_trainer(path)
        cache = ShapeBucketCache(tr, self._cache.max_batch_size)
        self._warm(cache)
        with self._model_lock:
            old_round = self._round
            self._trainer = tr
            self._cache = cache
            self._row_shapes = self._allowed_row_shapes(tr)
            self._set_model(path, round_)
        self._export_weight_gauges()
        # new model bytes: re-base the golden canary (and clear any
        # integrity latch — a reload is the operator's recovery path)
        self._canary_setup()
        obs_events.emit("serve.reload", ok=True, swapped=True,
                        round=round_, old_round=old_round, path=path)
        if not self.silent:
            print(f"serve: hot-reloaded round {round_} from {path}",
                  flush=True)
        return True

    def try_reload(self) -> bool:
        """:meth:`reload_if_newer` behind the circuit breaker — the
        reload poll loop's entry point.  Never raises: a failed reload
        is recorded (``reload_failures`` in ``/statsz``), trips the
        breaker after ``reload_breaker_threshold`` consecutive
        failures, and the OLD model keeps serving; while the breaker is
        open polls are skipped entirely (the back-off), and ``/healthz``
        reports ``degraded``.  Returns True only when a newer model was
        actually swapped in."""
        if not self.reload_breaker.allow():
            return False
        try:
            swapped = self.reload_if_newer()
        except Exception as e:  # noqa: BLE001 - degrade, don't die
            self.reload_breaker.record_failure()
            self.stats.record_reload(ok=False)
            state = self.reload_breaker.state
            obs_events.emit("serve.reload", ok=False,
                            error=f"{type(e).__name__}: {e}",
                            breaker=state, serving_round=self._round)
            if not self.silent:
                print(f"serve: reload failed ({type(e).__name__}: {e}); "
                      f"breaker {state}, serving round {self._round}",
                      flush=True)
            return False
        self.reload_breaker.record_success()
        self.stats.record_reload(ok=True, swapped=swapped)
        return swapped

    def reload_degraded(self) -> bool:
        """True while the reload breaker is not closed — the model
        still serves, but it may be stale.  A single sub-threshold
        poll failure does NOT degrade health (a load balancer keying
        on /healthz must not pull the instance for one transient
        blip — that threshold is exactly what the breaker provides);
        per-poll detail stays observable as ``last_reload_ok`` in
        /statsz."""
        return self.reload_breaker.state != "closed"

    def _warm(self, cache: ShapeBucketCache) -> None:
        """Compile the new model for every (kind, node, bucket, shape)
        the old cache served, by running zero batches through it."""
        with self._model_lock:
            keys = self._cache.keys_snapshot()
        for _fp, kind, node_id, bucket, row_shape, dtype, _kf, _q in keys:
            zeros = np.zeros((bucket,) + tuple(row_shape), dtype)
            try:
                cache._run(kind, node_id, zeros)
            except Exception:
                if not self.silent:
                    print(f"serve: warmup failed for bucket {bucket} "
                          f"shape {row_shape}", flush=True)

    # ------------------------------------------------------------------
    # live knobs + speculative prewarm (the self-tuning controller's
    # surface; doc/performance.md "Self-tuning runtime")
    def set_max_batch_size(self, n: int, prewarm: bool = True) -> int:
        """Retune the micro-batcher's coalescing limit at runtime,
        clamped to the engine's configured ``max_batch_size`` (the
        request-validation cap and largest compiled bucket).  With
        ``prewarm`` (the default) the new limit's bucket is compiled
        BEFORE the limit applies, on the calling thread — the first
        bigger coalesced batch then hits a warm program instead of
        stalling every submitter behind XLA."""
        n = max(1, min(int(n), self.max_batch_size))
        if prewarm:
            # warm the DOMINANT observed request row shape (or the
            # native shape before any traffic) — programs specialize
            # per row shape, so warming the wrong one buys nothing
            self._warm_bucket(self._bucket_for(n),
                              self._dominant_row_shape())
        self.batcher.set_max_batch_size(n)
        from ..tune.controller import set_effective

        set_effective("max_batch_size", n)
        return n

    def set_batch_timeout_ms(self, ms: float) -> float:
        """Retune the micro-batcher's batch-open window at runtime."""
        out = self.batcher.set_batch_timeout_ms(ms)
        from ..tune.controller import set_effective

        set_effective("batch_timeout_ms", out)
        return out

    def _bucket_for(self, n: int) -> int:
        with self._model_lock:
            return self._cache.bucket_for(n)

    def _dominant_row_shape(self) -> Tuple[int, ...]:
        """The most-requested row shape so far (native shape before
        any traffic) — what a speculative warm should compile for."""
        with self._req_lock:
            if self._req_buckets:
                (_b, shape), _ = max(self._req_buckets.items(),
                                     key=lambda kv: kv[1])
                return tuple(shape)
        return tuple(self._row_shapes[0])

    def _warm_bucket(self, bucket: int,
                     row_shape: Tuple[int, ...]) -> bool:
        """Compile the predict program for ``bucket`` rows of
        ``row_shape`` (no-op when that exact program is already warm —
        programs specialize per row shape, so the native 4-D and the
        flat wrapper spelling are distinct entries).  Thread-safe
        against the batcher — JAX dispatch is; the model lock is only
        held to snapshot the cache pointer."""
        row_shape = tuple(row_shape)
        with self._model_lock:
            cache = self._cache
        if any(k[1] == "out" and k[3] == bucket
               and tuple(k[4]) == row_shape
               for k in cache.keys_snapshot()):
            return False
        zeros = np.zeros((bucket,) + row_shape, np.float32)
        try:
            cache._run("out", None, zeros)
        except Exception as e:  # noqa: BLE001 - a failed warm only costs
            obs_events.log_exception_once(   # the later cold compile
                "serve.prewarm", e, kind="tune.error", bucket=bucket)
            return False
        return True

    def prewarm_buckets(self, max_new: int = 2) -> list:
        """Speculatively compile the hottest not-yet-warm
        (bucket, row shape) programs from the request-shape histogram
        (``serve_request_bucket_total`` / ``/statsz`` request_buckets),
        up to the current live batch limit.  Cheap when everything hot
        is already warm; the controller runs it once per tick."""
        with self._req_lock:
            hist = sorted(self._req_buckets.items(), key=lambda kv: -kv[1])
        ceiling = self._bucket_for(self.batcher.max_batch_size)
        warmed = []
        for (bucket, shape), count in hist:
            if len(warmed) >= max_new:
                break
            if bucket > ceiling:
                continue
            if self._warm_bucket(bucket, shape):
                warmed.append(bucket)
                obs_events.emit("tune.prewarm", bucket=bucket,
                                row_shape=list(shape), requests=count)
        return warmed

    # ------------------------------------------------------------------
    # serve golden canary (doc/robustness.md "Integrity plane")
    def _score_probe(self, probe: np.ndarray) -> int:
        from ..integrity import canary as integ_canary

        with self._model_lock:
            cache = self._cache
        return integ_canary.scores_crc(cache._run("out", None, probe))

    def _canary_setup(self) -> None:
        """(Re)base the golden at model load.  The manifest's ``probe``
        block (written by the trainer under ``integrity_probe = 1``)
        commits the probe batch; its recorded CRC is only binding when
        this engine scores through the same pipeline class (same
        backend, unquantized) AND reproduces it — a legitimate
        pipeline difference (or a distinct predict program) re-bases
        the golden to the load-time score with an
        ``integrity.golden_rebased`` event instead of a false alarm.
        Either way the periodic :meth:`check_canary` holds this frozen
        model to the load-time answer bit-for-bit."""
        if not self.integrity_probe:
            return
        import jax

        from ..integrity import canary as integ_canary

        self._canary_failed = False
        block = None
        if self._model_path is not None:
            man = ckpt.read_manifest(self._model_path) or {}
            block = man.get("probe")
        if not isinstance(block, dict):
            rows = max(1, min(8, self.max_batch_size))
            block = integ_canary.make_probe_block(
                0xC0FFEE, rows, tuple(self._row_shapes[0]), None,
                jax.default_backend())
        try:
            probe = integ_canary.probe_batch(
                block["seed"], block["rows"], tuple(block["shape"]))
            crc_now = integ_canary.scores_crc(
                self._cache._run("out", None, probe))
        except Exception as e:  # noqa: BLE001 - canary must not block serve
            obs_events.log_exception_once(
                "serve.canary_setup", e, kind="integrity.error",
                model=self._model_path)
            self._canary_probe = None
            self._canary_golden = None
            return
        binding = integ_canary.block_matches_pipeline(
            block, backend=jax.default_backend(),
            quant=bool(self.quant_scheme))
        if binding and int(block["crc32"]) == crc_now:
            src = "manifest"
        else:
            src = "local" if block.get("crc32") is None else "rebased"
            if src == "rebased":
                obs_events.emit(
                    "integrity.golden_rebased", round=self._round,
                    model=self._model_path,
                    manifest_crc32=block.get("crc32"),
                    local_crc32=crc_now, binding=binding,
                    backend=jax.default_backend(),
                    quant=self.quant_scheme or "f32")
        self._canary_probe = probe
        self._canary_golden = crc_now
        self._canary_src = src

    def check_canary(self) -> bool:
        """One golden comparison: re-score the committed probe batch
        and compare its CRC against the load-time golden bitwise.  The
        model bytes and the predict program are frozen between
        reloads, so ANY drift is memory or compute corruption: the
        failure latch degrades ``/healthz`` with ``integrity_failed``
        (the fleet supervisor ejects the replica from rotation without
        killing it) and clears on the next clean score or model
        reload.  Returns True when clean or disabled; never raises."""
        if self._canary_golden is None or self._canary_probe is None:
            return True
        from ..obs.registry import registry as obs_registry

        try:
            crc = self._score_probe(self._canary_probe)
        except Exception as e:  # noqa: BLE001 - degrade, don't die
            obs_events.log_exception_once(
                "serve.canary", e, kind="integrity.error",
                model=self._model_path)
            return True
        if self.inject_canary_mismatch > 0:
            self.inject_canary_mismatch -= 1
            crc ^= 0x1
        self._canary_runs += 1
        clean = crc == self._canary_golden
        obs_registry().counter(
            "integrity_checks_total",
            "Integrity-plane checks by kind and verdict.",
            labelnames=("kind", "verdict"),
        ).labels(kind="canary",
                 verdict="clean" if clean else "corrupt").inc()
        if clean:
            if self._canary_failed:
                self._canary_failed = False
                obs_events.emit("integrity.clean", kind="canary",
                                round=self._round, crc32=crc)
            return True
        first = not self._canary_failed
        self._canary_failed = True
        obs_registry().counter(
            "integrity_failures_total",
            "Integrity-plane corruption verdicts.",
            labelnames=("kind",),
        ).labels(kind="canary").inc()
        obs_events.emit("integrity.detect", kind="canary",
                        round=self._round, model=self._model_path,
                        golden_crc32=self._canary_golden, crc32=crc)
        if first and not self.silent:
            print(f"serve: integrity canary FAILED (golden "
                  f"{self._canary_golden:#010x} != {crc:#010x}); "
                  "/healthz degraded integrity_failed", flush=True)
        return False

    # ------------------------------------------------------------------
    # introspection
    @property
    def round(self) -> int:
        return self._round

    @property
    def model_path(self) -> Optional[str]:
        """Path of the checkpoint currently serving (None when built
        from an in-memory trainer)."""
        with self._model_lock:
            return self._model_path

    @property
    def model_crc32(self) -> Optional[int]:
        """Manifest CRC32 of the served checkpoint payload — the
        weights fingerprint (the net fingerprint only identifies the
        structure)."""
        with self._model_lock:
            return self._model_crc

    @property
    def quant_scheme(self) -> str:
        """Precision scheme of the served weights ("" for plain f32)."""
        with self._model_lock:
            return self._cache.quant_scheme()

    @property
    def trainer(self) -> NetTrainer:
        """The live trainer (swapped by hot reload; hold no references
        across requests)."""
        with self._model_lock:
            return self._trainer

    def healthz(self) -> Dict[str, object]:
        # firing alert rules degrade health exactly like an open reload
        # breaker: the model still serves, but a load balancer keying on
        # /healthz sees (and can act on) the named condition
        from ..obs import alerts as obs_alerts
        from ..parallel import elastic as par_elastic

        firing = obs_alerts.evaluator().firing()
        # a serve-colocated trainer mid mesh-rebuild degrades health the
        # same way an open reload breaker does: the model still serves,
        # but a load balancer sees the named transient condition
        rebuilding = par_elastic.rebuild_in_progress()
        # every degrade condition as a stable machine-readable token —
        # what the fleet supervisor's probe parses (doc/serving.md);
        # the legacy fields (mesh/alerts/reload_breaker) stay for
        # compatibility
        reasons: List[str] = []
        if self.reload_degraded():
            reasons.append("reload_breaker_open")
        if rebuilding:
            reasons.append("mesh_rebuilding")
        if self._canary_failed:
            # golden canary drift (integrity plane): the replica still
            # answers, but its compute can no longer be trusted — the
            # fleet supervisor ejects it from rotation without killing
            # it and readmits it after a clean canary
            reasons.append("integrity_failed")
        reasons.extend(f"alert:{name}" for name in firing)
        with self._model_lock:
            status = ("closed" if self._closed
                      else "degraded" if reasons else "ok")
            out = {
                "status": status,
                "round": self._round,
                "model": self._model_path,
                "model_crc32": self._model_crc,
                "net_fp": self._cache.net_fp(),
                "quant": self._cache.quant_scheme() or "f32",
                "reload_breaker": self.reload_breaker.state,
                "reasons": reasons,
            }
            if rebuilding:
                out["mesh"] = "rebuilding"
            if firing:
                out["alerts"] = firing
            return out

    def snapshot_stats(self) -> Dict[str, object]:
        out = self.stats.snapshot()
        from ..ops import quant as opsq

        with self._model_lock:
            out["compile_cache"] = self._cache.stats()
            wb, wb32 = opsq.weight_bytes(self._trainer.params)
            out["model"] = {
                "path": self._model_path,
                "round": self._round,
                "crc32": self._model_crc,
                "net_fp": self._cache.net_fp(),
                "quant": self._cache.quant_scheme() or "f32",
                "weight_bytes": wb,
                "weight_bytes_f32": wb32,
            }
        out["batcher"] = {
            "max_batch_size": self.batcher.max_batch_size,
            "batch_timeout_ms": self.batcher.batch_timeout * 1e3,
            "queue_limit": self.batcher.queue_limit,
        }
        # the CURRENT effective knob values (the batcher block reports
        # the same numbers but this block is the stable tuning surface:
        # what the controller chose, mirrored as tune_effective{knob}
        # gauges in /metricsz)
        out["tune_effective"] = {
            "max_batch_size": self.batcher.max_batch_size,
            "batch_timeout_ms": self.batcher.batch_timeout * 1e3,
        }
        agg: Dict[int, int] = {}
        with self._req_lock:
            for (b, _shape), c in self._req_buckets.items():
                agg[b] = agg.get(b, 0) + c
        out["request_buckets"] = {str(k): v for k, v in sorted(agg.items())}
        out["reload_breaker"] = self.reload_breaker.snapshot()
        if self.integrity_probe:
            out["integrity"] = {
                "probe": 1,
                "golden_crc32": self._canary_golden,
                "golden_src": self._canary_src,
                "runs": self._canary_runs,
                "failed": self._canary_failed,
            }
        return out

    # ------------------------------------------------------------------
    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self.batcher.close()

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
