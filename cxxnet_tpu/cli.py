"""Task driver: ``python -m cxxnet_tpu <config.conf> [name=val ...]``.

Parity: ``CXXNetLearnTask`` (``/root/reference/src/cxxnet_main.cpp``):
tasks ``train`` / ``pred`` / ``extract`` / ``finetune``; round loop with
per-round evaluation lines ``[round]\\tname-metric:value`` on stderr;
``%04d.model`` checkpoints every ``save_model`` rounds in ``model_dir``;
``continue=1`` resumes from the newest checkpoint; ``model_in`` loads a
model (inferring ``start_counter`` from its filename); ``test_io=1``
pulls batches without updating (IO throughput dry-run); ``print_step``
progress lines; ``max_round`` caps rounds this invocation.

New scope beyond the reference: ``task = serve`` runs the online
inference server (``serve/`` subsystem, doc/serving.md) — dynamic
micro-batching over an HTTP JSON endpoint with hot model reload.
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional

import numpy as np

from . import config as cfgmod
from .io.data import DataIter, create_iterator
from .nnet.trainer import NetTrainer


class LearnTask:
    def __init__(self) -> None:
        self.task = "train"
        self.net_trainer: Optional[NetTrainer] = None
        self.itr_train: Optional[DataIter] = None
        self.itr_pred: Optional[DataIter] = None
        self.itr_evals: List[DataIter] = []
        self.eval_names: List[str] = []
        self.name_model_dir = "models"
        self.num_round = 10
        self.max_round = 1 << 30
        self.test_io = 0
        self.test_on_server = 0
        self.silent = 0
        self.start_counter = 0
        self.continue_training = 0
        self.save_period = 1
        self.keep_latest = 0  # retention: 0 keeps every checkpoint
        self.divergence_policy = ""  # "" off | abort | rollback
        self.divergence_lr_backoff = 0.5
        self.divergence_max_retries = 3
        # integrity plane (cxxnet_tpu/integrity/, doc/robustness.md
        # "Integrity plane"): fingerprint-vote cadence, shadow-step
        # audit, serve golden-canary probe committed at save
        self.integrity_every = 0     # rounds between votes; 0 = off
        self.integrity_shadow = 0    # 1: shadow-step audit at cadence
        self.integrity_probe = 0     # 1: commit probe block at save
        self._integrity = None       # IntegrityPlane, built in run()
        self._integrity_rollback_before = None  # quarantine bound
        self.name_model_in = "NULL"
        self.name_pred = "pred.txt"
        self.print_step = 100
        self.extract_node_name = ""
        self.output_format = 1
        self.scan_steps = 1
        self.gen_prompt = ""
        self.gen_prompt_file = ""
        self.gen_len = 256
        self.gen_temp = 0.0
        self.gen_topk = 0
        self.gen_topp = 0.0
        self.gen_cache = 1
        self.serve_host = "127.0.0.1"
        self.serve_port = 9090
        # serving fleet (doc/serving.md "Serving fleet"): replicas >= 2
        # turns task=serve into a supervised multi-process fleet behind
        # one routing front-end; fleet_* / canary_* keys are parsed by
        # serve.fleet.FleetOptions from the raw cfg stream
        self.replicas = 1
        self.serve_max_batch = 0  # 0: the trainer's batch_size
        self.batch_timeout_ms = 2.0
        self.queue_limit = 128
        self.serve_reload_period = 0.0  # seconds; 0 disables hot reload
        self.serve_deadline_ms = 0.0  # default per-request deadline
        self.wire = "binary"  # accept binary x-cxb frames (json = refuse)
        self.drain_timeout_s = 5.0  # SIGTERM: flush in-flight this long
        self.reload_breaker_threshold = 3
        self.reload_breaker_cooldown_s = 30.0
        self.watchdog_timeout_s = 600.0  # serve batcher stall guard
        # disaggregated input-data service (task=data_service,
        # io/dataservice/, doc/io.md "Data service"): a shared decode/
        # augment fleet member serving CXD1 batch streams
        self.data_service_host = "127.0.0.1"
        self.data_service_port = 0  # 0 picks an ephemeral port
        self.data_service_http_port = 0
        self.data_service_max_sessions = 64
        self.data_service_cache_mb = 256.0
        self.data_service_window = 4
        self.data_service_ready_file = ""
        self.telemetry = 0  # per-round JSONL records (doc/observability.md)
        self.telemetry_path = "telemetry.jsonl"
        # self-tuning knob controller (cxxnet_tpu/tune/,
        # doc/performance.md): tune_* keys are parsed by
        # tune.options_from_cfg from the raw cfg stream
        self.controller = 0
        # closed-loop continuous training (task=serve_train,
        # doc/continuous_training.md).  The loop_*/publish_*/feedback_*
        # defaults live in ONE table shared with the per-tenant parser
        # (loop/tenant.py TenantOptions) so task=serve_train and
        # task=loop_fleet can never drift apart on the same conf.
        from .loop.tenant import TenantOptions

        for _key, _default in TenantOptions.DEFAULTS.items():
            setattr(self, _key, _default)
        self.loop_dir = "loop"
        self.loop_cycle_period_s = 2.0
        self.loop_max_cycles = 0  # stop fine-tuning after N trained cycles
        self.capture_predict = 0  # log /predict inputs+predictions too
        # multi-tenant loops (task=loop_fleet, loop/tenant.py): keys
        # inside a 'tenant = <name>' .. 'tenant = end' section bind to
        # that tenant, not to the driver
        self._in_tenant_section = False
        # quantized inference (task=export_quant / quant= at serve
        # time; doc/performance.md "Quantized inference")
        self.quant = "int8"  # export scheme (serve reads the raw key)
        self.quant_min_agreement = 0.99
        self.quant_calib_batches = 0  # 0 = the whole eval set
        self.quant_out = ""  # artifact path override
        self.quant_report = ""  # also write the verdict JSON here
        # elastic pod (doc/parallel.md "Elastic pod"): parsed once in
        # run() from the elastic_* / collective_timeout_s keys
        self.elastic_opts = None
        self.elastic_member = None
        self._elastic_joined = False
        self._elastic_left = False
        self._elastic_drop_done = False
        self._elastic_rebuilds = 0
        self._elastic_consec_recoveries = 0
        self._elastic_attempted_gen = 0
        self._elastic_last_rebuild_s = 0.0
        self.conf_path = ""
        self.cli_overrides: List[str] = []
        self.cfg: List[tuple] = []

    # ------------------------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        # tenant sections pass through untouched: a tenant's model_dir
        # (or any other key) must never clobber the driver's globals —
        # loop/tenant.py re-splits them from the raw stream
        if name == "tenant":
            self._in_tenant_section = val != "end"
            self.cfg.append((name, val))
            return
        if self._in_tenant_section:
            self.cfg.append((name, val))
            return
        if val == "default":
            return
        if name == "print_step":
            self.print_step = int(val)
        elif name == "continue":
            self.continue_training = int(val)
        elif name == "save_model":
            self.save_period = int(val)
        elif name == "keep_latest":
            self.keep_latest = int(val)
        elif name == "divergence_policy":
            self.divergence_policy = "" if val == "off" else val
        elif name == "divergence_lr_backoff":
            self.divergence_lr_backoff = float(val)
        elif name == "divergence_max_retries":
            self.divergence_max_retries = int(val)
        elif name == "integrity_every":
            self.integrity_every = int(val)
        elif name == "integrity_shadow":
            self.integrity_shadow = int(val)
        elif name == "integrity_probe":
            self.integrity_probe = int(val)
        elif name == "start_counter":
            self.start_counter = int(val)
        elif name == "model_in":
            self.name_model_in = val
        elif name == "model_dir":
            self.name_model_dir = val
        elif name == "num_round":
            self.num_round = int(val)
        elif name == "max_round":
            self.max_round = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "task":
            self.task = val
        elif name == "test_io":
            self.test_io = int(val)
        elif name == "test_on_server":
            # per-round cross-process weight-divergence check
            # (reference async_updater-inl.hpp:148-153 discipline)
            self.test_on_server = int(val)
        elif name == "extract_node_name":
            self.extract_node_name = val
        elif name == "output_format":
            self.output_format = 1 if val == "txt" else 0
        elif name == "scan_steps":
            self.scan_steps = int(val)
        elif name == "gen_prompt":
            self.gen_prompt = val
        elif name == "gen_prompt_file":
            self.gen_prompt_file = val  # read lazily in task_generate
        elif name == "gen_len":
            self.gen_len = int(val)
        elif name == "gen_temp":
            self.gen_temp = float(val)
        elif name == "gen_topk":
            self.gen_topk = int(val)
        elif name == "gen_topp":
            self.gen_topp = float(val)
        elif name == "gen_cache":
            self.gen_cache = int(val)
        elif name == "serve_host":
            self.serve_host = val
        elif name == "serve_port":
            self.serve_port = int(val)
        elif name == "replicas":
            self.replicas = int(val)
        elif name == "max_batch_size":
            self.serve_max_batch = int(val)
        elif name == "batch_timeout_ms":
            self.batch_timeout_ms = float(val)
        elif name == "queue_limit":
            self.queue_limit = int(val)
        elif name == "serve_reload_period":
            self.serve_reload_period = float(val)
        elif name == "serve_deadline_ms":
            self.serve_deadline_ms = float(val)
        elif name == "wire":
            # data-plane wire formats the engine accepts (the raw key
            # also reaches serve.Engine through self.cfg)
            if val not in ("binary", "json"):
                raise ValueError(
                    f"wire must be binary or json, got {val!r}")
            self.wire = val
        elif name == "drain_timeout_s":
            self.drain_timeout_s = float(val)
        elif name == "reload_breaker_threshold":
            self.reload_breaker_threshold = int(val)
        elif name == "reload_breaker_cooldown_s":
            self.reload_breaker_cooldown_s = float(val)
        elif name == "watchdog_timeout_s":
            self.watchdog_timeout_s = float(val)
        elif name == "controller":
            self.controller = int(val)
        elif name == "telemetry":
            self.telemetry = int(val)
        elif name == "telemetry_path":
            self.telemetry_path = val
        elif name == "loop_dir":
            self.loop_dir = val
        elif name == "loop_rounds_per_cycle":
            self.loop_rounds_per_cycle = int(val)
        elif name == "loop_replay_ratio":
            self.loop_replay_ratio = float(val)
        elif name == "loop_min_records":
            self.loop_min_records = int(val)
        elif name == "loop_max_records":
            self.loop_max_records = int(val)
        elif name == "loop_cycle_period_s":
            self.loop_cycle_period_s = float(val)
        elif name == "loop_max_cycles":
            self.loop_max_cycles = int(val)
        elif name == "publish_min_delta":
            self.publish_min_delta = float(val)
        elif name == "publish_metric":
            self.publish_metric = val
        elif name == "publish_slice_floor":
            self.publish_slice_floor = float(val)
        elif name == "publish_slice_min_count":
            self.publish_slice_min_count = int(val)
        elif name == "publish_source_field":
            self.publish_source_field = int(val)
        elif name == "capture_predict":
            self.capture_predict = int(val)
        elif name == "feedback_page_bytes":
            self.feedback_page_bytes = int(val)
        elif name == "feedback_rotate_bytes":
            self.feedback_rotate_bytes = int(val)
        elif name == "feedback_retain_shards":
            self.feedback_retain_shards = int(val)
        elif name == "feedback_retain_bytes":
            self.feedback_retain_bytes = int(val)
        elif name == "data_service_host":
            self.data_service_host = val
        elif name == "data_service_port":
            self.data_service_port = int(val)
        elif name == "data_service_http_port":
            self.data_service_http_port = int(val)
        elif name == "data_service_max_sessions":
            self.data_service_max_sessions = int(val)
        elif name == "data_service_cache_mb":
            self.data_service_cache_mb = float(val)
        elif name == "data_service_window":
            self.data_service_window = int(val)
        elif name == "data_service_ready_file":
            self.data_service_ready_file = val
        elif name == "quant":
            self.quant = "" if val in ("0", "off", "none") else val
        elif name == "quant_min_agreement":
            self.quant_min_agreement = float(val)
        elif name == "quant_calib_batches":
            self.quant_calib_batches = int(val)
        elif name == "quant_out":
            self.quant_out = val
        elif name == "quant_report":
            self.quant_report = val
        self.cfg.append((name, val))

    # ------------------------------------------------------------------
    def run(self, argv: List[str]) -> int:
        if len(argv) < 1:
            print("Usage: <config> [name=val ...]")
            return 0
        # the fleet supervisor re-launches this exact invocation per
        # replica (conf + overrides, fleet keys pinned) — keep the raw
        # argv around for serve.fleet.cli_spawn_fn
        self.conf_path = argv[0]
        self.cli_overrides = list(argv[1:])
        for name, val in cfgmod.parse_file(argv[0]):
            self.set_param(name, val)
        for name, val in cfgmod.parse_cli_overrides(argv[1:]):
            self.set_param(name, val)
        # join the multi-process job (if any) before any JAX backend use;
        # the distributed-PS replacement (SURVEY §2.8): bigger mesh, same
        # SPMD program, collectives over ICI/DCN
        from .parallel import maybe_init_distributed
        from .parallel.elastic import ElasticOptions

        self.elastic_opts = ElasticOptions.from_cfg(self.cfg)
        maybe_init_distributed(self.cfg)
        # arm the chaos harness (no-op without fault_inject keys); the
        # instrumented sites live in io/, utils/checkpoint.py and serve/
        from .utils import compile_cache, faults

        faults.configure(self.cfg)
        # observability (doc/observability.md): host-span tracing
        # (trace_dir/trace_steps) and the structured event log
        # (event_log*) — both default off; the metrics registry needs
        # no arming, layers write into it unconditionally
        from . import obs

        obs.configure(self.cfg)
        # persistent XLA compile cache (compile_cache_dir): enabled
        # before ANY jit of this run so every task's programs hit it
        compile_cache.configure(self.cfg, silent=bool(self.silent))
        if self.task not in ("train", "finetune", "pred", "pred_raw",
                             "extract", "generate", "summary", "serve",
                             "serve_train", "loop_fleet",
                             "export_quant", "data_service"):
            raise ValueError(f"unknown task {self.task!r}")
        if self.elastic_opts.join:
            # a rejoining process has no mesh yet: admission, backend
            # init, model load and iterators all happen inside
            # task_train (_elastic_join_setup) once the coordinator
            # grows the pod
            if self.task != "train":
                raise ValueError("elastic_join=1 only supports task=train")
        else:
            self.init()
        if not self.silent:
            print("initializing end, start working")
        if self.task == "export_quant":
            return self.task_export_quant()
        if self.task in ("train", "finetune"):
            self.task_train()
        elif self.task in ("pred", "pred_raw"):
            self.task_predict(raw=self.task == "pred_raw")
        elif self.task == "extract":
            self.task_extract()
        elif self.task == "generate":
            self.task_generate()
        elif self.task == "summary":
            self.task_summary()
        elif self.task == "serve":
            self.task_serve()
        elif self.task == "serve_train":
            self.task_serve_train()
        elif self.task == "loop_fleet":
            self.task_loop_fleet()
        elif self.task == "data_service":
            self.task_data_service()
        else:
            raise ValueError(f"unknown task {self.task!r}")
        return 0

    # ------------------------------------------------------------------
    def _create_trainer(self) -> NetTrainer:
        tr = NetTrainer()
        tr.set_params(self.cfg)
        return tr

    def init(self) -> None:
        if self.task == "serve":
            # the serving engine owns model discovery/validation and
            # needs no data iterators — see task_serve
            return
        if self.task == "data_service":
            # the server builds the conf's data chain itself (inside
            # BatchPlant) and has no model — see task_data_service
            return
        if self.task == "export_quant":
            # the exporter loads its own trainers (f32 reference +
            # candidate); the driver only supplies the held-out eval
            # iterator the agreement gate scores on
            if self.name_model_in == "NULL":
                raise ValueError(
                    "task=export_quant needs model_in (the trained f32 "
                    "checkpoint to quantize)")
            from .parallel.distributed import process_info

            if process_info()[1] > 1:
                raise ValueError("task=export_quant is single-process")
            self._create_iterators()
            return
        if self.task == "serve_train":
            # the engine owns the model; the continuous loop needs the
            # conf's data section (replay mixing) and eval section (the
            # publish gate) but no driver-level trainer
            from .parallel.distributed import process_info

            if process_info()[1] > 1:
                raise ValueError(
                    "task=serve_train is single-process (the trainer "
                    "rides beside the serving engine)")
            self._create_iterators()
            return
        if self.task == "loop_fleet":
            # every tenant builds its OWN engine + iterators from its
            # effective config (loop/tenant.py) — the driver only
            # validates the process shape here
            from .parallel.distributed import process_info

            if process_info()[1] > 1:
                raise ValueError(
                    "task=loop_fleet is single-process (N tenants "
                    "share this process's device pool)")
            return
        if self.task == "train" and self.continue_training:
            if self._sync_latest_model():
                print(f"Init: Continue training from round {self.start_counter}")
                self._create_iterators()
                return
            raise FileNotFoundError(
                "Init: cannot find models for continue training; "
                "specify model_in instead"
            )
        self.continue_training = 0
        if self.name_model_in == "NULL":
            if self.task not in ("train", "summary"):
                raise ValueError("must specify model_in if not training")
            self.net_trainer = self._create_trainer()
            self.net_trainer.init_model()
        elif self.task == "finetune":
            self.net_trainer = self._create_trainer()
            self.net_trainer.copy_model_from(self.name_model_in)
        else:
            self._load_model()
        self._create_iterators()

    def _net_fingerprint(self) -> Optional[str]:
        """Fingerprint of the conf's netconfig (manifest cross-check on
        resume); None when the conf has no parseable netconfig."""
        from .nnet.graph import NetGraph
        from .utils import checkpoint as ckpt

        try:
            g = NetGraph()
            g.configure(self.cfg)
            return ckpt.net_fingerprint(g.structure_to_json())
        except Exception:
            return None

    def _locate_agreed_checkpoint(self, before=None):
        """THE distributed resume/rollback discovery protocol — one copy
        so every caller issues the identical collective sequence (a
        divergent copy would deadlock multi-process runs).

        Collective: finds the newest locally-valid checkpoint, agrees on
        the newest round EVERY process holds (``agree_on_round``),
        validates the agreed round when it is older than the local
        newest (``find_latest_valid`` only vouched for the newest —
        consensus must not launder a corrupt/pruned file past the
        integrity checks), and agrees on the usable/unusable verdict
        (``any_process_flag`` — a lone local abort would strand the
        peers at their next collective).

        Returns ``(round_, path, reason)``: ``round_ == -1`` when no
        process has any valid checkpoint; ``reason`` is not None (or
        path unusable on a peer, reason None with path set) when the
        agreed round failed validation somewhere — the caller decides
        raise vs bail."""
        from .parallel.distributed import agree_on_round, any_process_flag
        from .utils import checkpoint as ckpt

        net_fp = self._net_fingerprint()
        found = ckpt.find_latest_valid(
            self.name_model_dir, net_fp=net_fp, silent=bool(self.silent),
            before=before,
        )
        local_round = found[0] if found else -1
        round_ = agree_on_round(local_round)
        if round_ < 0:
            return -1, None, None
        if round_ == local_round:
            path, reason = found[1], None
        else:
            if not self.silent:
                print(f"resume: agreed on round {round_} across processes "
                      f"(local newest was {local_round})")
            path = os.path.join(self.name_model_dir, f"{round_:04d}.model")
            reason = ckpt.validate_checkpoint(path, net_fp=net_fp)
        if any_process_flag(reason is not None):
            return round_, path, reason or "unusable on a peer process"
        return round_, path, None

    def _load_trainer(self, path: str) -> NetTrainer:
        """Fresh trainer with ``path`` loaded, retrying transient I/O
        under the unified :class:`RetryPolicy` (``retry_*`` config keys
        — the same policy the serving engine uses)."""
        from .utils.faults import RetryPolicy

        tr = self._create_trainer()
        RetryPolicy.from_cfg(self.cfg).run(
            lambda: tr.load_model(path),
            what=f"loading {path}", silent=bool(self.silent))
        return tr

    def _sync_latest_model(self) -> bool:
        """Resume from the newest VALID checkpoint in ``model_dir``.

        Globs all ``NNNN.model`` files (the old consecutive scan stopped
        at the first gap, so ``save_model > 1`` or ``keep_latest``
        pruning made resume find nothing), validates each against its
        manifest (CRC32 + size + net fingerprint), and falls back past
        corrupt/truncated ones — a kill mid-write never bricks resume.
        Multi-process runs agree on the newest round EVERY process can
        see before anyone loads.

        An integrity quarantine sets ``_integrity_rollback_before``
        (exclusive bound): the newest checkpoints may carry state the
        corrupt rank's gradients already poisoned, so survivors must
        resume from the last FINGERPRINT-VERIFIED round, not the newest
        round on disk — the poisoned rounds are re-trained and their
        checkpoints overwritten."""
        from .utils import checkpoint as ckpt

        bound, self._integrity_rollback_before = (
            self._integrity_rollback_before, None)
        round_, path, reason = self._locate_agreed_checkpoint(before=bound)
        if round_ < 0:
            return False
        if reason is not None:
            raise ckpt.CheckpointError(
                f"resume: processes agreed on round {round_} but "
                f"{path} is unusable: {reason}"
            )
        self.net_trainer = self._load_trainer(path)
        self.start_counter = round_ + 1
        from .obs import emit as obs_emit

        obs_emit("checkpoint.restore", round=round_, path=path)
        return True

    def _load_model(self) -> None:
        base = os.path.basename(self.name_model_in)
        stem = base.split(".")[0]
        if stem.isdigit():
            self.start_counter = int(stem)
        else:
            print(
                "WARNING: cannot infer start_counter from model name; "
                "set it in the config if needed"
            )
        self.net_trainer = self._create_trainer()
        self.net_trainer.load_model(self.name_model_in)
        self.start_counter += 1

    def _probe_block(self) -> Optional[dict]:
        """The golden-canary ``probe`` manifest block
        (``integrity_probe = 1``, doc/robustness.md "Integrity plane"):
        the deterministic probe-batch spec, plus — on single-process
        runs — the CRC of this trainer's scores for it.  Multi-process
        runs commit the spec only (scoring is a different SPMD program
        per mesh; the engine records its own golden at load)."""
        if not self.integrity_probe or self.net_trainer is None:
            return None
        import jax

        from .integrity import canary

        tr = self.net_trainer
        rows = max(1, min(int(tr.batch_size) or 8, 8))
        shape = tuple(tr.net.input_node_shape(tr.batch_size))[1:]
        seed = 0xC0FFEE ^ int(tr.seed or 0)
        crc = None
        if jax.process_count() == 1 and not tr.quant_scheme:
            probe = canary.probe_batch(seed, rows, shape)
            scores = tr._run_sharded(tr._eval_fn(), probe)
            crc = canary.scores_crc(scores)
        return canary.make_probe_block(seed, rows, shape, crc,
                                       jax.default_backend())

    def _save_model(self, force: bool = False) -> bool:
        """Checkpoint the current state as ``NNNN.model`` + manifest.

        Fault-tolerant write discipline: serialize (COLLECTIVE — every
        process assembles sharded state), then rank 0 alone writes
        atomically with retry/backoff, applies ``keep_latest`` retention,
        and everyone re-synchronizes at a barrier so no process reads a
        checkpoint before it is durable.  ``force=True`` (preemption
        snapshot) bypasses the ``save_model`` period gate — though
        ``save_model = 0`` (checkpointing disabled) stays disabled.
        Returns True when a checkpoint was written."""
        from .parallel.distributed import (
            any_process_flag, barrier, is_primary, process_info,
        )
        from .utils import checkpoint as ckpt

        round_ = self.start_counter
        path = os.path.join(self.name_model_dir, f"{round_:04d}.model")
        self.start_counter += 1
        if self.save_period == 0 or (
                not force and self.start_counter % self.save_period != 0):
            return False
        blob = self.net_trainer.checkpoint_bytes()
        probe = self._probe_block()
        err = None
        if is_primary():
            try:
                os.makedirs(self.name_model_dir, exist_ok=True)
                ckpt.write_checkpoint(
                    path, blob, round_=round_,
                    net_fp=self.net_trainer.net_fp(),
                    save_ustate=self.net_trainer.save_ustate,
                    retry=True, silent=bool(self.silent),
                    mesh=self.net_trainer.mesh_manifest(),
                    probe=probe,
                )
                if self.keep_latest > 0:
                    ckpt.apply_retention(
                        self.name_model_dir, self.keep_latest,
                        silent=bool(self.silent),
                    )
            except Exception as exc:  # noqa: BLE001 - relayed collectively
                err = exc
        if process_info()[1] > 1:
            # success/failure must be exchanged BEFORE the barrier — a
            # raise on rank 0 alone would strand the other ranks in it
            if any_process_flag(err is not None):
                if err is not None:
                    raise err
                raise ckpt.CheckpointError(
                    f"checkpoint {path} failed to write on the primary "
                    "process"
                )
            barrier("ckpt_save")
        elif err is not None:
            raise err
        return True

    def _create_iterators(self) -> None:
        split = cfgmod.split_sections(self.cfg)
        for sec in split.sections:
            if sec.kind == "data" and self.task not in ("pred", "pred_raw",
                                                        "generate",
                                                        "summary",
                                                        "export_quant"):
                if self.itr_train is not None:
                    raise ValueError("can only have one data section")
                self.itr_train = create_iterator(sec.entries)
            elif sec.kind == "eval" and self.task not in ("pred", "pred_raw",
                                                          "generate",
                                                          "summary"):
                self.itr_evals.append(create_iterator(sec.entries))
                self.eval_names.append(sec.tag)
            elif sec.kind == "pred":
                self.name_pred = sec.tag
                if self.task in ("pred", "pred_raw", "extract"):
                    if self.itr_pred is not None:
                        raise ValueError("can only have one pred section")
                    self.itr_pred = create_iterator(sec.entries)
        from .parallel.distributed import process_info

        pid, nproc = process_info()
        for it in [self.itr_train, self.itr_pred, *self.itr_evals]:
            if it is not None:
                for n, v in split.global_entries:
                    it.set_param(n, v)
                if nproc > 1 and (it is self.itr_train
                                  or it in self.itr_evals):
                    # multi-process contract (trainer._pad_train_batch):
                    # each process feeds batch_size/nproc LOCAL rows of
                    # its own data shard; batch_size in the conf is
                    # GLOBAL.  Shard + shrink the iterator here so dist
                    # confs run unchanged on any process count.  Eval
                    # iterators shard too (cross-process metric reduction
                    # reassembles the global number — trainer.evaluate);
                    # an eval chain that can't shard still works, every
                    # process just scores the full set redundantly.
                    gbs = self.net_trainer.batch_size
                    if gbs % nproc != 0:
                        raise ValueError(
                            f"batch_size={gbs} must divide by the "
                            f"process count ({nproc})"
                        )
                    if not it.supports_dist_shard():
                        if it is self.itr_train:
                            raise ValueError(
                                "multi-process training needs a train "
                                "iterator that honors dist_num_worker "
                                "(mnist/imgbin/img/csv/synthetic); this "
                                "chain would silently feed every process "
                                "identical data"
                            )
                    else:
                        it.set_param("batch_size", str(gbs // nproc))
                        it.set_param("dist_num_worker", str(nproc))
                        it.set_param("dist_worker_rank", str(pid))
                it.init()

    # ------------------------------------------------------------------
    # self-tuning controller (cxxnet_tpu/tune/): ``controller = 1``
    # arms a background KnobController for the task's live knobs
    def _start_controller(self, knobs, objective, on_tick=None,
                          name="tune"):
        from .tune import KnobController, options_from_cfg

        opts = options_from_cfg(self.cfg)
        ctrl = KnobController(
            objective, knobs,
            period_s=opts.period_s, band=opts.band,
            measure_ticks=opts.measure_ticks,
            settle_ticks=opts.settle_ticks,
            cooldown_ticks=opts.cooldown_ticks,
            name=name, on_tick=on_tick,
        )
        ctrl.start()
        if not self.silent:
            print(f"controller: tuning {[k.name for k in knobs]} "
                  f"every {opts.period_s:g}s (band {opts.band:g})",
                  flush=True)
        return ctrl

    def _start_train_controller(self):
        """``controller = 1`` for train tasks: tune the decode pool
        (workers + in-flight window) against the rate of rows the train
        loop actually dispatches.  None when the conf did not opt in or
        the chain has no parallel decode stage."""
        if not self.controller or self.itr_train is None:
            return None
        from .tune import find_pipeline, options_from_cfg, pipeline_knobs

        opts = options_from_cfg(self.cfg)
        knobs = []
        if opts.wants("pipeline"):
            pipe = find_pipeline(self.itr_train)
            if pipe is not None:
                knobs.extend(pipeline_knobs(pipe))
        if not knobs:
            if not self.silent:
                print("controller=1: no tunable pipeline stage in this "
                      "iterator chain; controller idle", flush=True)
            return None
        bs = float(self.net_trainer.batch_size or 1)
        return self._start_controller(
            knobs,
            objective=lambda: float(getattr(self, "_global_step", 0)) * bs,
            name="train",
        )

    def _start_serve_controller(self, engine):
        """``controller = 1`` for serve tasks: tune the micro-batcher
        (coalescing limit + batch window) against executed batch rows,
        with the speculative bucket prewarm riding every tick."""
        if not self.controller:
            return None
        from .tune import batcher_knobs, options_from_cfg

        opts = options_from_cfg(self.cfg)
        knobs = batcher_knobs(engine) if opts.wants("batcher") else []
        if not knobs:
            return None
        return self._start_controller(
            knobs,
            objective=lambda: float(engine.stats.batch_rows),
            on_tick=engine.prewarm_buckets,
            name="serve",
        )

    def _print_mesh_summary(self) -> None:
        """One line of SPMD layout truth at train start: mesh shape,
        ZeRO level, and the measured per-device train-state bytes vs
        the replicated footprint — the memory headroom the sharded
        weight update bought, stated where an operator reads logs
        (the same numbers live as ``train_state_shard_bytes{device}``
        in ``/metricsz``)."""
        tr = self.net_trainer
        if self.silent or tr is None or tr.mesh_plan is None:
            return
        plan = tr.mesh_plan
        if plan.n_devices <= 1:
            return
        try:
            per_device, total = tr.state_shard_bytes()
            worst = max(per_device.values()) if per_device else total
        except Exception:  # noqa: BLE001 - a log line must never abort
            return
        print(
            f"mesh: {plan.describe(zero=tr.zero)}"
            f" | train state {total / 1e6:.2f} MB replicated -> "
            f"{worst / 1e6:.2f} MB/device "
            f"({worst / total if total else 1:.2%} of a full copy)",
            flush=True,
        )

    # ------------------------------------------------------------------
    # elastic pod (doc/parallel.md "Elastic pod"): survive replica loss
    # and resize the mesh mid-run, inside ONE CLI invocation
    def _elastic_setup(self) -> None:
        """Arm the peer-liveness layer: rank 0 hosts the membership
        coordinator; every rank heartbeats it.  No-op unless
        ``elastic = 1`` on a real multi-process job."""
        opts = self.elastic_opts
        if not opts.elastic:
            return
        from .parallel import elastic as par_elastic
        from .parallel.distributed import distributed_spec, process_info

        spec = distributed_spec(self.cfg)
        if spec is None or process_info()[1] == 1:
            return  # single process: nothing to monitor
        coord, num, pid = spec
        addr = opts.resolve_coordinator(coord)
        self.elastic_member = par_elastic.ElasticMember(
            addr, pid, opts, host_coordinator=(pid == 0), num=num,
            jax_host=coord.rsplit(":", 1)[0],
        ).start()
        if not self.silent:
            print(f"elastic: liveness monitor armed (coordinator "
                  f"{self.elastic_member.addr}, heartbeat "
                  f"{opts.heartbeat_s:g}s, replica timeout "
                  f"{opts.timeout_s:g}s, collective deadline "
                  f"{opts.collective_timeout_s:g}s)", flush=True)

    def _elastic_join_setup(self) -> None:
        """``elastic_join = 1``: this process has NO mesh yet.  Announce
        to the coordinator, wait for a grow generation to assign a rank
        (``elastic_rejoin_s`` bounds the wait), join the re-init
        rendezvous, load the consensus checkpoint and shard the
        iterators — then fall straight into the round loop beside the
        survivors."""
        opts = self.elastic_opts
        from .parallel import elastic as par_elastic
        from .parallel.distributed import init_distributed

        if not opts.coordinator:
            raise ValueError(
                "elastic_join=1 needs elastic_coordinator=host:port "
                "(the running job's membership coordinator)")
        m = par_elastic.ElasticMember(opts.coordinator, -1, opts)
        print(f"elastic: waiting to join the mesh via {opts.coordinator}"
              f" (up to {opts.rejoin_s:g}s"
              + (f", at round {opts.join_at}" if opts.join_at else "")
              + ")", flush=True)
        plan = m.join()
        if plan.rank is None:
            raise RuntimeError("elastic join: admitted without a rank")
        print(f"elastic: admitted as rank {plan.rank}/{plan.num} "
              f"(generation {plan.generation})", flush=True)
        self._set_cfg_entries({
            "dist_coordinator": plan.jax_coordinator,
            "dist_num_proc": str(plan.num),
            "dist_proc_id": str(plan.rank),
        })
        # heartbeat BEFORE the blocking rendezvous: the coordinator
        # registered this member at plan time, and a slow survivor
        # teardown must not get the joiner evicted mid-admission
        m.rank = plan.rank
        m.generation = plan.generation
        m.start()
        init_distributed(plan.jax_coordinator, plan.num, plan.rank,
                         resilient=True)
        m.ack_generation(plan, rank=plan.rank)
        self.elastic_member = m
        self.continue_training = 1
        if not self._sync_latest_model():
            raise FileNotFoundError(
                "elastic join: no checkpoint in model_dir to sync from")
        self._create_iterators()
        self._elastic_joined = True

    def _elastic_guard(self, fn, what: str):
        """Collective deadline: a dead peer surfaces as
        ``ReplicaLossError`` within ``collective_timeout_s`` instead of
        hanging this rank inside a collective forever."""
        if self.elastic_member is None:
            return fn()
        from .parallel import elastic as par_elastic

        return par_elastic.guarded_call(
            fn, self.elastic_member,
            timeout_s=self.elastic_opts.collective_timeout_s, what=what)

    def _elastic_recover(self, exc: BaseException) -> bool:
        """Classify a round/checkpoint failure: replica loss → rebuild
        onto the survivors and return True (the caller re-enters the
        loop); anything else → False (the error propagates)."""
        if self.elastic_member is None:
            return False
        from .parallel import elastic as par_elastic

        loss = par_elastic.classify_failure(
            exc, self.elastic_member,
            confirm_s=self.elastic_opts.timeout_s + 2.0)
        if loss is None:
            return False
        min_gen = 0
        while True:
            if loss.fatal:
                print(f"elastic: unrecoverable replica loss: {loss}",
                      flush=True)
                return False
            # a persistent NON-replica error misclassified as loss
            # would otherwise rebuild forever (the caller refunds the
            # round budget) — bound consecutive recoveries; a
            # completed round resets the counter
            self._elastic_consec_recoveries += 1
            if self._elastic_consec_recoveries > 5:
                print("elastic: giving up after "
                      f"{self._elastic_consec_recoveries - 1} "
                      "consecutive rebuilds without completing a "
                      "round", flush=True)
                return False
            print(f"elastic: replica loss detected ({loss})", flush=True)
            try:
                self._elastic_rebuild("replica_lost",
                                      min_generation=min_gen)
                return True
            except par_elastic.ReplicaLossError as again:
                # a SECOND replica died during the rebuild's own
                # collectives: wait for the next generation and retry
                # (survivors above quorum must not give up)
                loss = again
            except Exception as again:  # noqa: BLE001 - classify
                loss = par_elastic.classify_failure(
                    again, self.elastic_member,
                    confirm_s=self.elastic_opts.timeout_s + 2.0)
                if loss is None:
                    raise
            min_gen = self._elastic_attempted_gen + 1

    def _elastic_boundary(self) -> bool:
        """Planned mesh transitions at the round boundary (the
        consensus checkpoint for this boundary is already durable).
        Returns True when the mesh changed (rebuilt, or this rank
        left)."""
        m = self.elastic_member
        opts = self.elastic_opts
        m.poll_now()  # synchronous beat: every rank reads the same state
        plan = m.pending_plan()
        if plan is not None and plan.at_round is None:
            # a replica died while this rank sat at the boundary (its
            # own collectives all completed) — rebuild without waiting
            # to trip over the corpse inside the next round
            print(f"elastic: replica loss at round boundary "
                  f"(generation {plan.generation})", flush=True)
            self._elastic_rebuild("replica_lost", plan=plan)
            return True
        r = self.start_counter
        if (opts.drop_at and r >= opts.drop_at
                and not self._elastic_drop_done):
            # >= + latch: a boundary whose RPC failed retries at the
            # next round instead of silently skipping the drop forever
            plan = m.plan_shrink(r)
            self._elastic_drop_done = True
            if plan.rank is None:
                self._elastic_left = True
                return True
            self._elastic_rebuild(plan.reason, plan=plan)
            return True
        g = m.grow_round()
        if g is not None and r >= g:
            plan = m.plan_grow(r)
            if plan is None:
                return False  # every waiter abandoned the join
            self._elastic_rebuild("grow", plan=plan)
            return True
        return False

    def _await_plan(self, min_generation: int = 0):
        """Block (briefly) until the coordinator's generation plan for a
        detected loss arrives over the heartbeat channel.
        ``min_generation`` skips a stale plan from a rebuild attempt
        that itself died (a second loss mid-rebuild)."""
        import time as _time

        m = self.elastic_member
        deadline = _time.monotonic() + self.elastic_opts.timeout_s * 2 + 5
        while _time.monotonic() < deadline:
            p = m.pending_plan()
            if p is not None and p.generation >= min_generation:
                return p
            try:
                m.poll_now()
            except (OSError, ValueError, RuntimeError):
                pass
            _time.sleep(0.1)
        return None

    def _set_cfg_entries(self, updates: dict) -> None:
        """Rewrite config entries in place (the rebuilt generation's
        dist_* identity) so anything re-reading the cfg stream agrees
        with the live mesh."""
        out, seen = [], set()
        for n, v in self.cfg:
            if n in updates:
                if n in seen:
                    continue  # collapse duplicates to one entry
                out.append((n, updates[n]))
                seen.add(n)
            else:
                out.append((n, v))
        for n, v in updates.items():
            if n not in seen:
                out.append((n, v))
        self.cfg = out

    def _elastic_rebuild(self, reason: str, plan=None,
                         min_generation: int = 0) -> None:
        """Checkpoint-consensus rebuild onto the new process set, inside
        this CLI invocation: tear down the distributed backend, re-init
        on the plan's fresh coordinator, re-load the agreed round via
        the PR-1 consensus machinery (state re-places onto the CURRENT
        mesh through the eager ``_place_state`` + PR-9 cross-mesh
        reshard), and re-shard the iterators; ``start_counter`` rewinds
        to the consensus round + 1, and the deterministic augmentation
        stream (``RecordRNG`` + ``dist_shard = block``) makes the
        resumed stream exact."""
        import gc

        from .obs import emit as obs_emit
        from .parallel import elastic as par_elastic
        from .parallel.distributed import (
            init_distributed, shutdown_distributed,
        )
        from .utils import checkpoint as ckpt

        m = self.elastic_member
        t0 = time.time()
        par_elastic.set_rebuilding(True)
        try:
            plan = plan or self._await_plan(min_generation)
            if plan is None:
                raise par_elastic.ReplicaLossError(
                    "elastic: replica loss with no generation plan "
                    "(membership coordinator unreachable?)", fatal=True)
            if plan.abort:
                raise par_elastic.ReplicaLossError(
                    f"elastic: cannot continue: {plan.abort}", fatal=True)
            self._elastic_attempted_gen = plan.generation
            obs_emit("mesh.rebuild_start", reason=reason,
                     generation=plan.generation, num=plan.num,
                     rank=plan.rank, round=self.start_counter)
            print(f"elastic: {reason} -> rebuilding the mesh as "
                  f"{plan.num} process(es), this rank becomes "
                  f"{plan.rank} (generation {plan.generation})",
                  flush=True)
            # a guarded worker may still be wedged in a dead collective:
            # give it a grace to error out before the backend dies under it
            t = par_elastic.guarded_call.last_thread
            if t is not None and t.is_alive():
                t.join(timeout=min(
                    self.elastic_opts.collective_timeout_s, 10.0))
                if t.is_alive():
                    obs_emit("mesh.guard_thread_abandoned", what=reason)
            # drop every reference into the old backend before it dies
            for it in [self.itr_train, *self.itr_evals]:
                if it is not None:
                    try:
                        it.close()
                    except Exception:  # noqa: BLE001 - teardown
                        pass
            self.itr_train = None
            self.itr_evals = []
            self.eval_names = []
            if self.net_trainer is not None:
                # async data-parallel: in-flight aggregates were reduced
                # by the DEAD generation's collectives — generation-stamp
                # them out so nothing stale can ever be applied (the
                # rebuilt trainer reloads a drained checkpoint anyway;
                # this guards the window until it does, and the event
                # makes the discard auditable)
                try:
                    self.net_trainer.async_abandon(
                        generation=plan.generation, reason="rebuild")
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            self.net_trainer = None
            gc.collect()
            # zero-RPC teardown: a shutdown barrier can never complete
            # with a dead peer, and its failure broadcast would kill
            # the surviving clients — abandon, don't negotiate
            shutdown_distributed(graceful=False)
            self._set_cfg_entries({
                "dist_coordinator": plan.jax_coordinator,
                "dist_num_proc": str(plan.num),
                "dist_proc_id": str(plan.rank),
            })
            # the rebuild rendezvous: initialize blocks until every
            # member of the new generation connects
            init_distributed(plan.jax_coordinator, plan.num, plan.rank,
                             resilient=True)
            m.ack_generation(plan, rank=plan.rank)
            # round consensus + reload on the NEW process set; the
            # iterator re-shards for the new rank/process count
            if not self._sync_latest_model():
                raise ckpt.CheckpointError(
                    "elastic: no valid checkpoint any survivor can "
                    "load — cannot rebuild")
            self._create_iterators()
            dt = time.time() - t0
            self._elastic_rebuilds += 1
            self._elastic_last_rebuild_s = dt
            try:
                from .obs.registry import registry as obs_registry

                obs_registry().counter(
                    "mesh_rebuilds_total",
                    "Elastic mesh rebuilds by trigger.",
                    labelnames=("reason",),
                ).labels(reason=reason).inc()
                obs_registry().gauge(
                    "mesh_rebuild_seconds",
                    "Wall time of the last elastic mesh rebuild.",
                ).set(dt)
            except Exception:  # noqa: BLE001 - telemetry never aborts
                pass
            obs_emit("mesh.rebuild_done", reason=reason,
                     generation=plan.generation, num=plan.num,
                     rank=plan.rank, wall_s=round(dt, 3),
                     resume_round=self.start_counter)
            self._print_mesh_summary()
            print(f"elastic: rebuilt in {dt:.2f}s; resuming at round "
                  f"{self.start_counter} on {plan.num} process(es)",
                  flush=True)
        finally:
            par_elastic.set_rebuilding(False)

    def _elastic_quiet_teardown(self) -> None:
        """End-of-task teardown for elastic runs: drop the resilient
        coordination client BEFORE interpreter exit destructs the
        in-process coordination service — a client that outlives its
        service sees the socket close as a fatal error and aborts the
        process.  Zero-RPC (graceful=False); rank 0 lingers briefly so
        every peer's client is gone before its exit closes the live
        service's socket."""
        from .parallel.distributed import (
            distributed_initialized, shutdown_distributed,
        )

        m, self.elastic_member = self.elastic_member, None
        if m is None:
            return
        try:
            m.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        if distributed_initialized():
            shutdown_distributed(graceful=False)
        if m.coordinator is not None:  # this process is rank 0
            time.sleep(2.0)

    def task_train(self) -> None:
        from .integrity.plane import IntegrityError
        from .parallel.distributed import any_process_flag, process_info
        from .utils.checkpoint import DivergenceError, PreemptionHandler

        self._train_start = time.time()
        if self.elastic_opts is None:  # task_train without run()
            from .parallel.elastic import ElasticOptions

            self.elastic_opts = ElasticOptions.from_cfg(self.cfg)
        if self.elastic_opts.join:
            self._elastic_join_setup()
        else:
            self._elastic_setup()
        if self._elastic_joined:
            # a rejoined process enters the round loop directly: the
            # survivors are already mid-loop, so any extra collective
            # here (initial eval / checkpoint) would deadlock the mesh
            pass
        elif self.continue_training == 0 and self.name_model_in == "NULL":
            self._elastic_guard(self._save_model, what="initial checkpoint")
        else:
            for it, nm in zip(self.itr_evals, self.eval_names):
                sys.stderr.write(self.net_trainer.evaluate(it, nm))
            sys.stderr.write("\n")
            sys.stderr.flush()
        if self.itr_train is None:
            return
        if self.test_io:
            print("start I/O test")
        from .obs import emit as obs_emit
        from .obs import trace as obs_trace
        from .utils.profiler import StepTimer, TraceController

        timer = StepTimer()
        tracer = TraceController()
        tracer.configure(self.cfg)
        self._print_mesh_summary()
        obs_emit("train.start", task=self.task, round=self.start_counter,
                 num_round=self.num_round)
        self._global_step = 0
        self._divergence_retries = 0
        self._lr_scale = 1.0
        # integrity plane (doc/robustness.md "Integrity plane"): one
        # driver per task, surviving trainer rebuilds; the state loaded
        # or initialized before the loop is taken as the clean baseline
        if self.integrity_every > 0 and self._integrity is None:
            from .integrity import IntegrityPlane

            self._integrity = IntegrityPlane(
                self.integrity_every, self.integrity_shadow)
            self._integrity.last_clean_round = self.start_counter - 1
        # SIGTERM/SIGINT → finish the current step, snapshot, exit clean.
        # Single-process runs stop at the next BATCH boundary; multi-
        # process runs stop at the next ROUND boundary (the per-batch
        # check would need a per-batch collective to keep the SPMD
        # programs aligned) — the flag is agreed across processes so one
        # preempted worker stops the whole job consistently.
        self._preempt = PreemptionHandler().install()
        preempted = False
        tuner = self._start_train_controller()
        try:
            cc = self.max_round
            while self.start_counter <= self.num_round and cc > 0:
                cc -= 1
                if self.elastic_member is not None:
                    self.elastic_member.report_round(self.start_counter)
                try:
                    with obs_trace.span("train.round",
                                        round=self.start_counter):
                        completed = self._elastic_guard(
                            lambda: self._train_one_round(timer, tracer),
                            what=f"train round {self.start_counter}",
                        )
                except DivergenceError as e:
                    if self._handle_divergence(e):
                        cc += 1  # the aborted attempt keeps its budget
                        continue
                    tracer.close()
                    raise
                except Exception as e:  # noqa: BLE001 - replica loss?
                    if self._elastic_recover(e):
                        cc += 1  # the aborted round is re-run
                        continue
                    raise
                self._divergence_retries = 0
                self._elastic_consec_recoveries = 0  # a round completed
                if not completed:  # preempted mid-round (single-process)
                    snapshotted = self._save_model(force=True)
                    preempted = True
                    break
                # integrity plane: fingerprint vote (+ shadow audit) at
                # the round boundary, BEFORE the consensus checkpoint —
                # state that failed the vote is never made durable, so
                # with integrity_every=1 no poisoned round is ever
                # resumable
                if (self._integrity is not None
                        and self._integrity.due(self.start_counter - 1)):
                    try:
                        self._elastic_guard(
                            lambda: self._integrity.check_round(
                                self.net_trainer,
                                self.start_counter - 1),
                            what="integrity check")
                    except IntegrityError as e:
                        if self._handle_integrity(e):
                            cc += 1  # the re-run rounds keep the budget
                            continue
                        tracer.close()
                        raise
                    except Exception as e:  # noqa: BLE001 - replica loss?
                        if self._elastic_recover(e):
                            continue  # the round completed; only re-sync
                        raise
                # boundary preemption check (collective in multi-process
                # runs): force the snapshot past the save_model period
                # gate so the preempted state is never lost
                try:
                    stop = (
                        self._preempt.requested
                        if process_info()[1] == 1
                        else self._elastic_guard(
                            lambda: any_process_flag(
                                self._preempt.requested),
                            what="preemption sync"))
                    snapshotted = self._elastic_guard(
                        lambda: self._save_model(force=stop),
                        what="checkpoint save")
                except Exception as e:  # noqa: BLE001 - replica loss?
                    if self._elastic_recover(e):
                        continue  # the round completed; only re-sync
                    raise
                if stop:
                    preempted = True
                    break
                # planned mesh transitions land at the round boundary,
                # AFTER the consensus checkpoint is durable.  A
                # transient coordinator RPC failure must not kill a
                # survivor — skip this boundary and retry at the next
                # (the drop/grow latches re-fire until handled)
                if (self.elastic_member is not None
                        and self.start_counter <= self.num_round):
                    try:
                        changed = self._elastic_boundary()
                    except (OSError, ValueError, RuntimeError) as e:
                        obs_emit("mesh.boundary_rpc_failed",
                                 round=self.start_counter, error=str(e))
                        changed = False
                    if changed:
                        if self._elastic_left:
                            break  # this rank left (planned shrink)
                        continue  # rebuilt onto the new mesh
        finally:
            if tuner is not None:
                tuner.stop()
            self._preempt.uninstall()
            if self.elastic_member is not None:
                self._elastic_quiet_teardown()
        tracer.close()
        obs_trace.tracer().flush_window(self._global_step)
        if self._elastic_left:
            obs_emit("mesh.left", round=self.start_counter)
            print(
                f"elastic: this rank left the mesh at round "
                f"{self.start_counter} (planned shrink); exiting clean",
                flush=True,
            )
            return
        if preempted:
            last = self.start_counter - 1
            obs_emit("train.preempted", round=last,
                     snapshotted=snapshotted)
            if snapshotted:
                print(
                    f"preemption: state saved through round {last} "
                    f"({last:04d}.model); resume with continue=1",
                    flush=True,
                )
            else:
                print("preemption: exiting (checkpointing disabled, "
                      "save_model=0)", flush=True)
            return
        obs_emit("train.end", rounds=self.start_counter - 1,
                 elapsed_s=time.time() - self._train_start)
        if not self.silent:
            print(f"\nupdating end, "
                  f"{int(time.time() - self._train_start)} sec in all")

    def _handle_divergence(self, e) -> bool:
        """Respond to a non-finite loss per ``divergence_policy``.

        ``rollback``: reload the newest valid checkpoint, optionally back
        off the learning rate (``divergence_lr_backoff``), and retry —
        up to ``divergence_max_retries`` consecutive failures.  Returns
        True when training should continue; False aborts (the default
        ``abort`` policy: stop rather than train on corrupt weights)."""
        from .obs import emit as obs_emit

        obs_emit("divergence.trip", error=str(e),
                 policy=self.divergence_policy or "abort",
                 retries=self._divergence_retries)
        print(f"DIVERGENCE: {e}", flush=True)
        if self.divergence_policy != "rollback":
            return False
        if self._divergence_retries >= self.divergence_max_retries:
            print(
                f"divergence: giving up after "
                f"{self._divergence_retries} consecutive rollbacks",
                flush=True,
            )
            return False
        # the injected fault (fault-injection harness) is one-shot: drop
        # it from the cfg so the rebuilt trainer doesn't re-arm it
        self.cfg = [(n, v) for n, v in self.cfg
                    if n not in ("inject_nan_step", "inject_spike_step")]
        bound = None  # exclusive upper round bound while falling back
        while True:
            round_, path, reason = self._locate_agreed_checkpoint(
                before=bound)
            if round_ < 0:
                print("divergence: no valid checkpoint to roll back to",
                      flush=True)
                return False
            if reason is not None:
                print(f"divergence: agreed rollback target round {round_} "
                      f"is unusable: {reason}", flush=True)
                return False
            tr = self._load_trainer(path)
            if tr.weights_finite():  # collective — identical verdict
                break
            # CRC-valid but numerically poisoned: the blow-up happened in
            # the LAST update of the round this checkpoint captured (its
            # losses were measured pre-update, all finite) — exclude it
            # and fall back further
            print(f"divergence: checkpoint {path} carries non-finite "
                  "weights; falling back past it", flush=True)
            bound = round_
        self._divergence_retries += 1
        if self.divergence_lr_backoff != 1.0:
            self._lr_scale *= self.divergence_lr_backoff
            tr.scale_learning_rate(self._lr_scale)
        if self.net_trainer is not None:
            # async data-parallel: the discarded trainer may hold
            # pending staleness aggregates — count + event-log the
            # discard (same auditability as the elastic-rebuild path)
            # so the staleness gauges don't misreport dead work
            try:
                self.net_trainer.async_abandon(reason="rollback")
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        self.net_trainer = tr
        self.start_counter = round_ + 1
        obs_emit("divergence.rollback", round=round_, path=path,
                 lr_scale=self._lr_scale,
                 retry=self._divergence_retries)
        print(
            f"divergence: rolled back to round {round_} ({path}), "
            f"lr scale now {self._lr_scale:g} "
            f"(retry {self._divergence_retries}/"
            f"{self.divergence_max_retries})",
            flush=True,
        )
        return True

    def _handle_integrity(self, e) -> bool:
        """Quarantine response to an integrity verdict
        (doc/robustness.md "Integrity plane").  The vote ran on the
        full allgathered digest matrix, so every rank holds the
        IDENTICAL verdict without another collective: the corrupt rank
        self-quarantines (``integrity.quarantine`` event, hard exit 41
        — it must never contribute another gradient), the survivors
        evict it through the elastic coordinator (idempotent per
        (rank, round) verdict) and rebuild onto the last
        fingerprint-VERIFIED round — state the corrupt rank's
        gradients touched after the flip is discarded with it.
        Returns True when this surviving rank rebuilt and the round
        loop should continue; False aborts the run (no elastic mesh
        to quarantine within, or no rank was named)."""
        from .obs import emit as obs_emit
        from .parallel.distributed import process_info

        rank, num = process_info()
        round_ = self.start_counter - 1
        print(f"INTEGRITY: {e}", flush=True)
        if e.rank is None or num == 1 or self.elastic_member is None:
            # ambiguous vote (2-way tie / 2-replica group), a
            # single-process run, or no elastic membership: there is
            # no healthy majority to rebuild onto — stopping beats
            # training on silently corrupt state
            return False
        last_clean = self._integrity.last_clean_round
        obs_emit("integrity.quarantine", kind=e.kind, rank=e.rank,
                 tensor=e.tensor, round=round_,
                 last_clean_round=last_clean, self_evict=e.rank == rank)
        if e.rank == rank:
            # self-quarantine: leave the coordination plane quietly and
            # hard-exit with the distinct quarantine code (41) — the
            # supervisor must not relaunch onto the same device, and a
            # plain exit would let resilient-client destructors abort
            # with a misleading status (_hard_exit_if_resilient)
            print(f"integrity: this rank ({rank}) was named corrupt — "
                  "self-quarantining (exit 41)", flush=True)
            self._elastic_quiet_teardown()
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(41)
        # survivor: rebuild rolls back PAST every unverified round —
        # _sync_latest_model consumes the bound (exclusive)
        self._integrity_rollback_before = (
            None if last_clean is None else last_clean + 1)
        try:
            plan = self.elastic_member.plan_evict(e.rank, round_)
        except (OSError, ValueError, RuntimeError) as err:
            print(f"integrity: evict RPC failed: {err}", flush=True)
            return False
        if plan.rank is None:
            print("integrity: eviction plan dropped this rank too — "
                  "aborting", flush=True)
            return False
        self._elastic_rebuild("integrity_evict", plan=plan)
        return True

    def _train_one_round(self, timer, tracer) -> bool:
        """Run one training round; returns False when a preemption
        request stopped the round early (single-process only — see
        task_train), True when the round ran to completion."""
        if not self.silent:
            print(f"update round {self.start_counter - 1}", flush=True)
        from .parallel.distributed import process_info

        from .obs import trace as obs_trace
        from .utils.profiler import pipeline_stats

        nproc = process_info()[1]
        check_preempt = nproc == 1
        # async data-parallel (doc/parallel.md "Async data-parallel"):
        # per-step fences move to the round boundary — the loop must
        # not sync after every update or the overlap is gone
        async_on = (self.test_io == 0
                    and self.net_trainer._async_active())
        preempted = False
        sample_counter = 0
        self.net_trainer.start_round(self.start_counter)
        self.itr_train.before_first()
        # anchor the augmentation epoch to the ROUND counter (after the
        # rewind, overriding the process-local epoch count): a resumed
        # run's round r then draws the identical stream an uninterrupted
        # run drew at round r (io/augment.py `augment_epoch`)
        self.itr_train.set_param("augment_epoch", str(self.start_counter))
        timer.clear()
        pipeline_stats().reset()  # per-round stage breakdown
        pipe_mark = time.perf_counter()  # last fence (lap start)
        pending: List = []  # scan_steps>1: batches staged for ONE dispatch
        in_flight: List = []  # async (handle, n_steps) chunks in flight

        def _lap(n_steps: int) -> None:
            """Fold the span since the last fence into the timer —
            decode + dispatch + device wait for one chunk.  The laps
            (plus the round-end drain) tile the round's wall time
            exactly, so samples/sec is the true PIPELINE rate (max of
            host and device time per chunk), not just device time."""
            nonlocal pipe_mark
            now = time.perf_counter()
            timer.add(now - pipe_mark, n_steps)
            pipe_mark = now

        def _fence(drain_all: bool) -> None:
            """Block on finished chunks, recording a lap per chunk.
            ``drain_all=False`` keeps the newest chunk running — the
            double buffer (chunk k-1 must land before k+2 stages)."""
            import jax as _jx

            while len(in_flight) > (0 if drain_all else 1):
                handle, ns = in_flight.pop(0)
                t0 = time.perf_counter()
                with obs_trace.span("train.device_wait", steps=ns):
                    _jx.block_until_ready(handle)
                pipeline_stats().add(
                    "device_wait", time.perf_counter() - t0,
                    rows=ns * self.net_trainer.batch_size,
                )
                _lap(ns)

        def _flush_pending() -> None:
            """Run staged batches as one device program (lax.scan over
            the fused step) — amortizes per-dispatch host cost
            exactly like bench.py (doc/performance.md).

            With ``eval_train = 0`` the scan dispatch is ASYNC: the
            device chews chunk k while the host decodes/augments
            chunk k+1 (the reference's two-stage ThreadBuffer
            overlap, here via XLA's async dispatch queue).  At most
            two chunks stay in flight — a double buffer — so host
            memory stays bounded.  Timing is fence-to-fence (_lap):
            each recorded span covers a chunk's host decode AND its
            device wait, so the round statistics report the honest
            pipeline rate.  With ``eval_train = 1`` every chunk is
            synchronous (metrics fetch outputs) and the timer spans
            just the dispatch+wait, the plain step-time metric."""
            if not pending:
                return
            tracer.step(self._global_step)
            obs_trace.step(self._global_step)
            sync_mode = bool(self.net_trainer.eval_train)
            if sync_mode:
                timer.start()
            if len(pending) == 1:
                from .io.data import DataBatch as _DB

                if not sync_mode:
                    _fence(drain_all=True)  # update() syncs anyway
                self.net_trainer.update(
                    _DB(data=pending[0][0], label=pending[0][1])
                )
                if not sync_mode:
                    t0 = time.perf_counter()
                    self.net_trainer.sync()
                    pipeline_stats().add(
                        "device_wait", time.perf_counter() - t0,
                        rows=self.net_trainer.batch_size,
                    )
                    _lap(1)
            else:
                import numpy as _np

                with obs_trace.span("train.dispatch",
                                    steps=len(pending)):
                    handle = self.net_trainer.update_scan(
                        _np.stack([d for d, _ in pending]),
                        _np.stack([l for _, l in pending]),
                        sync=sync_mode,
                        # sharded iterators guarantee equal K per process
                        # (equal-steps contract) — skip the collective
                        # K-check so the async overlap stays unbroken
                        check_steps=False,
                    )
                if not sync_mode:
                    in_flight.append((handle, len(pending)))
                    _fence(drain_all=False)
            if sync_mode:
                timer.stop(n_steps=len(pending))
            self._global_step += len(pending)
            pending.clear()

        def _drain_in_flight() -> None:
            _fence(drain_all=True)

        # multi-process scan is safe from the CLI: sharded train
        # iterators run equal batch counts per round (equal-steps
        # contract), so every process flushes identical [K, ...]
        # stacks at the same points
        scan_ok = (
            self.scan_steps > 1
            and not async_on  # the scan program is the fused sync step
            and self.net_trainer.update_period == 1
            and not self.net_trainer._n_extras()
            # node-bound train metrics need the per-step node
            # forwards only update() provides (irrelevant when
            # eval_train is off — train metrics never run then)
            and not (self.net_trainer.eval_train
                     and self.net_trainer.train_metric.need_nodes())
        )
        # double-buffered device feed (doc/performance.md): in the
        # per-batch path with no metric fetch in the way, batch N+1 is
        # decoded AND transferred (stage_batch: async sharding-aware
        # device_put) while step N still executes, then step N is
        # fenced — h2d no longer serializes with dispatch.  The staged
        # copy is owned (iterator buffers are reused by next()).  The
        # timed span becomes fence-to-fence, i.e. the honest pipeline
        # rate, exactly like the scan path.
        db_ok = (
            self.test_io == 0
            and not scan_ok
            and nproc == 1
            and not self.net_trainer.eval_train
        )
        staged_next = None  # owned copy of batch N+1, H2D in flight
        exhausted = False   # next() returned False — NEVER call it
        # again this epoch (a ThreadBufferIterator delivers exactly one
        # end marker per generation; a second next() would block)
        while True:
            if staged_next is not None:
                batch, staged_next = staged_next, None
            elif exhausted:
                break
            elif self.itr_train.next():
                batch = (self.itr_train.value() if self.test_io == 0
                         else None)
            else:
                break
            if self.test_io == 0:
                if scan_ok and not batch.num_batch_padd:
                    import numpy as _np

                    # copy: iterator buffers are reused by next()
                    pending.append(
                        (_np.array(batch.data), _np.array(batch.label))
                    )
                    if len(pending) >= self.scan_steps:
                        _flush_pending()
                else:
                    _flush_pending()  # keep update order
                    _fence(drain_all=True)  # update()'s sync would
                    # fence leftovers inside the timed span otherwise
                    tracer.step(self._global_step)
                    obs_trace.step(self._global_step)
                    timer.start()
                    self.net_trainer.update(batch)
                    if not self.net_trainer.eval_train:
                        if db_ok and not exhausted:
                            if self.itr_train.next():
                                import numpy as _np

                                from .io.data import DataBatch as _DB

                                v = self.itr_train.value()
                                staged_next = _DB(
                                    data=_np.array(v.data),
                                    label=_np.array(v.label),
                                    num_batch_padd=v.num_batch_padd,
                                    extra_data=[_np.array(e)
                                                for e in v.extra_data],
                                )
                                self.net_trainer.stage_batch(staged_next)
                            else:
                                exhausted = True
                        if not async_on:
                            # async mode: NO per-step fence — the
                            # dispatch pipeline runs free until the
                            # round-boundary async_round_end below
                            t0 = time.perf_counter()
                            self.net_trainer.sync()
                            pipeline_stats().add(
                                "device_wait", time.perf_counter() - t0,
                                rows=self.net_trainer.batch_size,
                            )
                    timer.stop()
                    self._global_step += 1
                    pipe_mark = time.perf_counter()  # span was timed
            sample_counter += 1
            if (self.print_step > 0 and sample_counter % self.print_step == 0
                    and not self.silent):
                elapsed = int(time.time() - self._train_start)
                print(
                    f"round {self.start_counter - 1:8d}:"
                    f"[{sample_counter:8d}] {elapsed} sec elapsed",
                    flush=True,
                )
            if check_preempt and self._preempt.requested:
                preempted = True
                break
        _flush_pending()  # tail chunk shorter than scan_steps
        _drain_in_flight()  # round/preemption boundary: queue empty
        if async_on:
            # round-boundary fence (and, on resync rounds, the hard
            # barrier draining the staleness buffers); billed as one
            # device_wait lap so the round timing stays honest
            t0 = time.perf_counter()
            self.net_trainer.async_round_end(self.start_counter)
            dt = time.perf_counter() - t0
            pipeline_stats().add(
                "device_wait", dt,
                rows=sample_counter * self.net_trainer.batch_size,
            )
            timer.add(dt, 0)
        if preempted:
            return False
        stage_line = pipeline_stats().report()
        if not self.silent and stage_line:
            # per-stage host-pipeline breakdown (decode/augment/batch/
            # h2d/device_wait) — prints in test_io dry-runs too, where
            # it IS the measurement
            print(
                f"round {self.start_counter - 1:8d} pipeline: "
                + stage_line,
                flush=True,
            )
        if self.test_io == 0:
            if not self.silent and timer.count:
                print(
                    f"round {self.start_counter - 1:8d}: "
                    + timer.report(self.net_trainer.batch_size),
                    flush=True,
                )
            sys.stderr.write(f"[{self.start_counter}]")
            eval_text = ""
            if not self.itr_evals:
                eval_text += self.net_trainer.evaluate(None, "train")
            for it, nm in zip(self.itr_evals, self.eval_names):
                eval_text += self.net_trainer.evaluate(it, nm)
            sys.stderr.write(eval_text)
            sys.stderr.write("\n")
            sys.stderr.flush()
            self._write_telemetry(timer, eval_text, sample_counter)
            if self.test_on_server:
                dev = self.net_trainer.check_weight_sync()
                sys.stderr.write(
                    f"[{self.start_counter}]\tweight-sync:"
                    f"max_dev={dev:g} ok\n"
                )
                sys.stderr.flush()
        return True

    def _write_telemetry(self, timer, eval_text: str,
                         n_batches: int) -> None:
        """Append one per-round JSONL record to ``telemetry_path``
        (``telemetry = 1``; doc/observability.md).  The record carries
        what the human-facing round lines print — eval metrics, step
        timing, samples/sec, learning rate, per-stage pipeline timers —
        as one machine-parseable object.  Never raises: a full disk
        must not abort training (failures are event-logged once)."""
        if not self.telemetry:
            return
        import json
        import re

        from .obs import device as obs_device
        from .obs import events as obs_events
        from .obs import log_exception_once
        from .utils import diskio
        from .utils.profiler import pipeline_stats

        metrics = {
            m.group(1): float(m.group(2))
            for m in re.finditer(
                r"(\S+?):([-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)",
                eval_text or "",
            )
        }
        lr = None
        try:
            up = next(iter(self.net_trainer.updaters.values()))
            lr = float(up.param.base_lr)
        except (StopIteration, AttributeError):
            pass
        record = {
            "ts": time.time(),
            "round": self.start_counter - 1,
            "steps": timer.count,
            "batches": n_batches,
            "elapsed_s": time.time() - self._train_start,
            "lr": lr,
            "eval": metrics,
            "step": timer.summary(self.net_trainer.batch_size),
            "stages": pipeline_stats().snapshot(),
            # device plane (doc/observability.md): programs compiled so
            # far, their estimated FLOPs/bytes, cumulative XLA compile
            # seconds, sampled step fences — lifetime totals, so per-
            # round deltas are computable between records
            "device": obs_device.summary(),
        }
        async_snap = self.net_trainer.async_snapshot()
        if async_snap is not None:
            # async data-parallel pipeline block: pending aggregate
            # depths, push/apply/drop totals, last overlap fraction
            record["async"] = async_snap
        if self.elastic_member is not None or self._elastic_rebuilds:
            from .parallel.distributed import process_info as _pinfo

            record["elastic"] = {
                "rebuilds": self._elastic_rebuilds,
                "last_rebuild_s": round(self._elastic_last_rebuild_s, 3),
                "processes": _pinfo()[1],
                "generation": (self.elastic_member.generation
                               if self.elastic_member is not None
                               else None),
            }
        if self._integrity is not None:
            # integrity plane: check cadence/count and the newest
            # fingerprint-verified round (the quarantine rollback bound)
            record["integrity"] = self._integrity.snapshot()
        try:
            line = json.dumps(record, separators=(",", ":")) + "\n"
            diskio.append_bytes(self.telemetry_path,
                                line.encode("utf-8"), site="obs.append")
        except (OSError, ValueError, TypeError) as e:
            # degrade-don't-crash: a round record is droppable; training
            # and serving keep going, the drop is counted and the first
            # failure logged (disk-full additionally bumps
            # disk_full_total inside diskio → the paging alert)
            import errno as _errno
            reason = ("disk" if getattr(e, "errno", None) == _errno.ENOSPC
                      else "io")
            obs_events.record_drop("telemetry", reason)
            log_exception_once("cli.telemetry", e, kind="telemetry.error",
                               path=self.telemetry_path)

    def task_predict(self, raw: bool = False) -> None:
        """``task=pred``: one argmax/value per line.  ``task=pred_raw``:
        the full output row (softmax probabilities) space-separated —
        the submission-file input (reference ``CXXNetPredRaw``,
        ``wrapper/cxxnet_wrapper.h:150``; kaggle_bowl make_submission
        expects a trailing separator, kept for format parity)."""
        if self.itr_pred is None:
            raise ValueError("must specify a pred iterator to generate predictions")
        print("start predicting...")
        t0 = time.perf_counter()
        nrow = 0
        with open(self.name_pred, "w", encoding="utf-8") as fo:
            self.itr_pred.before_first()
            while self.itr_pred.next():
                # stream per batch: each batch's rows are formatted and
                # flushed as soon as they land, so memory stays O(batch)
                # no matter how large the prediction set is
                batch = self.itr_pred.value()
                n = batch.batch_size - batch.num_batch_padd
                if raw:
                    rows = self.net_trainer.extract_feature(batch, "top[-1]")
                    rows = rows.reshape(rows.shape[0], -1)
                    for r in rows[:n]:
                        fo.write(" ".join(f"{v:g}" for v in r) + " \n")
                else:
                    preds = self.net_trainer.predict(batch)
                    for v in preds[:n]:
                        if np.ndim(v):  # sequence models: (T,) ids/row
                            fo.write(
                                " ".join(f"{t:g}" for t in v) + "\n"
                            )
                        else:
                            fo.write(f"{v:g}\n")
                fo.flush()
                nrow += n
        dt = time.perf_counter() - t0
        rate = nrow / dt if dt > 0 else 0.0
        print(f"finished prediction, write into {self.name_pred} "
              f"({nrow} rows, {rate:.1f} rows/sec)")

    def task_serve_fleet(self) -> None:
        """``task=serve`` with ``replicas >= 2``: the serving fleet
        (doc/serving.md "Serving fleet").

        Launches ``replicas`` single-engine ``task=serve`` child
        processes (each re-reading this conf with the fleet keys
        pinned), supervises them (healthz probing, SLOW/GONE
        classification, restart-with-backoff, eject-from-rotation of
        wedged replicas), and runs the routing front-end on
        ``serve_host:serve_port`` — priority-classed admission control
        (batch sheds first), least-loaded dispatch with failover, and
        deadline budgets split between route and execute.  With
        ``serve_reload_period > 0`` new rounds in ``model_dir`` roll
        out one replica at a time behind a fleet-level circuit
        breaker; with ``canary = int8`` the fleet runs a rolling int8
        canary that promotes or rolls back through the publish
        pointer, with ``/alertz`` as the rollback trigger."""
        import signal as _signal
        import threading

        from .serve.fleet import FleetOptions, ServingFleet, cli_spawn_fn

        opts = FleetOptions.from_cfg(self.cfg)
        model_dir = (self.name_model_dir
                     if self.name_model_in == "NULL" else None)
        log_dir = opts.log_dir or (
            os.path.join(model_dir, "fleet_logs") if model_dir
            else "fleet_logs")
        spawn = cli_spawn_fn(self.conf_path, self.cli_overrides,
                             host=self.serve_host, opts=opts,
                             log_dir=log_dir)
        fleet = ServingFleet(
            opts, spawn_fn=spawn, host=self.serve_host,
            port=self.serve_port, model_dir=model_dir,
            default_deadline_ms=self.serve_deadline_ms,
            reload_period_s=self.serve_reload_period,
            silent=bool(self.silent),
        )
        httpd_box = {}

        def _stop(signum, frame):
            print(f"fleet: shutdown requested, draining (up to "
                  f"{self.drain_timeout_s:g}s)", flush=True)
            h = httpd_box.get("httpd")
            if h is not None:
                threading.Thread(target=h.shutdown, daemon=True).start()
            else:
                # still booting replicas: abort startup — the raise
                # lands in the main thread inside fleet.start(), the
                # finally below reaps the spawned children
                raise SystemExit(0)

        prev = {s: _signal.signal(s, _stop)
                for s in (_signal.SIGTERM, _signal.SIGINT)}
        try:
            httpd = fleet.start()
            httpd_box["httpd"] = httpd
            h = fleet.healthz()
            print(f"fleet: serving {h['rotation']}/{opts.replicas} "
                  f"replica(s) (round {h['round']}) on "
                  f"http://{self.serve_host}:{httpd.server_port}",
                  flush=True)
            httpd.serve_forever(poll_interval=0.2)
        finally:
            for s, p in prev.items():
                _signal.signal(s, p)
            fleet.close(self.drain_timeout_s)
        print("fleet: shutdown complete", flush=True)

    def task_serve(self) -> None:
        """``task=serve``: run the online inference server (doc/serving.md).

        Loads ``model_in`` (or the newest valid checkpoint in
        ``model_dir``) into a :class:`~cxxnet_tpu.serve.Engine` and
        serves ``/predict`` / ``/extract`` / ``/healthz`` / ``/statsz``
        on ``serve_host:serve_port`` (``serve_port = 0`` picks an
        ephemeral port, printed on startup).  SIGTERM/SIGINT drain
        gracefully: the server stops accepting, in-flight requests get
        up to ``drain_timeout_s`` to finish, queued ones are failed
        with 503, then the process exits 0.

        ``replicas >= 2`` routes to :meth:`task_serve_fleet` instead —
        N supervised engine subprocesses behind one front door."""
        import signal as _signal
        import threading

        from .serve import Engine
        from .serve.server import serve_forever

        if self.replicas > 1:
            return self.task_serve_fleet()

        model_in = (None if self.name_model_in == "NULL"
                    else self.name_model_in)
        engine = Engine(
            cfg=self.cfg,
            model_in=model_in,
            model_dir=None if model_in else self.name_model_dir,
            max_batch_size=self.serve_max_batch,
            batch_timeout_ms=self.batch_timeout_ms,
            queue_limit=self.queue_limit,
            default_deadline_ms=self.serve_deadline_ms,
            silent=bool(self.silent),
            reload_breaker_threshold=self.reload_breaker_threshold,
            reload_breaker_cooldown_s=self.reload_breaker_cooldown_s,
            watchdog_timeout_s=self.watchdog_timeout_s,
        )
        httpd_box = {}

        def _ready(httpd):
            httpd_box["httpd"] = httpd
            h = engine.healthz()
            print(f"serving model round {h['round']} "
                  f"(fp {h['net_fp']}) on "
                  f"http://{httpd.server_address[0]}:{httpd.server_port}",
                  flush=True)

        def _stop(signum, frame):
            print(f"serve: shutdown requested, draining in-flight "
                  f"requests (up to {self.drain_timeout_s:g}s)", flush=True)
            h = httpd_box.get("httpd")
            if h is not None:
                # shutdown() blocks until serve_forever returns — must
                # not run on the thread stuck inside serve_forever
                threading.Thread(target=h.shutdown, daemon=True).start()

        prev = {s: _signal.signal(s, _stop)
                for s in (_signal.SIGTERM, _signal.SIGINT)}
        tuner = self._start_serve_controller(engine)
        try:
            serve_forever(
                engine,
                host=self.serve_host,
                port=self.serve_port,
                reload_period_s=self.serve_reload_period,
                drain_timeout_s=self.drain_timeout_s,
                verbose=not self.silent,
                ready_fn=_ready,
            )
        finally:
            for s, p in prev.items():
                _signal.signal(s, p)
            if tuner is not None:
                tuner.stop()
            engine.close()
        print("serve: shutdown complete", flush=True)

    def task_data_service(self) -> None:
        """``task=data_service``: run the shared decode/augment server
        (doc/io.md "Data service").

        Hosts the conf's ``data`` section iterator chain behind the
        ``CXD1`` batch protocol on ``data_service_host:
        data_service_port`` (0 picks an ephemeral port; the bound
        address lands in ``data_service_ready_file`` for discovery) and
        a ``/healthz``/``/statsz``/``/metricsz`` HTTP sidecar on
        ``data_service_http_port``.  SIGTERM/SIGINT stop both planes
        and close the chain."""
        import signal as _signal
        import threading

        from .io.dataservice.server import DataServiceServer

        split = cfgmod.split_sections(self.cfg)
        data_secs = split.find("data")
        if not data_secs:
            raise ValueError(
                "task=data_service needs a 'data = train ... iter = "
                "end' section (the chain this server deals)")
        if len(data_secs) > 1:
            raise ValueError("task=data_service serves exactly one "
                             "data section")
        server = DataServiceServer(
            data_secs[0].entries,
            split.global_entries,
            host=self.data_service_host,
            port=self.data_service_port,
            http_port=self.data_service_http_port,
            max_sessions=self.data_service_max_sessions,
            cache_bytes=int(self.data_service_cache_mb * (1 << 20)),
            window=self.data_service_window,
            ready_file=self.data_service_ready_file,
            silent=bool(self.silent),
        )

        def _stop(signum, frame):
            print("data_service: shutdown requested", flush=True)
            # shutdown() joins serve_forever loops — never run it on
            # the thread blocked inside serve_forever
            threading.Thread(target=server.shutdown, daemon=True).start()

        prev = {s: _signal.signal(s, _stop)
                for s in (_signal.SIGTERM, _signal.SIGINT)}
        try:
            server.serve_forever()
        finally:
            for s, p in prev.items():
                _signal.signal(s, p)
            server.close()
        print("data_service: shutdown complete", flush=True)

    def task_serve_train(self) -> None:
        """``task=serve_train``: the closed loop — serve, collect
        feedback, fine-tune, publish behind the eval gate
        (doc/continuous_training.md).

        The serving engine and HTTP front-end run exactly as
        ``task=serve`` (plus a ``POST /feedback`` route and, with
        ``capture_predict = 1``, prediction capture); a daemon thread
        runs the :class:`~cxxnet_tpu.loop.ContinuousLoop` — tail the
        feedback log, fine-tune ``loop_rounds_per_cycle`` rounds mixed
        with ``loop_replay_ratio`` base-iterator rows, and hand the
        candidate to the eval-gated publisher.  Published checkpoints
        land in ``model_dir`` and hot-reload immediately.
        ``loop_max_cycles > 0`` stops fine-tuning after that many
        trained cycles (serving continues).  Shutdown is the same
        graceful drain as ``task=serve``."""
        import signal as _signal
        import threading

        from .loop import ContinuousLoop, FeedbackWriter
        from .serve import Engine
        from .serve.server import serve_forever

        if self.replicas > 1:
            raise ValueError(
                "task=serve_train is single-replica (the fine-tune loop "
                "rides beside one engine); run the fleet with task=serve "
                "and a separate serve_train process if both are needed")
        if not self.itr_evals:
            raise ValueError(
                "task=serve_train needs an eval section — the publish "
                "gate scores candidates on held-out data")
        if any(n == "quant" and v not in ("", "0", "off", "none")
               for n, v in self.cfg):
            raise ValueError(
                "task=serve_train cannot serve a quantized model: the "
                "fine-tune loop trains on the served weights, and "
                "quantized trainers are inference-only — serve the f32 "
                "checkpoints and run task=export_quant offline")
        engine = Engine(
            cfg=self.cfg,
            model_dir=self.name_model_dir,
            max_batch_size=self.serve_max_batch,
            batch_timeout_ms=self.batch_timeout_ms,
            queue_limit=self.queue_limit,
            default_deadline_ms=self.serve_deadline_ms,
            silent=bool(self.silent),
            reload_breaker_threshold=self.reload_breaker_threshold,
            reload_breaker_cooldown_s=self.reload_breaker_cooldown_s,
            watchdog_timeout_s=self.watchdog_timeout_s,
        )
        feedback = FeedbackWriter(
            os.path.join(self.loop_dir, "feedback"),
            page_bytes=self.feedback_page_bytes,
            rotate_bytes=self.feedback_rotate_bytes,
        )
        retention = None
        if self.feedback_retain_shards >= 0:
            from .loop.retention import RetentionOptions, Sweeper

            retention = Sweeper(
                feedback.dir,
                RetentionOptions(self.feedback_retain_shards,
                                 self.feedback_retain_bytes),
                silent=bool(self.silent))
        loop = ContinuousLoop(
            engine,
            self.cfg,
            feedback_dir=feedback.dir,
            base_iter=self.itr_train,
            eval_iter=self.itr_evals[0],
            eval_name=self.eval_names[0] if self.eval_names else "eval",
            rounds_per_cycle=self.loop_rounds_per_cycle,
            replay_ratio=self.loop_replay_ratio,
            min_records=self.loop_min_records,
            max_records_per_cycle=self.loop_max_records,
            cycle_period_s=self.loop_cycle_period_s,
            publish_min_delta=self.publish_min_delta,
            publish_metric=self.publish_metric,
            publish_slice_floor=(self.publish_slice_floor
                                 if self.publish_slice_floor >= 0
                                 else None),
            publish_slice_min_count=self.publish_slice_min_count,
            publish_source_field=(self.publish_source_field
                                  if self.publish_source_field >= 0
                                  else None),
            feedback_writer=feedback,
            retention=retention,
            silent=bool(self.silent),
        )
        loop_thread = threading.Thread(
            target=loop.run, kwargs={"max_cycles": self.loop_max_cycles},
            name="cxxnet-serve-train-loop", daemon=True,
        )
        httpd_box = {}

        def _ready(httpd):
            httpd_box["httpd"] = httpd
            h = engine.healthz()
            print(f"serve_train: serving model round {h['round']} "
                  f"(fp {h['net_fp']}) on "
                  f"http://{httpd.server_address[0]}:{httpd.server_port}; "
                  f"feedback log at {feedback.dir}",
                  flush=True)
            loop_thread.start()

        def _stop(signum, frame):
            print(f"serve_train: shutdown requested, draining (up to "
                  f"{self.drain_timeout_s:g}s)", flush=True)
            loop.stop()
            h = httpd_box.get("httpd")
            if h is not None:
                threading.Thread(target=h.shutdown, daemon=True).start()

        prev = {s: _signal.signal(s, _stop)
                for s in (_signal.SIGTERM, _signal.SIGINT)}
        tuner = self._start_serve_controller(engine)
        try:
            serve_forever(
                engine,
                host=self.serve_host,
                port=self.serve_port,
                reload_period_s=self.serve_reload_period,
                drain_timeout_s=self.drain_timeout_s,
                verbose=not self.silent,
                ready_fn=_ready,
                feedback=feedback,
                capture_predict=bool(self.capture_predict),
            )
        finally:
            for s, p in prev.items():
                _signal.signal(s, p)
            if tuner is not None:
                tuner.stop()
            loop.stop()
            if loop_thread.is_alive():
                loop_thread.join(timeout=max(self.drain_timeout_s, 5.0))
            engine.close()
            feedback.close()
        print("serve_train: shutdown complete", flush=True)

    def task_loop_fleet(self) -> None:
        """``task=loop_fleet``: multi-tenant continuous learning
        (doc/continuous_training.md "Multi-tenant loops").

        Hosts one serving engine + feedback log + fine-tune loop per
        ``[tenant:<name>]`` conf section, all sharing this process's
        device pool.  One HTTP front door dispatches by the request's
        ``model`` field (``serve/router.ModelRouter``); a scheduler
        thread round-robins the tenants' fine-tune cycles under the
        SLO-constrained arbiter — while any ``alert=`` rule fires
        (e.g. the serve plane's p99 bound), ALL tune cycles shed.
        Gates are per-slice when ``publish_slice_floor >= 0``; consumed
        feedback shards compact when ``feedback_retain_shards >= 0``.
        Shutdown drains like ``task=serve``."""
        import signal as _signal
        import threading

        from .loop.tenant import TenantManager
        from .serve import Engine
        from .serve.server import serve_forever
        from .tune import options_from_cfg

        if self.replicas > 1:
            raise ValueError(
                "task=loop_fleet is single-replica per tenant engine; "
                "front a replica fleet with task=serve separately")
        if any(n == "quant" and v not in ("", "0", "off", "none")
               for n, v in self.cfg):
            raise ValueError(
                "task=loop_fleet cannot serve quantized models: the "
                "fine-tune loops train on the served weights")
        shared_cfg, tenant_secs = cfgmod.split_tenant_sections(self.cfg)
        if not tenant_secs:
            raise ValueError(
                "task=loop_fleet needs at least one tenant section "
                "(tenant = <name> .. tenant = end)")
        if not cfgmod.split_sections(shared_cfg).find("eval"):
            raise ValueError(
                "task=loop_fleet needs an eval section — every "
                "tenant's publish gate scores on held-out data")

        def engine_factory(tenant_cfg, model_dir):
            return Engine(
                cfg=tenant_cfg,
                model_dir=model_dir,
                max_batch_size=self.serve_max_batch,
                batch_timeout_ms=self.batch_timeout_ms,
                queue_limit=self.queue_limit,
                default_deadline_ms=self.serve_deadline_ms,
                silent=bool(self.silent),
                reload_breaker_threshold=self.reload_breaker_threshold,
                reload_breaker_cooldown_s=self.reload_breaker_cooldown_s,
                watchdog_timeout_s=self.watchdog_timeout_s,
            )

        def make_iters(tenant_cfg):
            # a tenant's iterators come from the SHARED data/eval
            # sections with the tenant's own overrides applied last
            # (e.g. seed_data) — fresh instances per tenant, iterator
            # state is never shared
            tsplit = cfgmod.split_sections(tenant_cfg)
            data = tsplit.find("data")
            evals = tsplit.find("eval")
            base = create_iterator(data[0].entries) if data else None
            ev = create_iterator(evals[0].entries)
            for it in (base, ev):
                if it is None:
                    continue
                for n, v in tsplit.global_entries:
                    it.set_param(n, v)
                it.init()
            return base, ev, evals[0].tag or "eval"

        manager = TenantManager(
            shared_cfg, tenant_secs,
            engine_factory=engine_factory,
            make_iters=make_iters,
            loop_dir=self.loop_dir,
            period_s=self.loop_cycle_period_s,
            # the fleet-wide arbiter reads the SHARED stream: a tune_*
            # key inside a tenant section must never retune the shared
            # controller (the same isolation set_param enforces)
            tune_opts=options_from_cfg(shared_cfg),
            silent=bool(self.silent),
        )
        router = manager.router()
        httpd_box = {}

        def _ready(httpd):
            httpd_box["httpd"] = httpd
            names = ", ".join(t.name for t in manager.tenants)
            print(f"loop_fleet: serving {len(manager.tenants)} "
                  f"tenant(s) [{names}] on "
                  f"http://{httpd.server_address[0]}:{httpd.server_port}",
                  flush=True)
            manager.start()

        def _stop(signum, frame):
            # signal only — joining the scheduler here would stall the
            # accept loop for up to a whole fine-tune cycle and eat the
            # drain window; close() in the finally block does the join
            print(f"loop_fleet: shutdown requested, draining (up to "
                  f"{self.drain_timeout_s:g}s)", flush=True)
            manager.request_stop()
            h = httpd_box.get("httpd")
            if h is not None:
                threading.Thread(target=h.shutdown, daemon=True).start()

        prev = {s: _signal.signal(s, _stop)
                for s in (_signal.SIGTERM, _signal.SIGINT)}
        try:
            serve_forever(
                manager.tenants[0].engine,
                host=self.serve_host,
                port=self.serve_port,
                reload_period_s=self.serve_reload_period,
                drain_timeout_s=self.drain_timeout_s,
                verbose=not self.silent,
                ready_fn=_ready,
                capture_predict=bool(self.capture_predict),
                router=router,
            )
        finally:
            for s, p in prev.items():
                _signal.signal(s, p)
            manager.close()
        print("loop_fleet: shutdown complete", flush=True)

    def task_export_quant(self) -> int:
        """``task=export_quant``: post-training quantized export with
        the accuracy gate (doc/performance.md "Quantized inference").

        Quantizes ``model_in`` per ``quant`` (default int8), gates it
        on top-1 agreement with the f32 model over the conf's eval
        section (``quant_min_agreement`` / ``quant_calib_batches``),
        falling individual layers back to bf16 until the gate passes,
        and writes ``<round>.quant.model`` + manifest beside the
        source.  Prints one JSON verdict line; exit 0 on publish, 3 on
        reject (nothing written — the f32 artifact keeps serving)."""
        import json

        from .nnet import quant as nquant

        eval_iter = self.itr_evals[0] if self.itr_evals else None
        verdict = nquant.export_quantized(
            self.cfg,
            self.name_model_in,
            eval_iter=eval_iter,
            scheme=self.quant or "int8",
            min_agreement=self.quant_min_agreement,
            calib_batches=self.quant_calib_batches,
            out_path=self.quant_out or None,
            silent=bool(self.silent),
        )
        line = json.dumps(verdict, separators=(",", ":"))
        print(line, flush=True)
        if self.quant_report:
            from .utils.checkpoint import atomic_write_bytes
            atomic_write_bytes(self.quant_report,
                               (line + "\n").encode("utf-8"))
        return 0 if verdict["ok"] else 3

    def task_summary(self) -> None:
        """``task=summary``: per-layer table — type, name, output node
        shapes, parameter counts — plus totals.  Works on a bare conf
        (no data files needed; batch column shows the conf batch)."""
        import jax

        tr = self.net_trainer
        g = tr.graph
        shapes = tr.net.node_shapes
        total = 0
        print(f"{'#':>3} {'layer':22s} {'type':18s} {'out shape':20s} "
              f"{'params':>12}")
        for i, spec in enumerate(g.layers):
            key = tr.net.param_key[i]
            n_par = 0
            if spec.type_name != "shared" and key in tr.params:
                n_par = int(sum(
                    np.prod(np.shape(w))
                    for w in jax.tree_util.tree_leaves(tr.params[key])
                ))
                total += n_par
            out = shapes[spec.nindex_out[0]] if spec.nindex_out else ()
            name = spec.name or ""
            print(f"{i:>3} {name:22s} {spec.type_name:18s} "
                  f"{str(tuple(out)):20s} {n_par:>12,}")
        print(f"{'':66s}{'-' * 12}")
        print(f"total parameters: {total:,} "
              f"({total * 4 / 1e6:.1f} MB f32)")
        if tr.mesh_plan is not None:
            print(f"mesh: {tr.mesh_plan.describe(zero=tr.zero)}")

    def task_generate(self) -> None:
        """``task=generate``: autoregressive byte sampling from a trained
        language model (``nnet/generate.py``; doc/tasks.md).  KV-cache
        incremental decoding by default (``gen_cache = 1``), sliding
        window otherwise or as the fallback."""
        from .nnet.generate import generate

        prompt = self.gen_prompt
        if self.gen_prompt_file:
            with open(self.gen_prompt_file, "rb") as f:
                prompt = f.read().decode("utf-8", "replace")
        text = generate(
            self.net_trainer, prompt, self.gen_len, self.gen_temp,
            cache=bool(self.gen_cache), topk=self.gen_topk,
            topp=self.gen_topp, silent=bool(self.silent),
        )
        with open(self.name_pred, "w", encoding="utf-8") as fo:
            fo.write(text)
        if not self.silent:
            print(f"generated {len(text.encode())} bytes -> {self.name_pred}")
            print(text)

    def task_extract(self) -> None:
        if self.itr_pred is None:
            raise ValueError("must specify a pred iterator for feature extraction")
        if not self.extract_node_name:
            raise ValueError("extract_node_name must be specified in task extract")
        print("start predicting...")
        nrow = 0
        dshape = None
        meta_path = self.name_pred + ".meta"
        mode = "w" if self.output_format else "wb"
        with open(self.name_pred, mode) as fo:
            self.itr_pred.before_first()
            while self.itr_pred.next():
                batch = self.itr_pred.value()
                feats = self.net_trainer.extract_feature(batch, self.extract_node_name)
                n = batch.batch_size - batch.num_batch_padd
                feats = feats[:n]
                nrow += n
                flat = feats.reshape(feats.shape[0], -1)
                if self.output_format:
                    for row in flat:
                        fo.write(" ".join(f"{v:g}" for v in row) + " \n")
                else:
                    flat.astype("<f4").tofile(fo)
                if n:
                    dshape = feats.shape[1:]
        with open(meta_path, "w", encoding="utf-8") as fm:
            shp = list(dshape) if dshape else []
            while len(shp) < 3:
                shp.append(1)
            fm.write(f"{nrow},{shp[0]},{shp[1]},{shp[2]}\n")
        print(f"finished prediction, write into {self.name_pred}")


def _hard_exit_if_resilient(rc: int) -> None:
    """Elastic runs built coordination clients whose error-poll threads
    cannot be stopped from Python; interpreter-exit destructor order
    (leaked generation services vs zombie pollers) would abort the
    process AFTER all real work succeeded.  Flush and hard-exit
    instead — every artifact this process writes (checkpoints,
    manifests, telemetry, events) is flushed/fsynced at write time."""
    from .parallel.distributed import resilient_client_used

    if resilient_client_used():
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)


def main(argv: Optional[List[str]] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    try:
        rc = LearnTask().run(argv)
    except SystemExit:
        raise
    except BaseException:
        import traceback

        from .parallel.distributed import resilient_client_used

        if resilient_client_used():
            traceback.print_exc()
            _hard_exit_if_resilient(1)
        raise
    _hard_exit_if_resilient(rc)
    return rc
