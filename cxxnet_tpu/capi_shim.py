"""Flat-function shim behind the C ABI (``native/cxxnet_capi.cc``).

The C library embeds CPython and calls these module-level functions —
one per C entry point, names matching ``native/cxxnet_capi.h`` — so the
C side stays pure marshalling (no Python API knowledge beyond calling a
function and reading a buffer).  Parity surface:
``/root/reference/wrapper/cxxnet_wrapper.h:36-230`` (CXNIO* / CXNNet*).

Array-returning calls hand back C-contiguous float32 numpy arrays; the
C side holds a reference alongside the handle so the data pointer stays
alive until the next call on the same handle (the reference wrapper's
temp-buffer discipline, ``cxxnet_wrapper.cc`` returned mshadow tensor
views with the same lifetime rule).

Data layout note: the reference is NCHW; this framework is NHWC
(TPU-native).  4-D shapes returned here are ``(n, h, w, c)``; flat
data comes back ``(n, 1, 1, d)``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .wrapper import DataIter, Net


def _c_f32(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float32)


def _from_c4(d: np.ndarray) -> np.ndarray:
    """C-side data is always (n, h, w, c); collapse the (n, 1, 1, d)
    encoding of flat nodes back to (n, d) for the net input."""
    d = np.asarray(d)
    if d.ndim == 4 and d.shape[1] == 1 and d.shape[2] == 1:
        return d.reshape(d.shape[0], d.shape[3])
    return d


# ------------------------------------------------------------------ io
def io_create(cfg: str) -> DataIter:
    return DataIter(cfg)


def io_next(it: DataIter) -> int:
    return int(it.next())


def io_before_first(it: DataIter) -> None:
    it.before_first()


def io_get_data(it: DataIter) -> np.ndarray:
    d = np.asarray(it.get_data())
    if d.ndim == 2:
        d = d[:, None, None, :]
    elif d.ndim != 4:
        raise ValueError(f"io_get_data: unexpected data ndim {d.ndim}")
    return _c_f32(d)


def io_get_label(it: DataIter) -> np.ndarray:
    l = np.asarray(it.get_label())
    if l.ndim == 1:
        l = l[:, None]
    return _c_f32(l)


# ----------------------------------------------------------------- net
def net_create(device: Optional[str], cfg: str) -> Net:
    return Net(dev=device or "", cfg=cfg)


def net_set_param(net: Net, name: str, val: str) -> None:
    net.set_param(name, val)


def net_init_model(net: Net) -> None:
    net.init_model()


def net_save_model(net: Net, fname: str) -> None:
    net.save_model(fname)


def net_load_model(net: Net, fname: str) -> None:
    net.load_model(fname)


def net_start_round(net: Net, round_counter: int) -> None:
    net.start_round(round_counter)


def net_update_batch(net: Net, data: np.ndarray, label: np.ndarray) -> None:
    net.update(_from_c4(data), np.asarray(label))


def net_update_iter(net: Net, it: DataIter) -> None:
    net.update(it)


def net_predict_batch(net: Net, data: np.ndarray) -> np.ndarray:
    return _c_f32(net.predict(_from_c4(data)))


def net_predict_iter(net: Net, it: DataIter) -> np.ndarray:
    # DataIter path so num_batch_padd filler rows are trimmed
    return _c_f32(net.predict(it))


def net_extract_batch(net: Net, data: np.ndarray, name: str) -> np.ndarray:
    out = np.asarray(net.extract(_from_c4(data), name))
    return _c_f32(out.reshape(out.shape[0], -1))


def net_extract_iter(net: Net, it: DataIter, name: str) -> np.ndarray:
    out = np.asarray(net.extract(it, name))  # trims num_batch_padd rows
    return _c_f32(out.reshape(out.shape[0], -1))


def net_evaluate(net: Net, it: DataIter, name: str) -> str:
    return net.evaluate(it, name)


def net_set_weight(net: Net, weight: np.ndarray, layer: str, tag: str) -> None:
    net.set_weight(weight, layer, tag)


def net_get_weight(net: Net, layer: str, tag: str):
    """None (-> NULL at the C ABI, reference cxxnet_wrapper behavior)
    when the layer has no such weight."""
    w = net.get_weight(layer, tag)
    if w is None or w.size == 0:
        return None
    return _c_f32(w)
