"""Structured event log: lifecycle facts as rotating JSONL.

The third observability pillar (doc/observability.md).  Metrics say how
MUCH; events say WHAT HAPPENED: checkpoint saves/restores, hot reloads,
quarantined records, fault injections, watchdog fires, divergence-guard
trips, preemption snapshots.  Each event is one JSON object per line —
``{"ts": <unix seconds>, "kind": "checkpoint.save", ...fields}`` — so
``tools/obs_dump.py`` (or any jq pipeline) can tail, filter and
summarize a run post-hoc.

Behavior:

* an **in-memory ring** (bounded) always records, file or not — tests
  and ``/statsz``-style introspection read :func:`recent` without any
  filesystem coupling;
* a **file sink** activates when ``event_log = <path>`` is configured,
  with size-based rotation (``event_log_max_bytes``, default 4 MiB;
  ``event_log_backups``, default 2: ``events.jsonl`` → ``.1`` → ``.2``);
* :func:`emit` **never raises** — observability must not take down the
  thing it observes; write failures are counted (``dropped``) and the
  ring keeps recording;
* every emit bumps the ``obs_events_total{kind=...}`` counter in the
  metrics registry, so event rates are scrapeable from ``/metricsz``;
* :func:`log_exception_once` deduplicates noisy failure sites (e.g. a
  broken queue-depth gauge polled every scrape): the first exception
  per key is logged in full, repeats only count.
"""

from __future__ import annotations

import collections
import errno
import json
import os
import threading
import time
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from . import registry as _registry

__all__ = [
    "EventLog",
    "event_log",
    "emit",
    "recent",
    "configure",
    "log_exception_once",
    "record_drop",
]

ConfigEntry = Tuple[str, str]


def _jsonable(v):
    """Coerce a field value to something json.dumps accepts (events must
    never raise; a numpy scalar or Path in a field is not an error)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    try:
        import numpy as np

        if isinstance(v, np.generic):
            return v.item()
    except Exception:  # noqa: BLE001 - numpy optional here
        pass
    return str(v)


class EventLog:
    """One rotating JSONL sink + in-memory ring (see module docstring)."""

    def __init__(self, ring: int = 512) -> None:
        # reentrant: a failing file write reports through the diskio
        # layer, whose disk-full accounting emits right back here
        self._lock = threading.RLock()
        self._ring: Deque[dict] = collections.deque(maxlen=max(1, int(ring)))
        self.path: Optional[str] = None
        self.max_bytes = 4 << 20
        self.backups = 2
        self.dropped = 0
        #: bounded drop under a sick disk: after a write failure the
        #: file sink is skipped (events counted, ring still recording)
        #: for this long, instead of re-running makedirs + rotation +
        #: open against a full disk on EVERY event
        self.holdoff_s = 2.0
        self._skip_until = 0.0
        self._skip_reason = "io"
        self._once_counts: Dict[str, int] = {}
        self._counter = None  # obs_events_total, created lazily
        self._drop_counter = None  # events_dropped_total, lazy
        # file-sink re-entrancy guard: writing an event can itself emit
        # (a fault firing at the obs.append site, disk-full accounting
        # in diskio) — nested events go to the ring only, never back
        # into the file write that is already on this thread's stack
        self._tls = threading.local()

    # config -------------------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        if name == "event_log":
            self.path = val or None
        elif name == "event_log_max_bytes":
            self.max_bytes = max(1024, int(val))
        elif name == "event_log_backups":
            self.backups = max(0, int(val))
        elif name == "event_log_holdoff_s":
            self.holdoff_s = max(0.0, float(val))
        elif name == "event_log_ring":
            with self._lock:
                self._ring = collections.deque(
                    self._ring, maxlen=max(1, int(val))
                )

    def configure(self, cfg: Sequence[ConfigEntry]) -> None:
        for n, v in cfg:
            self.set_param(n, v)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._once_counts.clear()
        self.path = None
        self.max_bytes = 4 << 20
        self.backups = 2
        self.dropped = 0
        self.holdoff_s = 2.0
        self._skip_until = 0.0
        self._skip_reason = "io"

    # emission -----------------------------------------------------------
    def _count(self, kind: str) -> None:
        try:
            if self._counter is None:
                self._counter = _registry.registry().counter(
                    "obs_events_total",
                    "Structured events emitted, by kind.",
                    labelnames=("kind",),
                )
            self._counter.labels(kind=kind).inc()
        except Exception:  # noqa: BLE001 - never raise from emit
            pass

    def record_drop(self, sink: str, reason: str) -> None:
        """Count one dropped observability record:
        ``events_dropped_total{sink,reason}`` (``reason="disk"`` is the
        full-disk degrade path the ISSUE-16 alert watches)."""
        try:
            if self._drop_counter is None:
                self._drop_counter = _registry.registry().counter(
                    "events_dropped_total",
                    "Observability records dropped by the file sink "
                    "(bounded degrade; the ring keeps recording).",
                    labelnames=("sink", "reason"),
                )
            self._drop_counter.labels(sink=sink, reason=reason).inc()
        except Exception:  # noqa: BLE001 - never raise from emit
            pass

    def _rotate_locked(self, need: int) -> None:
        """Rotate ``path`` when appending ``need`` bytes would cross
        ``max_bytes``.  Caller holds the lock."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size + need <= self.max_bytes:
            return
        from ..utils import diskio
        if self.backups <= 0:
            # no backups: truncate in place
            diskio.truncate(self.path, 0)
            return
        oldest = f"{self.path}.{self.backups}"
        if os.path.exists(oldest):
            diskio.unlink(oldest)
        for i in range(self.backups - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                diskio.replace(src, f"{self.path}.{i + 1}")
        diskio.replace(self.path, f"{self.path}.1")

    def emit(self, kind: str, /, **fields) -> dict:
        """Record one event; returns the record.  Never raises.

        ``kind`` is positional-only so a field may itself be named
        ``kind``; field names colliding with the envelope (``ts`` /
        ``kind``) are stored with a ``_`` suffix rather than clobbering
        it."""
        rec = {"ts": time.time(), "kind": str(kind)}
        for k, v in fields.items():
            k = str(k)
            if k in ("ts", "kind"):
                k += "_"
            rec[k] = _jsonable(v)
        try:
            line = json.dumps(rec, separators=(",", ":"))
        except Exception:  # noqa: BLE001 - _jsonable should prevent this
            rec = {"ts": rec["ts"], "kind": rec["kind"],
                   "error": "unserializable fields"}
            line = json.dumps(rec, separators=(",", ":"))
        with self._lock:
            self._ring.append(rec)
            if self.path and getattr(self._tls, "writing", False):
                pass  # nested emit inside a file write: ring only
            elif self.path:
                if time.monotonic() < self._skip_until:
                    # bounded drop: the file sink failed recently; skip
                    # the I/O attempt entirely until the holdoff passes
                    self.dropped += 1
                    self.record_drop("events", self._skip_reason)
                else:
                    from ..utils import diskio
                    self._tls.writing = True
                    try:
                        d = os.path.dirname(self.path)
                        if d:
                            os.makedirs(d, exist_ok=True)
                        self._rotate_locked(len(line) + 1)
                        diskio.append_bytes(
                            self.path, (line + "\n").encode("utf-8"),
                            site="obs.append")
                    except OSError as e:
                        self.dropped += 1
                        reason = ("disk" if getattr(e, "errno", None)
                                  == errno.ENOSPC else "io")
                        self._skip_reason = reason
                        self._skip_until = (time.monotonic()
                                            + self.holdoff_s)
                        self.record_drop("events", reason)
                    finally:
                        self._tls.writing = False
        self._count(rec["kind"])
        return rec

    def emit_once(self, key: str, kind: str, **fields) -> bool:
        """Emit at most once per ``key`` (process lifetime) — for
        recurring facts a poll loop would otherwise flood the log with
        (e.g. the same invalid checkpoint skipped every reload poll).
        Repeats only count (:meth:`suppressed_count`).  Returns True
        when this call actually emitted."""
        with self._lock:
            n = self._once_counts.get(key, 0)
            self._once_counts[key] = n + 1
        if n:
            return False
        self.emit(kind, key=key, deduped=True, **fields)
        return True

    def log_exception_once(self, key: str, exc: BaseException,
                          kind: str = "error", **fields) -> bool:
        """:meth:`emit_once` for exceptions: the first failure per
        ``key`` is logged in full, repeats only count.  Returns True
        when this call actually emitted."""
        return self.emit_once(key, kind,
                              error=f"{type(exc).__name__}: {exc}",
                              **fields)

    def suppressed_count(self, key: str) -> int:
        """How many times ``key`` fired (including the logged first)."""
        with self._lock:
            return self._once_counts.get(key, 0)

    # reading ------------------------------------------------------------
    def recent(self, n: int = 50, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            out = list(self._ring)
        if kind is not None:
            out = [r for r in out if r.get("kind") == kind]
        return out[-n:]


_LOG = EventLog()


def event_log() -> EventLog:
    """The process-wide event log."""
    return _LOG


def emit(kind: str, /, **fields) -> dict:
    return _LOG.emit(kind, **fields)


def emit_once(key: str, kind: str, **fields) -> bool:
    return _LOG.emit_once(key, kind, **fields)


def recent(n: int = 50, kind: Optional[str] = None) -> List[dict]:
    return _LOG.recent(n, kind)


def configure(cfg: Sequence[ConfigEntry]) -> None:
    _LOG.configure(cfg)


def log_exception_once(key: str, exc: BaseException,
                       kind: str = "error", **fields) -> bool:
    return _LOG.log_exception_once(key, exc, kind, **fields)


def record_drop(sink: str, reason: str) -> None:
    """Count one dropped observability record (telemetry.jsonl uses
    this; the event sink counts its own drops internally)."""
    _LOG.record_drop(sink, reason)
