"""Device-plane telemetry: XLA program costs, compile time, memory.

The fourth observability pillar (doc/observability.md).  The host-side
pillars (registry / spans / events) say what the PROCESS is doing; this
module says what the CHIP is being asked to do — per-program FLOPs and
bytes from XLA's own cost analysis, wall-clock compile time for every
program the trainer / serve cache / loop fine-tuner jits, live and peak
device-memory watermarks where the backend reports them, and sampled
per-step device timing via periodic blocking fences.  All of it lands in
the shared metrics registry, so ``GET /metricsz`` exposes the device
plane next to the host plane:

* ``xla_program_flops{kind,bucket}`` / ``xla_program_bytes{kind,bucket}``
  — estimated FLOPs / bytes accessed of the most recently compiled
  program of that kind and leading data dimension (``bucket``), from
  ``Lowered.cost_analysis()`` (no extra backend compile);
* ``xla_program_compile_seconds{kind,bucket}`` — cold-call wall time of
  that program's first dispatch (trace + backend compile + first run);
* ``xla_compile_seconds_total`` / ``xla_compiles_total`` — cumulative
  backend-compile time and count, process-wide, captured exactly via
  ``jax.monitoring``'s compile-duration events (cache hits from the
  persistent compile cache do not count — they did not compile);
* ``xla_device_memory_bytes{device,stat}`` — live (``bytes_in_use``) and
  peak (``peak_bytes_in_use``) allocator watermarks from
  ``device.memory_stats()``, sampled at scrape time; absent on backends
  that do not report them (CPU);
* ``train_step_device_seconds`` — a histogram of sampled step fences
  (``device_sample_every = N``: every Nth update blocks until the device
  finishes and the wait is observed).  Default off — a fence breaks the
  async dispatch overlap, so it is an opt-in diagnostic.

Instrumentation is wrapper-based and fail-open: :func:`instrument` wraps
a jitted callable; the wrapped call is a straight pass-through except
the FIRST call per argument-shape signature, which is timed (the cold
call) and then re-lowered once for cost analysis.  Any failure inside
the accounting path is event-logged once and disables that wrapper —
telemetry must never take down the program it measures.  With
``device_telemetry = 0`` the wrapper is a single flag check per call.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

from . import events as obs_events
from .registry import registry as obs_registry

__all__ = [
    "configure",
    "enabled",
    "instrument",
    "InstrumentedJit",
    "install_compile_listener",
    "register_memory_collector",
    "maybe_sample_step",
    "mark_kernel_selected",
    "set_train_state_bytes",
    "summary",
    "device_metrics",
    "reset",
]

ConfigEntry = Tuple[str, str]

#: compile-fence buckets (seconds): cold XLA compiles run 10ms-minutes
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0)
#: sampled step-fence buckets (seconds)
STEP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class _State:
    """Module config + lifetime totals (the telemetry.jsonl summary)."""

    def __init__(self) -> None:
        # CXXNET_DEVICE_TELEMETRY=0 is the environment kill switch —
        # reachable without a conf edit (CI bisection, emergency opt-out)
        import os

        self.enabled = os.environ.get(
            "CXXNET_DEVICE_TELEMETRY", "1") != "0"
        self.sample_every = 0
        self.lock = threading.Lock()
        self.programs = 0
        self.flops = 0.0
        self.bytes = 0.0
        self.compiles = 0
        self.compile_seconds = 0.0
        self.cold_call_seconds = 0.0
        self.sampled_steps = 0


_STATE = _State()


class _DeviceMetrics:
    """Lazy registry families for the device plane (shared process-wide)."""

    def __init__(self) -> None:
        reg = obs_registry()
        self.program_flops = reg.gauge(
            "xla_program_flops",
            "Estimated FLOPs of the most recently compiled XLA program "
            "of this kind/bucket (HLO cost analysis).",
            labelnames=("kind", "bucket"),
        )
        self.program_bytes = reg.gauge(
            "xla_program_bytes",
            "Estimated bytes accessed by the most recently compiled XLA "
            "program of this kind/bucket.",
            labelnames=("kind", "bucket"),
        )
        self.program_compile = reg.gauge(
            "xla_program_compile_seconds",
            "Cold-call wall time (trace + compile + first run) of this "
            "kind/bucket's most recent program.",
            labelnames=("kind", "bucket"),
        )
        self.programs = reg.counter(
            "xla_programs_total",
            "Distinct (function, argument shapes) programs instrumented.",
            labelnames=("kind",),
        )
        self.compiles = reg.counter(
            "xla_compiles_total",
            "XLA backend compiles observed process-wide.")
        self.compile_seconds = reg.counter(
            "xla_compile_seconds_total",
            "Cumulative XLA backend-compile wall time, process-wide.")
        self.compile_hist = reg.histogram(
            "xla_backend_compile_seconds",
            "Per-compile backend-compile durations.",
            buckets=COMPILE_BUCKETS,
        )
        self.step_seconds = reg.histogram(
            "train_step_device_seconds",
            "Sampled per-step device fence time "
            "(device_sample_every = N).",
            buckets=STEP_BUCKETS,
        )


_METRICS: Optional[_DeviceMetrics] = None
_METRICS_LOCK = threading.Lock()


def device_metrics() -> _DeviceMetrics:
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            _METRICS = _DeviceMetrics()
        return _METRICS


# ----------------------------------------------------------------------
# config
def configure(cfg: Sequence[ConfigEntry]) -> None:
    """Arm from the ordered config stream (``device_telemetry``,
    ``device_sample_every``); unknown keys ignored."""
    for name, val in cfg:
        if name == "device_telemetry":
            _STATE.enabled = bool(int(val))
        elif name == "device_sample_every":
            _STATE.sample_every = max(0, int(val))
    if _STATE.enabled:
        install_compile_listener()
        register_memory_collector()


def enabled() -> bool:
    return _STATE.enabled


def reset() -> None:
    """Test isolation: restore defaults and zero the lifetime totals
    (registered listeners/collectors stay — they are idempotent)."""
    global _METRICS
    _STATE.enabled = True
    _STATE.sample_every = 0
    with _STATE.lock:
        _STATE.programs = 0
        _STATE.flops = 0.0
        _STATE.bytes = 0.0
        _STATE.compiles = 0
        _STATE.compile_seconds = 0.0
        _STATE.cold_call_seconds = 0.0
        _STATE.sampled_steps = 0
    with _METRICS_LOCK:
        _METRICS = None


# ----------------------------------------------------------------------
# process-wide compile accounting (jax.monitoring)
_LISTENER_INSTALLED = False
_LISTENER_LOCK = threading.Lock()


def _on_event_duration(name: str, duration: float, **_kw) -> None:
    if not name.endswith("backend_compile_duration"):
        return
    try:
        m = device_metrics()
        m.compiles.inc()
        m.compile_seconds.inc(duration)
        m.compile_hist.observe(duration)
        with _STATE.lock:
            _STATE.compiles += 1
            _STATE.compile_seconds += duration
    except Exception:  # noqa: BLE001 - telemetry must never raise
        pass


def install_compile_listener() -> bool:
    """Register the ``jax.monitoring`` duration listener once; every XLA
    backend compile in the process then feeds the compile counters, no
    matter which subsystem triggered it.  Returns True when installed
    (now or previously)."""
    global _LISTENER_INSTALLED
    with _LISTENER_LOCK:
        if _LISTENER_INSTALLED:
            return True
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(
                _on_event_duration)
        except Exception as e:  # noqa: BLE001 - jax too old / absent
            obs_events.log_exception_once(
                "obs.device.listener", e, kind="obs.device_error")
            return False
        _LISTENER_INSTALLED = True
        return True


# ----------------------------------------------------------------------
# device-memory watermarks (scrape-time collector)
_MEM_REGISTERED = False
_MEM_LOCK = threading.Lock()

#: memory_stats keys exported, renamed to a stable label value
_MEM_STATS = (("bytes_in_use", "bytes_in_use"),
              ("peak_bytes_in_use", "peak_bytes_in_use"),
              ("bytes_limit", "bytes_limit"))


def _memory_collector():
    """Collector: ``xla_device_memory_bytes{device,stat}`` samples from
    every addressable device that reports ``memory_stats()``."""
    try:
        import jax

        samples = []
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 - backend-dependent API
                stats = None
            if not stats:
                continue
            dev = f"{d.platform}:{d.id}"
            for key, label in _MEM_STATS:
                v = stats.get(key)
                if v is not None:
                    samples.append(({"device": dev, "stat": label},
                                    float(v)))
        if not samples:
            return []
        return [("xla_device_memory_bytes", "gauge",
                 "Device allocator watermarks from memory_stats() "
                 "(absent on backends that do not report them).",
                 samples)]
    except Exception:  # noqa: BLE001 - scrape must survive
        return []


def register_memory_collector() -> None:
    global _MEM_REGISTERED
    with _MEM_LOCK:
        if _MEM_REGISTERED:
            return
        obs_registry().register_collector(_memory_collector)
        _MEM_REGISTERED = True


# ----------------------------------------------------------------------
# per-program instrumentation
def _shape_key(args) -> tuple:
    """Hashable signature of a call's argument shapes/dtypes — the same
    granularity XLA specializes on.  Cheap: one flatten + a tuple of
    small tuples; non-array leaves key by type."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is None:
            sig.append((type(leaf).__name__, repr(leaf)))
        else:
            sig.append((tuple(shape), str(getattr(leaf, "dtype", "?")),
                        bool(getattr(leaf, "weak_type", False))))
    return (treedef, tuple(sig))


def _cost_of(lowered) -> Tuple[float, float]:
    """(flops, bytes accessed) from a Lowered's cost analysis; handles
    the dict and list-of-dict spellings across jax versions."""
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return 0.0, 0.0
    return (float(ca.get("flops", 0.0) or 0.0),
            float(ca.get("bytes accessed", 0.0) or 0.0))


class InstrumentedJit:
    """Accounting wrapper around one jitted callable.

    Dispatch is untouched — every call goes to the wrapped function, so
    jax's own compilation cache (and the persistent on-disk cache)
    behaves exactly as without the wrapper.  The first call per argument
    signature is additionally timed (the cold call, compile included)
    and the function is re-lowered ONCE for HLO cost analysis (tracing
    only; no second backend compile).  Everything lands in the shared
    registry labeled ``{kind, bucket}`` where ``bucket`` is the leading
    dimension of the designated data argument (the serve cache's
    power-of-two bucket; the trainer's batch size / scan depth).
    """

    __slots__ = ("fn", "kind", "data_arg", "_seen", "_fast", "_lock",
                 "_broken")

    def __init__(self, fn: Callable, kind: str,
                 data_arg: Optional[int] = None) -> None:
        self.fn = fn
        self.kind = kind
        self.data_arg = data_arg
        self._seen: Dict[tuple, bool] = {}
        # warm-path shortcut: the data argument's (shape, dtype) is the
        # only signature dimension that varies call to call in practice,
        # so once a full signature is accounted its data key lands here
        # and steady-state calls skip the full-pytree flatten + lock.
        # Benign miss semantics: a program differing ONLY in a non-data
        # argument's shape (a wider label tensor, say) may skip its own
        # accounting — it still executes correctly through fn.
        self._fast: set = set()
        self._lock = threading.Lock()
        self._broken = False

    # pass through the AOT surface so wrapped fns stay lowerable
    def lower(self, *args, **kw):
        return self.fn.lower(*args, **kw)

    def _bucket(self, args) -> str:
        if self.data_arg is None or self.data_arg >= len(args):
            return ""
        shape = getattr(args[self.data_arg], "shape", None)
        return str(shape[0]) if shape else ""

    def _fast_key(self, args) -> Optional[tuple]:
        if self.data_arg is None or self.data_arg >= len(args):
            return None
        arr = args[self.data_arg]
        shape = getattr(arr, "shape", None)
        if shape is None:
            return None
        return (tuple(shape), str(getattr(arr, "dtype", "")))

    def __call__(self, *args):
        if not _STATE.enabled or self._broken:
            return self.fn(*args)
        fk = self._fast_key(args)
        if fk is not None and fk in self._fast:
            return self.fn(*args)
        try:
            key = _shape_key(args)
        except Exception as e:  # noqa: BLE001 - fail open, once
            self._broken = True
            obs_events.log_exception_once(
                f"obs.device.key:{self.kind}", e, kind="obs.device_error",
                program=self.kind)
            return self.fn(*args)
        with self._lock:
            fresh = key not in self._seen
            if fresh:
                # mark before the call: a concurrent caller with the
                # same shapes must not double-account the program
                self._seen[key] = True
        if not fresh:
            if fk is not None:
                self._fast.add(fk)
            return self.fn(*args)
        # ALL C++-side accounting runs BEFORE the call: lowering after
        # it would re-trace over donated (deleted) argument buffers,
        # and HLO cost analysis after it runs concurrently with the
        # program's own first, async-dispatched execution — both were
        # observed as rare segfaults on the CPU backend.  Lowering and
        # cost analysis are abstract (avals and HLO only, no buffers),
        # so running them first costs one extra trace per program and
        # nothing else; everything after the call is pure-Python
        # metric/event writes.
        cost = None
        try:
            cost = _cost_of(self.fn.lower(*args))
        except Exception as e:  # noqa: BLE001 - accounting is best-effort
            obs_events.log_exception_once(
                f"obs.device.lower:{self.kind}", e,
                kind="obs.device_error", program=self.kind)
        bucket = self._bucket(args)
        t0 = time.perf_counter()
        out = self.fn(*args)
        cold_s = time.perf_counter() - t0
        if cost is not None:
            try:
                self._account(cost, bucket, cold_s)
            except Exception as e:  # noqa: BLE001 - best-effort
                obs_events.log_exception_once(
                    f"obs.device.account:{self.kind}", e,
                    kind="obs.device_error", program=self.kind)
        return out

    def _account(self, cost: Tuple[float, float], bucket: str,
                 cold_s: float) -> None:
        flops, nbytes = cost
        m = device_metrics()
        m.program_flops.labels(kind=self.kind, bucket=bucket).set(flops)
        m.program_bytes.labels(kind=self.kind, bucket=bucket).set(nbytes)
        m.program_compile.labels(kind=self.kind, bucket=bucket).set(cold_s)
        m.programs.labels(kind=self.kind).inc()
        with _STATE.lock:
            _STATE.programs += 1
            _STATE.flops += flops
            _STATE.bytes += nbytes
            _STATE.cold_call_seconds += cold_s
        obs_events.emit("device.program", kind=self.kind, bucket=bucket,
                        flops=flops, bytes=nbytes, cold_call_s=cold_s)


def instrument(fn: Callable, kind: str,
               data_arg: Optional[int] = None) -> Callable:
    """Wrap a jitted callable for device accounting (see
    :class:`InstrumentedJit`); also makes sure the process-wide compile
    listener is armed.  Returns ``fn`` unchanged when telemetry is
    disabled at wrap time — the zero-cost path."""
    if not _STATE.enabled:
        return fn
    install_compile_listener()
    register_memory_collector()
    return InstrumentedJit(fn, kind, data_arg=data_arg)


# ----------------------------------------------------------------------
# kernel-library selection (ops/kernels) — which Pallas kernels the
# selector activated, per backend, made scrapeable next to the
# per-kernel xla_program_*{kind="kernel_<name>"} families the A/B
# driver's instrumented standalone launches record
def mark_kernel_selected(name: str, backend: str, active: bool) -> None:
    """Publish ``kernel_selected{name,backend}`` (1 = the Pallas path
    runs, 0 = selected-off/rejected).  Called by the kernel selector at
    every dispatch decision (trace time — cheap)."""
    try:
        obs_registry().gauge(
            "kernel_selected",
            "Kernel-library selection state: 1 when the named Pallas "
            "kernel is active on this backend (kernel_lib conf + "
            "recorded verdicts + capability probe), else 0.",
            labelnames=("name", "backend"),
        ).labels(name=name, backend=backend).set(1.0 if active else 0.0)
    except Exception:  # noqa: BLE001 - telemetry must never raise
        pass


# ----------------------------------------------------------------------
# train-state residency (the ZeRO memory win, made scrapeable)
def set_train_state_bytes(per_device, total: float) -> None:
    """Publish the trainer's state-residency gauges.

    ``train_state_shard_bytes{device}`` — bytes of params + updater
    state addressable on each local device after placement (the
    ``xla_device_memory_bytes``-adjacent number CPU backends cannot
    report from ``memory_stats()``); ``train_state_total_bytes`` — what
    ONE full replica costs.  On an N-way ZeRO mesh the per-device gauge
    sits at ~total/N; per-device == total is the replicated baseline.
    Called by ``NetTrainer`` whenever state is (re)placed — init, load,
    copy — so a resume onto a different mesh re-reports immediately.
    """
    try:
        reg = obs_registry()
        g = reg.gauge(
            "train_state_shard_bytes",
            "Params + updater-state bytes resident per device "
            "(~1/N of the replicated total on a ZeRO mesh).",
            labelnames=("device",),
        )
        for dev, nbytes in sorted(per_device.items()):
            g.labels(device=dev).set(float(nbytes))
        reg.gauge(
            "train_state_total_bytes",
            "Bytes one full (replicated) copy of params + updater "
            "state costs — the ZeRO memory-win denominator.",
        ).set(float(total))
    except Exception:  # noqa: BLE001 - telemetry must never raise
        pass


# ----------------------------------------------------------------------
# sampled step fences
def maybe_sample_step(step: int, sync_fn: Callable[[], None]) -> bool:
    """Every ``device_sample_every``-th step (and only when the key is
    set), block on ``sync_fn`` and observe the wait as
    ``train_step_device_seconds``.  Off (the default) this is one int
    compare — the hot-path cost the <1% bar allows."""
    n = _STATE.sample_every
    if n <= 0 or (step % n) != 0:
        return False
    t0 = time.perf_counter()
    try:
        sync_fn()
    finally:
        dt = time.perf_counter() - t0
        try:
            device_metrics().step_seconds.observe(dt)
            with _STATE.lock:
                _STATE.sampled_steps += 1
        except Exception:  # noqa: BLE001 - telemetry must never raise
            pass
    return True


# ----------------------------------------------------------------------
def summary() -> Dict[str, float]:
    """Lifetime totals for the per-round telemetry record (cli.py):
    programs instrumented, estimated FLOPs/bytes across them, backend
    compiles and their cumulative seconds, sampled fences."""
    with _STATE.lock:
        return {
            "programs": _STATE.programs,
            "flops": _STATE.flops,
            "bytes": _STATE.bytes,
            "compiles": _STATE.compiles,
            "compile_seconds": round(_STATE.compile_seconds, 6),
            "cold_call_seconds": round(_STATE.cold_call_seconds, 6),
            "sampled_steps": _STATE.sampled_steps,
        }
