"""Process-wide metrics registry: Counters, Gauges, bucketed Histograms.

The unified metrics pillar of the observability subsystem
(doc/observability.md).  Before this, three disconnected stats systems
grew piecemeal — ``utils/profiler.py`` (StepTimer / PercentileTracker /
PipelineStats), ``serve/metrics.py`` (ServingStats) and ad-hoc prints in
the trainer round loop — none machine-readable.  This module is the
shared substrate they now sit on:

* :class:`MetricsRegistry` — thread-safe, name-keyed registry of
  labeled metrics with get-or-create semantics (two subsystems asking
  for the same counter share it) and pluggable *collectors* for state
  that is cheaper to snapshot at scrape time than to double-write
  (``PipelineStats`` exports through one).
* :class:`Counter` / :class:`Gauge` / :class:`Histogram` — the
  Prometheus metric kinds.  Histograms are cumulative-bucket
  (``le``-labeled) with ``_sum``/``_count``, so rate/latency SLOs can
  be computed server-side by any Prometheus-compatible scraper.
* :class:`PercentileWindow` — the sliding-window percentile estimator
  that ``utils.profiler.PercentileTracker`` is now a facade over: exact
  window percentiles for human-facing ``/statsz`` output, complementing
  (not replacing) the bucketed histograms ``/metricsz`` exposes.
* :meth:`MetricsRegistry.render_prometheus` — the text exposition
  (version 0.0.4) behind the serve front-end's ``GET /metricsz``.

Everything here is stdlib-only and import-cheap: the registry is
touched from hot paths (request accounting, per-stage pipeline timers)
and from module import time across io/, serve/ and utils/.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "PercentileWindow",
    "MetricsRegistry",
    "registry",
    "DEFAULT_BUCKETS",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Prometheus' classic latency buckets (seconds) — wide enough for both
#: sub-ms device dispatch and multi-second cold compiles.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

LabelValues = Tuple[str, ...]


def escape_label_value(value: str) -> str:
    """Escape a label value per the text exposition format: backslash,
    double-quote and newline must be escaped, everything else is raw."""
    return (str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(text: str) -> str:
    """HELP lines escape backslash and newline (but not quotes)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v: float) -> str:
    """Render a sample value: integers without a trailing ``.0``,
    non-finite values as Prometheus spells them."""
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_to_text(names: Sequence[str], values: LabelValues) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{escape_label_value(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Metric:
    """Base: one named family with fixed label names and per-labelset
    children.  ``labels(...)`` returns the child for one labelset;
    the no-label child is the metric itself (``inc``/``set``/``observe``
    directly on the family)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"bad label name {ln!r} for {name}")
        if len(set(labelnames)) != len(tuple(labelnames)):
            raise ValueError(f"duplicate label names for {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[LabelValues, object] = {}

    # child management ---------------------------------------------------
    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass labels positionally OR by name")
            try:
                values = tuple(kv[n] for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e.args[0]!r}"
                ) from None
            if len(kv) != len(self.labelnames):
                raise ValueError(
                    f"{self.name}: unexpected labels "
                    f"{sorted(set(kv) - set(self.labelnames))}"
                )
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes {len(self.labelnames)} label value(s), "
                f"got {len(values)}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                self._children[values] = child
        return child

    def _default_child(self):
        """The ()-labelset child for label-less metrics."""
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; use .labels()"
            )
        return self.labels()

    def _make_child(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def children(self) -> List[Tuple[LabelValues, object]]:
        with self._lock:
            return sorted(self._children.items())

    # exposition ---------------------------------------------------------
    def samples(self) -> List[Tuple[str, str, float]]:
        """``[(suffixed_name, rendered_labels, value), ...]``."""
        raise NotImplementedError


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Counter(_Metric):
    """Monotonically increasing count (name it ``*_total``)."""

    kind = "counter"

    def _make_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value

    def samples(self):
        return [
            (self.name, _labels_to_text(self.labelnames, lv), c.value)
            for lv, c in self.children()
        ]


class _GaugeChild:
    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self._fn = None

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount
            self._fn = None

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Sample ``fn()`` at scrape time (live gauges: queue depth).
        A raising ``fn`` makes the sample *absent* — scrape errors are
        the caller's to count (see ServingStats.queue_depth_errors);
        a sentinel value would poison dashboards silently."""
        with self._lock:
            self._fn = fn

    def get(self) -> float:
        """Current value; raises whatever a bound function raises."""
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        return float(fn())


class Gauge(_Metric):
    """A value that can go up and down, or track a live callable."""

    kind = "gauge"

    def _make_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._default_child().set_function(fn)

    def get(self) -> float:
        return self._default_child().get()

    def samples(self):
        out = []
        for lv, c in self.children():
            try:
                v = c.get()
            except Exception:  # noqa: BLE001 - absent sample, not a 500
                continue
            out.append((self.name, _labels_to_text(self.labelnames, lv), v))
        return out


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds: Tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        i = 0
        for i, b in enumerate(self._bounds):  # noqa: B007
            if v <= b:
                break
        else:
            i = len(self._bounds)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._count


class Histogram(_Metric):
    """Cumulative-bucket histogram (``le`` buckets + ``_sum``/``_count``)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError(f"{name}: bucket bounds must be increasing")
        if "le" in labelnames:
            raise ValueError(f"{name}: 'le' is reserved for buckets")
        self.buckets = bounds
        super().__init__(name, help, labelnames)

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    def samples(self):
        out = []
        for lv, c in self.children():
            counts, total, count = c.snapshot()
            acc = 0
            for b, n in zip(self.buckets, counts):
                acc += n
                out.append((
                    self.name + "_bucket",
                    _labels_to_text(
                        self.labelnames + ("le",), lv + (format_value(b),)
                    ),
                    acc,
                ))
            out.append((
                self.name + "_bucket",
                _labels_to_text(self.labelnames + ("le",), lv + ("+Inf",)),
                count,
            ))
            base = _labels_to_text(self.labelnames, lv)
            out.append((self.name + "_sum", base, total))
            out.append((self.name + "_count", base, count))
        return out


class PercentileWindow:
    """Thread-safe sliding-window percentile estimator.

    Keeps the newest ``window`` samples in a ring buffer; percentiles
    AND the window mean are computed over that window on demand, while
    lifetime ``count``/``total`` accumulate forever.  This is the shared
    primitive behind ``utils.profiler.PercentileTracker`` (serving
    latency, per-stage pipeline timers): exact small-window percentiles
    for human-facing snapshots, where a bucketed :class:`Histogram`
    would quantize."""

    def __init__(self, window: int = 2048) -> None:
        self._window = max(1, int(window))
        self._buf: List[float] = []
        self._pos = 0
        self._count = 0
        self._total = 0.0
        self._lock = threading.Lock()

    def add(self, value: float) -> None:
        with self._lock:
            if len(self._buf) < self._window:
                self._buf.append(float(value))
            else:
                self._buf[self._pos] = float(value)
                self._pos = (self._pos + 1) % self._window
            self._count += 1
            self._total += float(value)

    @property
    def count(self) -> int:
        return self._count

    @staticmethod
    def _percentiles_of(snap: List[float],
                        qs: Sequence[float]) -> Dict[str, float]:
        n = len(snap)
        out = {}
        for q in qs:
            idx = min(n - 1, max(0, int(round(q / 100.0 * n)) - 1))
            out[f"p{q:g}"] = snap[idx]
        return out

    def percentiles(
        self, qs: Sequence[float] = (50.0, 95.0, 99.0)
    ) -> Dict[str, float]:
        """``{"p50": ..., "p95": ...}`` over the current window (empty
        dict when no samples); nearest-rank on the sorted window."""
        with self._lock:
            snap = sorted(self._buf)
        if not snap:
            return {}
        return self._percentiles_of(snap, qs)

    def summary(self, scale: float = 1.0) -> Dict[str, float]:
        """count / mean / lifetime_mean / p50 / p95 / p99, each value
        multiplied by ``scale`` (pass 1e3 to report seconds as ms).

        ``mean`` and the percentiles cover the SAME sliding window, so
        they are mutually consistent; ``lifetime_mean`` (with ``count``)
        is the all-time average — the two diverge exactly when recent
        behavior shifted, which is the signal worth alerting on."""
        with self._lock:
            count, total = self._count, self._total
            snap = sorted(self._buf)
        if not count:
            return {"count": 0}
        out = {
            "count": float(count),
            "mean": sum(snap) / len(snap) * scale,
            "lifetime_mean": total / count * scale,
        }
        out.update(
            {k: v * scale
             for k, v in self._percentiles_of(snap, (50.0, 95.0, 99.0)).items()}
        )
        return out


#: A collector returns an iterable of ``(name, kind, help, samples)``
#: families at scrape time; samples are ``(labels_dict, value)`` pairs.
CollectorFn = Callable[[], Iterable[Tuple[str, str, str,
                                          List[Tuple[Dict[str, str], float]]]]]


class MetricsRegistry:
    """Thread-safe, name-keyed registry of metric families.

    ``counter()`` / ``gauge()`` / ``histogram()`` are get-or-create:
    asking twice for the same name returns the same object, and asking
    with a conflicting kind / label set / bucket layout raises — two
    subsystems cannot silently fork one metric.  ``register_collector``
    plugs in scrape-time exporters for state that already has its own
    locking (PipelineStats)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[CollectorFn] = []

    # get-or-create ------------------------------------------------------
    def _get_or_make(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, labelnames=labelnames, **kw)
                self._metrics[name] = m
                return m
        if type(m) is not cls:
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}"
            )
        if m.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{m.labelnames}, not {tuple(labelnames)}"
            )
        if kw.get("buckets") is not None and tuple(
                sorted(float(b) for b in kw["buckets"])) != m.buckets:
            raise ValueError(
                f"histogram {name!r} already registered with different "
                "buckets"
            )
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(Histogram, name, help, labelnames,
                                 buckets=buckets)

    def register_collector(self, fn: CollectorFn) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn: CollectorFn) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self) -> None:
        """Drop every metric and collector (test isolation only — live
        code holds references to registered metrics, never re-asks)."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()

    # exposition ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """``{family: {"name{labels}": value}}`` — the machine-readable
        twin of :meth:`render_prometheus` for in-process consumers,
        including collector-exported families."""
        out: Dict[str, Dict[str, float]] = {}
        for m in self.metrics():
            out[m.name] = {
                f"{n}{labels}": float(v) for n, labels, v in m.samples()
            }
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                families = list(fn())
            except Exception:  # noqa: BLE001 - same policy as render
                continue
            for name, _kind, _help, samples in families:
                fam = out.setdefault(name, {})
                for labelmap, value in samples:
                    names = tuple(sorted(labelmap))
                    txt = _labels_to_text(
                        names, tuple(str(labelmap[k]) for k in names)
                    )
                    fam[f"{name}{txt}"] = float(value)
        return out

    def render_prometheus(self) -> str:
        """Text exposition format 0.0.4 (the ``/metricsz`` body)."""
        lines: List[str] = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, labels, value in m.samples():
                lines.append(f"{name}{labels} {format_value(value)}")
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                families = list(fn())
            except Exception:  # noqa: BLE001 - one bad collector must
                continue       # not take down the whole scrape
            for name, kind, help, samples in families:
                if not _NAME_RE.match(name):
                    continue
                if help:
                    lines.append(f"# HELP {name} {escape_help(help)}")
                lines.append(f"# TYPE {name} {kind}")
                for labelmap, value in samples:
                    names = tuple(sorted(labelmap))
                    txt = _labels_to_text(
                        names, tuple(str(labelmap[k]) for k in names)
                    )
                    lines.append(f"{name}{txt} {format_value(value)}")
        return "\n".join(lines) + "\n"


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry (what ``/metricsz`` renders)."""
    return _REGISTRY
