"""Declarative alert evaluator over the metrics registry.

PR 5 made everything measurable; nothing ever *alerted* on the
measurements.  This module closes that gap with config-driven threshold
rules evaluated against registry snapshots on a background thread:

    alert = <name>:<metric>:<op>:<threshold>[:<for_s>]
    alert = slow_predict:serve_request_latency_seconds_mean:>:0.25:10
    alert = shedding:serve_request_outcomes_rate{outcome="shed"}:>:0
    alert = feedback_backlog:loop_feedback_pending_records:>:5000

* ``metric`` names a registry sample: a family (every labelset of it is
  a candidate; the rule fires if ANY crosses) or one exact sample
  (``family{label="v"}``).  Two **derived** series exist per evaluation
  interval so rules can clear again: every counter sample ``X_total``
  also appears as ``X_rate`` (per-second delta since the previous
  evaluation) and every histogram ``Y`` as ``Y_mean`` (interval
  Δsum/Δcount — absent when no new observations landed, so a latency
  rule CLEARS when traffic stops or gets fast, where the lifetime mean
  never recovers).
* ``op`` is one of ``> < >= <=`` (spellings ``gt lt ge le`` accepted
  for shell-quoting comfort).
* ``for_s`` debounces: the condition must hold continuously that long
  before the rule transitions to ``firing`` (default 0: immediate).

Transitions emit structured events (``alert.firing`` /
``alert.cleared``), flip the ``obs_alerts_firing{name}`` gauge, and
count in ``obs_alert_transitions_total{name,to}``.  The serve front-end
exposes :meth:`AlertEvaluator.status` as ``GET /alertz`` and the engine
degrades ``/healthz`` while anything fires.  Evaluation is pull-only —
a broken rule or scrape can never touch the hot paths it watches.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import events as obs_events
from .registry import registry as obs_registry

__all__ = [
    "AlertRule",
    "AlertEvaluator",
    "evaluator",
    "configure",
    "reset",
    "parse_rule",
]

ConfigEntry = Tuple[str, str]

_OPS = {
    ">": lambda v, t: v > t,
    "<": lambda v, t: v < t,
    ">=": lambda v, t: v >= t,
    "<=": lambda v, t: v <= t,
    "gt": lambda v, t: v > t,
    "lt": lambda v, t: v < t,
    "ge": lambda v, t: v >= t,
    "le": lambda v, t: v <= t,
}
_OP_CANON = {"gt": ">", "lt": "<", "ge": ">=", "le": "<="}

_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_.\-]*$")


class AlertRule:
    """One parsed threshold rule (immutable config; mutable state lives
    in the evaluator)."""

    __slots__ = ("name", "metric", "op", "threshold", "for_s")

    def __init__(self, name: str, metric: str, op: str,
                 threshold: float, for_s: float = 0.0) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"alert: bad rule name {name!r}")
        if op not in _OPS:
            raise ValueError(
                f"alert {name}: op must be one of > < >= <= "
                f"(or gt/lt/ge/le), got {op!r}")
        if not metric:
            raise ValueError(f"alert {name}: empty metric")
        self.name = name
        self.metric = metric
        self.op = _OP_CANON.get(op, op)
        self.threshold = float(threshold)
        self.for_s = max(0.0, float(for_s))

    def crossed(self, value: float) -> bool:
        return _OPS[self.op](value, self.threshold)

    def to_dict(self) -> Dict[str, object]:
        return {"name": self.name, "metric": self.metric, "op": self.op,
                "threshold": self.threshold, "for_s": self.for_s}


def parse_rule(spec: str) -> AlertRule:
    """``name:metric:op:threshold[:for_s]`` → :class:`AlertRule`.

    The metric token may itself contain ``{label="v"}`` selectors whose
    VALUES contain colons (device labels like ``device="tpu:0"``), so
    the spec is split from the outside in: the rule name from the left,
    op/threshold/for_s from the right, everything between is the
    metric.  The trailing fields' grammar (op symbol + numbers) is
    unambiguous, so a metric can never be misparsed as them."""
    name, sep, rest = spec.partition(":")
    if not sep:
        raise ValueError(
            f"alert={spec!r}: want name:metric:op:threshold[:for_s]")
    # try the 5-field form first: ...:op:threshold:for_s
    for n_tail in (3, 2):
        parts = rest.rsplit(":", n_tail)
        if len(parts) != n_tail + 1:
            continue
        metric, op, thresh = parts[0], parts[1], parts[2]
        for_s = parts[3] if n_tail == 3 else "0"
        if op not in _OPS:
            continue
        try:
            return AlertRule(name, metric, op, float(thresh),
                             float(for_s))
        except ValueError:
            continue
    raise ValueError(
        f"alert={spec!r}: want name:metric:op:threshold[:for_s] "
        "(op one of > < >= <= / gt lt ge le, numeric threshold)")


class _RuleState:
    __slots__ = ("state", "value", "cross_since", "changed_ts")

    def __init__(self) -> None:
        self.state = "ok"          # ok | pending | firing
        self.value: Optional[float] = None
        self.cross_since: Optional[float] = None
        self.changed_ts: Optional[float] = None


class AlertEvaluator:
    """Threshold rules over periodic registry snapshots.

    Drive it manually with :meth:`evaluate_once` (tests, one-shot
    tools) or as a daemon thread via :meth:`start` — the CLI starts it
    whenever the conf carries ``alert=`` rules, for every task."""

    def __init__(self, registry=None, period_s: float = 2.0) -> None:
        self._registry = registry
        self.period_s = float(period_s)
        self._lock = threading.Lock()
        # serializes whole evaluation passes: transitions mutate rule
        # state and emit events, so two concurrent evaluate_once calls
        # (the thread + a manual driver, or parallel scrapers in tests)
        # must not interleave and double-fire
        self._eval_lock = threading.Lock()
        self._rules: List[AlertRule] = []
        self._states: Dict[str, _RuleState] = {}
        self._prev: Optional[Dict[str, float]] = None
        self._prev_ts: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.evaluations = 0
        self._gauge = None
        self._transitions = None

    # ------------------------------------------------------------------
    def _reg(self):
        return self._registry if self._registry is not None \
            else obs_registry()

    def _metrics(self):
        if self._gauge is None:
            reg = self._reg()
            self._gauge = reg.gauge(
                "obs_alerts_firing",
                "1 while the named alert rule is firing.",
                labelnames=("name",))
            self._transitions = reg.counter(
                "obs_alert_transitions_total",
                "Alert state transitions, by rule and target state.",
                labelnames=("name", "to"))
        return self._gauge, self._transitions

    def add_rule(self, rule: AlertRule) -> None:
        with self._lock:
            if any(r.name == rule.name for r in self._rules):
                raise ValueError(f"alert: duplicate rule {rule.name!r}")
            self._rules.append(rule)
            self._states[rule.name] = _RuleState()
        gauge, _ = self._metrics()
        gauge.labels(name=rule.name).set(0)

    def configure(self, cfg: Sequence[ConfigEntry]) -> int:
        """Consume ``alert=`` specs and ``alert_period_s`` from the
        ordered config stream; returns how many rules were added.
        A re-parsed spec whose name already exists is ignored (the CLI
        configures once; tests may configure twice)."""
        added = 0
        for name, val in cfg:
            if name == "alert_period_s":
                self.period_s = max(0.05, float(val))
            elif name == "alert":
                rule = parse_rule(val)
                with self._lock:
                    dup = any(r.name == rule.name for r in self._rules)
                if dup:
                    continue
                self.add_rule(rule)
                added += 1
        return added

    def rules(self) -> List[AlertRule]:
        with self._lock:
            return list(self._rules)

    # ------------------------------------------------------------------
    # sample space
    @staticmethod
    def _flatten(snapshot: Dict[str, Dict[str, float]]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for fam in snapshot.values():
            out.update(fam)
        return out

    @staticmethod
    def _derive(cur: Dict[str, float], prev: Optional[Dict[str, float]],
                dt: float) -> Dict[str, float]:
        """Interval-delta series: ``X_total`` → ``X_rate`` (per second),
        histogram ``Y_sum``/``Y_count`` pairs → ``Y_mean`` (mean of the
        observations that landed THIS interval; absent when none did)."""
        derived: Dict[str, float] = {}
        if prev is None or dt <= 0:
            return derived
        for key, v in cur.items():
            name, _, labels = key.partition("{")
            if name.endswith("_total"):
                d = v - prev.get(key, 0.0)
                if d < 0:
                    d = v  # registry was reset between evaluations
                rk = name[:-len("_total")] + "_rate"
                derived[rk + ("{" + labels if labels else "")] = d / dt
            elif name.endswith("_sum"):
                ck = name[:-len("_sum")] + "_count" + (
                    "{" + labels if labels else "")
                if ck not in cur:
                    continue
                dsum = v - prev.get(key, 0.0)
                dcount = cur[ck] - prev.get(ck, 0.0)
                if dcount > 0:
                    mk = name[:-len("_sum")] + "_mean"
                    derived[mk + ("{" + labels if labels else "")] = \
                        dsum / dcount
        return derived

    @staticmethod
    def _match(metric: str, samples: Dict[str, float]) -> List[float]:
        """Values the rule's metric selector matches: the exact sample,
        or every labelset of a bare family name."""
        if metric in samples:
            return [samples[metric]]
        prefix = metric + "{"
        return [v for k, v in samples.items() if k.startswith(prefix)]

    # ------------------------------------------------------------------
    def evaluate_once(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass; returns the transition events emitted
        (empty when nothing changed state).  Passes are serialized —
        concurrent callers queue rather than double-firing transitions."""
        with self._eval_lock:
            return self._evaluate_locked(now)

    def _evaluate_locked(self, now: Optional[float]) -> List[dict]:
        now = time.monotonic() if now is None else now
        try:
            cur = self._flatten(self._reg().snapshot())
        except Exception as e:  # noqa: BLE001 - a bad collector must not
            obs_events.log_exception_once(   # kill the evaluator thread
                "obs.alerts.snapshot", e, kind="obs.alert_error")
            return []
        with self._lock:
            prev, prev_ts = self._prev, self._prev_ts
            self._prev, self._prev_ts = cur, now
            rules = list(self._rules)
            self.evaluations += 1
        samples = dict(cur)
        samples.update(self._derive(
            cur, prev, (now - prev_ts) if prev_ts is not None else 0.0))
        gauge, transitions = self._metrics()
        emitted: List[dict] = []
        for rule in rules:
            st = self._states[rule.name]
            values = self._match(rule.metric, samples)
            crossing = [v for v in values if rule.crossed(v)]
            if crossing:
                # report the worst offender for the rule's direction
                worst = (max if rule.op.startswith(">") else min)(crossing)
                st.value = worst
                if st.cross_since is None:
                    st.cross_since = now
                if (st.state != "firing"
                        and now - st.cross_since >= rule.for_s):
                    st.state = "firing"
                    st.changed_ts = time.time()
                    gauge.labels(name=rule.name).set(1)
                    transitions.labels(name=rule.name, to="firing").inc()
                    emitted.append(obs_events.emit(
                        "alert.firing", name=rule.name,
                        metric=rule.metric, op=rule.op,
                        threshold=rule.threshold, value=worst,
                        for_s=rule.for_s))
                elif st.state == "ok":
                    st.state = "pending"
            else:
                st.value = (max(values) if values else None)
                st.cross_since = None
                if st.state == "firing":
                    st.state = "ok"
                    st.changed_ts = time.time()
                    gauge.labels(name=rule.name).set(0)
                    transitions.labels(name=rule.name, to="cleared").inc()
                    emitted.append(obs_events.emit(
                        "alert.cleared", name=rule.name,
                        metric=rule.metric, value=st.value))
                elif st.state == "pending":
                    st.state = "ok"
        return emitted

    def firing(self) -> List[str]:
        """Names of the rules currently firing (the /healthz detail)."""
        with self._lock:
            return sorted(n for n, st in self._states.items()
                          if st.state == "firing")

    def status(self) -> Dict[str, object]:
        """The ``GET /alertz`` body: every configured rule with its
        live state and last-seen value."""
        with self._lock:
            rules = list(self._rules)
            out_rules = []
            for r in rules:
                st = self._states[r.name]
                d = r.to_dict()
                d.update({
                    "state": st.state,
                    "value": st.value,
                    "since": st.changed_ts,
                })
                out_rules.append(d)
            return {
                "period_s": self.period_s,
                "evaluations": self.evaluations,
                "running": self._thread is not None
                and self._thread.is_alive(),
                "rules": out_rules,
                "firing": sorted(r["name"] for r in out_rules
                                 if r["state"] == "firing"),
            }

    # ------------------------------------------------------------------
    def start(self) -> "AlertEvaluator":
        """Start the background evaluation thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="cxxnet-obs-alerts", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            self.evaluate_once()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        self._thread = None


_EVALUATOR: Optional[AlertEvaluator] = None
_EVALUATOR_LOCK = threading.Lock()


def evaluator() -> AlertEvaluator:
    """The process-wide evaluator (what /alertz and /healthz read)."""
    global _EVALUATOR
    with _EVALUATOR_LOCK:
        if _EVALUATOR is None:
            _EVALUATOR = AlertEvaluator()
        return _EVALUATOR


def configure(cfg: Sequence[ConfigEntry]) -> None:
    """Arm the process-wide evaluator from the config stream and start
    its thread when any rules exist (no rules → no thread)."""
    ev = evaluator()
    ev.configure(cfg)
    if ev.rules():
        ev.start()


def reset() -> None:
    """Test isolation: stop the thread and drop the singleton."""
    global _EVALUATOR
    with _EVALUATOR_LOCK:
        ev, _EVALUATOR = _EVALUATOR, None
    if ev is not None:
        ev.stop()
