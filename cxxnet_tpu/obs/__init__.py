"""Unified observability subsystem: metrics, spans, structured events.

Three pillars (doc/observability.md), all stdlib-only and safe to import
from any layer:

* :mod:`~cxxnet_tpu.obs.registry` — process-wide
  :class:`~cxxnet_tpu.obs.registry.MetricsRegistry` of labeled Counters
  / Gauges / bucketed Histograms, rendered as Prometheus text exposition
  by the serve front-end's ``GET /metricsz``;
* :mod:`~cxxnet_tpu.obs.trace` — context-manager host spans with
  thread-local parent tracking and a bounded ring, exported as Chrome
  trace-event JSON (``trace_dir`` / ``trace_steps`` config keys);
* :mod:`~cxxnet_tpu.obs.events` — a rotating structured JSONL event log
  for lifecycle facts (``event_log`` / ``event_log_max_bytes`` /
  ``event_log_backups``), with an always-on in-memory ring;
* :mod:`~cxxnet_tpu.obs.device` — device-plane telemetry: per-program
  XLA FLOPs/bytes, cumulative compile seconds, device-memory
  watermarks, sampled step fences (``device_telemetry`` /
  ``device_sample_every``);
* :mod:`~cxxnet_tpu.obs.alerts` — declarative threshold alerts over
  registry snapshots (``alert=<name>:<metric>:<op>:<threshold>[:for_s]``
  / ``alert_period_s``), surfaced at ``GET /alertz`` and in
  ``/healthz``.

:func:`configure` routes one ordered config stream to every pillar —
the CLI calls it once at startup, right after the fault injector.
"""

from __future__ import annotations

from typing import Sequence, Tuple

from . import alerts as alerts
from . import device as device
from . import events as events
from . import trace as trace
from .events import emit, event_log, log_exception_once, recent
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    PercentileWindow,
    registry,
)
from .trace import span, tracer

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "PercentileWindow",
    "registry",
    "tracer",
    "span",
    "alerts",
    "device",
    "events",
    "trace",
    "event_log",
    "emit",
    "recent",
    "log_exception_once",
    "configure",
]

ConfigEntry = Tuple[str, str]


def configure(cfg: Sequence[ConfigEntry]) -> None:
    """Arm every pillar from one ordered config stream (idempotent;
    unknown keys ignored — the whole framework's config discipline)."""
    trace.configure(cfg)
    events.configure(cfg)
    device.configure(cfg)
    alerts.configure(cfg)
