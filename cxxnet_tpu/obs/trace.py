"""Lightweight span tracing: context-manager spans → Chrome trace JSON.

The second observability pillar (doc/observability.md).  ``jax.profiler``
(``profile=1`` in ``utils/profiler.py``) answers "what is the DEVICE
doing" with xplane protos; these spans answer "what is the HOST doing"
— checkpoint writes, batch coalescing, round phases — at near-zero cost
and with no heavyweight viewer: the export is Chrome trace-event JSON,
loadable in ``chrome://tracing`` / Perfetto next to an XLA trace.

* :func:`span` — a context manager; nesting is tracked per thread
  (thread-local parent stack), so a span records its parent id and the
  viewer shows host call trees per thread.
* completed spans land in a **bounded ring** (oldest evicted) — tracing
  left on in a long service costs a fixed few hundred KB, never an
  unbounded buffer.
* config keys (via :func:`configure`): ``trace_dir`` enables tracing
  and names the output directory; ``trace_steps`` (default 50) sizes
  the train-loop capture window — the round loop calls :func:`step`
  once per training step and the window's spans are flushed to
  ``<trace_dir>/host_trace_<start>-<end>.json`` when it closes;
  ``trace_ring`` (default 4096) bounds the ring.

When tracing is disabled (the default), :func:`span` returns a shared
no-op context manager — one attribute load and two no-op calls on the
hot path, no allocation.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Span", "Tracer", "tracer", "span", "configure", "step"]

ConfigEntry = Tuple[str, str]


class Span:
    """One completed span (immutable once recorded)."""

    __slots__ = ("name", "cat", "start_us", "dur_us", "tid", "thread_name",
                 "span_id", "parent_id", "args")

    def __init__(self, name, cat, start_us, dur_us, tid, thread_name,
                 span_id, parent_id, args) -> None:
        self.name = name
        self.cat = cat
        self.start_us = start_us
        self.dur_us = dur_us
        self.tid = tid
        self.thread_name = thread_name
        self.span_id = span_id
        self.parent_id = parent_id
        self.args = args

    def to_event(self, pid: int) -> Dict[str, object]:
        from .events import _jsonable

        # span args are caller-supplied (set(shape=np.int64(...)) is
        # legal API use) — coerce so export can never throw mid-train
        args = {k: _jsonable(v) for k, v in (self.args or {}).items()}
        args["span_id"] = self.span_id
        if self.parent_id is not None:
            args["parent_id"] = self.parent_id
        return {
            "name": self.name,
            "cat": self.cat or "host",
            "ph": "X",
            "ts": self.start_us,
            "dur": self.dur_us,
            "pid": pid,
            "tid": self.tid,
            "args": args,
        }


class _NopSpan:
    """Shared do-nothing context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self) -> "_NopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def set(self, **args) -> None:
        return None


_NOP = _NopSpan()


class _LiveSpan:
    """An open span; records itself into the tracer ring on exit."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0",
                 "span_id", "parent_id")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Optional[dict]) -> None:
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.span_id = None
        self.parent_id = None
        self._t0 = 0.0

    def set(self, **args) -> None:
        """Attach key/values to the span after entry (results, counts)."""
        if self.args is None:
            self.args = {}
        self.args.update(args)

    def __enter__(self) -> "_LiveSpan":
        tr = self._tracer
        self.span_id = tr._next_id()
        stack = tr._stack()
        self.parent_id = stack[-1] if stack else None
        stack.append(self.span_id)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        tr = self._tracer
        stack = tr._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        th = threading.current_thread()
        tr._record(Span(
            self.name, self.cat,
            start_us=(self._t0 - tr._epoch) * 1e6,
            dur_us=(t1 - self._t0) * 1e6,
            tid=th.ident or 0, thread_name=th.name,
            span_id=self.span_id, parent_id=self.parent_id,
            args=self.args,
        ))


class Tracer:
    """Bounded ring of completed spans + the train-step capture window."""

    def __init__(self, ring: int = 4096) -> None:
        self._lock = threading.Lock()
        self._ring_size = max(1, int(ring))
        self._ring: List[Span] = []
        self._tls = threading.local()
        self._id = 0
        self._epoch = time.perf_counter()
        self.enabled = False
        self.trace_dir = ""
        self.trace_steps = 50
        self.dropped = 0
        # train-loop capture window state
        self._win_start: Optional[int] = None
        self._win_done = False

    # config -------------------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        if name == "trace_dir":
            self.trace_dir = val
            self.enabled = bool(val)
        elif name == "trace_steps":
            self.trace_steps = int(val)
        elif name == "trace_ring":
            with self._lock:
                self._ring_size = max(1, int(val))

    def configure(self, cfg: Sequence[ConfigEntry]) -> None:
        for n, v in cfg:
            self.set_param(n, v)

    def enable(self, ring: Optional[int] = None) -> None:
        """Programmatic enable (tests / embedding use; no auto-flush)."""
        if ring is not None:
            with self._lock:
                self._ring_size = max(1, int(ring))
        self.enabled = True

    def reset(self) -> None:
        with self._lock:
            self._ring = []
            self.dropped = 0
        self.enabled = False
        self.trace_dir = ""
        self.trace_steps = 50
        self._win_start = None
        self._win_done = False

    # span recording -----------------------------------------------------
    def _stack(self) -> List[int]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _next_id(self) -> int:
        with self._lock:
            self._id += 1
            return self._id

    def _record(self, s: Span) -> None:
        with self._lock:
            self._ring.append(s)
            if len(self._ring) > self._ring_size:
                drop = len(self._ring) - self._ring_size
                del self._ring[:drop]
                self.dropped += drop

    def span(self, name: str, cat: str = "", **args):
        """Open a span; use as ``with tracer().span("checkpoint.write"):``.
        Returns a shared no-op when tracing is disabled."""
        if not self.enabled:
            return _NOP
        return _LiveSpan(self, name, cat, args or None)

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring = []

    # export -------------------------------------------------------------
    def to_chrome_trace(self) -> Dict[str, object]:
        pid = os.getpid()
        spans = self.spans()
        events: List[Dict[str, object]] = []
        seen_tids = {}
        for s in spans:
            seen_tids.setdefault(s.tid, s.thread_name)
        for tid, tname in sorted(seen_tids.items()):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": tname},
            })
        events.extend(s.to_event(pid) for s in spans)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export(self, path: str) -> str:
        """Write the ring as Chrome trace JSON; returns the path.
        Atomic (via the shared diskio helper) so a crash mid-export
        can't leave a half-written trace that chrome://tracing rejects."""
        from ..utils import diskio
        diskio.write_atomic(
            path, json.dumps(self.to_chrome_trace()).encode("utf-8"),
            site=None)
        return path

    # train-loop capture window ------------------------------------------
    def step(self, global_step: int) -> None:
        """Called once per training step.  With ``trace_dir`` set, the
        FIRST ``trace_steps`` steps are captured (spans are recording
        the whole time — the window only decides when to flush), then
        the ring is exported once and tracing disables itself, exactly
        the one-window discipline of ``profiler.TraceController``."""
        if not self.enabled or not self.trace_dir or self._win_done:
            return
        if self._win_start is None:
            self._win_start = global_step
        if global_step - self._win_start + 1 >= self.trace_steps:
            self.flush_window(global_step)

    def flush_window(self, end_step: Optional[int] = None) -> Optional[str]:
        """Export the current window (round end / close); idempotent."""
        if not self.trace_dir or self._win_done or self._win_start is None:
            return None
        self._win_done = True
        # one-window discipline holds even when the export fails (full
        # disk): recording stops either way, the hot path must not keep
        # paying span cost for a trace that can no longer be written
        self.enabled = False
        path = os.path.join(
            self.trace_dir,
            f"host_trace_{self._win_start:06d}-"
            f"{(end_step if end_step is not None else self._win_start):06d}"
            ".json",
        )
        try:
            return self.export(path)
        except (OSError, TypeError, ValueError):
            # the flush runs inside the train loop — a full disk or a
            # pathological span must never abort the round
            return None


_TRACER = Tracer()


def tracer() -> Tracer:
    """The process-wide tracer."""
    return _TRACER


def span(name: str, cat: str = "", **args):
    """Module-level convenience: ``with obs.span("serve.batch"): ...``."""
    return _TRACER.span(name, cat, **args)


def configure(cfg: Sequence[ConfigEntry]) -> None:
    _TRACER.configure(cfg)


def step(global_step: int) -> None:
    _TRACER.step(global_step)
