"""User-facing Python API: ``DataIter`` / ``Net`` / ``train``.

Parity: the reference's ctypes wrapper (``/root/reference/wrapper/cxxnet.py``
classes ``DataIter`` (:64), ``Net`` (:105), ``train`` (:281) over the C ABI in
``/root/reference/wrapper/cxxnet_wrapper.h:36-230``).  The reference routed
every call through a ``libcxxnetwrapper.so`` C shim because its trainer was
C++; here the trainer is the in-process :class:`~cxxnet_tpu.nnet.trainer.
NetTrainer`, so the same surface is plain Python — numpy in, numpy out, with
JAX/XLA doing device placement under the hood.

Layout note: batch arrays are **NHWC** (the TPU-native layout used across the
framework), not the reference's NCHW.  Flat ``(N, D)`` input is accepted
anywhere a 4-D tensor is (it is reshaped to ``(N, 1, 1, D)``).
"""

from __future__ import annotations

import sys
from typing import Dict, Iterable, Optional, Tuple, Union

import numpy as np

from . import config as cfgmod
from .io.data import DataBatch, create_iterator
from .nnet.trainer import NetTrainer

__all__ = ["DataIter", "Net", "train"]


class DataIter:
    """Config-driven data iterator (reference ``DataIter``, cxxnet.py:64-103).

    ``cfg`` is the text of one iterator section — the lines that would sit
    between ``data = train`` and ``iter = end`` in a ``.conf`` file (the
    section markers themselves are tolerated and ignored, so a pasted
    section works verbatim).
    """

    def __init__(self, cfg: str) -> None:
        entries = [
            (n, v)
            for n, v in cfgmod.parse_pairs(cfg)
            if n not in ("data", "eval", "pred")
            and not (n == "iter" and v == "end")
        ]
        self._iter = create_iterator(entries)
        self._iter.init()
        self.head = True
        self.tail = False

    def next(self) -> bool:
        ret = self._iter.next()
        self.head = False
        self.tail = not ret
        return ret

    def before_first(self) -> None:
        self._iter.before_first()
        self.head = True
        self.tail = False

    def check_valid(self) -> None:
        if self.head:
            raise RuntimeError(
                "iterator was at head state, call next to get to valid state"
            )
        if self.tail:
            raise RuntimeError("iterator reaches end")

    def value(self) -> DataBatch:
        self.check_valid()
        return self._iter.value()

    def get_data(self) -> np.ndarray:
        """Current batch data, NHWC (reference returned NCHW)."""
        return np.asarray(self.value().data)

    def get_label(self) -> np.ndarray:
        return np.asarray(self.value().label)

    def __iter__(self):
        self.before_first()
        while self.next():
            yield self._iter.value()


def _as_batch(data: np.ndarray, label: Optional[np.ndarray]) -> DataBatch:
    data = np.ascontiguousarray(data, np.float32)
    if label is not None:
        label = np.asarray(label, np.float32)
        if label.ndim == 1:
            label = label.reshape(label.shape[0], 1)
        if label.ndim != 2:
            raise ValueError("label must be 1-D or 2-D")
        if label.shape[0] != data.shape[0]:
            raise ValueError("Net.update: data size mismatch")
    else:
        label = np.zeros((data.shape[0], 1), np.float32)
    return DataBatch(data=data, label=label)


ParamSpec = Union[Dict[str, object], Iterable[Tuple[str, object]]]


class Net:
    """Trainer handle (reference ``Net``, cxxnet.py:105-280).

    ``dev`` is the device string (``tpu``, ``tpu:0-3``, ``cpu``); ``cfg`` is
    full ``.conf`` text (netconfig section + globals). Further settings can
    be layered on with :meth:`set_param` before :meth:`init_model`.
    """

    def __init__(self, dev: str = "tpu", cfg: str = "") -> None:
        self._trainer = NetTrainer()
        self._trainer.set_param("dev", dev)
        if cfg:
            self._trainer.set_params(cfgmod.parse_pairs(cfg))
        self._predict_cache = None  # lazy ShapeBucketCache (predict/extract)

    @property
    def trainer(self) -> NetTrainer:
        """The underlying NetTrainer (escape hatch; no reference analog)."""
        return self._trainer

    def set_param(self, name: str, value: object) -> None:
        self._trainer.set_param(str(name), str(value))

    def init_model(self) -> None:
        # join a multi-process job if dist_* keys are present (same entry
        # condition as the CLI, cli.py run()); no-op for single-process
        from .parallel import maybe_init_distributed

        maybe_init_distributed(self._trainer.cfg)
        self._trainer.init_model()

    def load_model(self, fname: str) -> None:
        self._trainer.load_model(fname)

    def save_model(self, fname: str) -> None:
        self._trainer.save_model(fname)

    def start_round(self, round_counter: int) -> None:
        self._trainer.start_round(round_counter)

    def update(
        self,
        data: Union[DataIter, np.ndarray],
        label: Optional[np.ndarray] = None,
    ) -> None:
        if isinstance(data, DataIter):
            self._trainer.update(data.value())
        elif isinstance(data, np.ndarray):
            if label is None:
                raise ValueError("Net.update: need label to use update")
            self._trainer.update(_as_batch(data, label))
        else:
            raise TypeError(f"update does not support type {type(data)}")

    def update_scan(self, data: np.ndarray, label: np.ndarray,
                    n_steps: Optional[int] = None) -> np.ndarray:
        """Run K train steps as ONE device program (the CLI's
        ``scan_steps`` fast path, ``NetTrainer.update_scan``): ``data``
        is a ``[K, B, ...]`` micro-batch stack, or a single ``[B, ...]``
        batch reused ``n_steps`` times.  Returns the per-step losses —
        the library-API spelling of device-side multi-step training."""
        return np.asarray(
            self._trainer.update_scan(np.asarray(data), np.asarray(label),
                                      n_steps=n_steps)
        )

    def evaluate(self, data: DataIter, name: str) -> str:
        if not isinstance(data, DataIter):
            raise TypeError(f"evaluate does not support type {type(data)}")
        ret = self._trainer.evaluate(data._iter, name)
        if len(self._trainer.metric) > 0:
            # the trainer drained the iterator; mark the wrapper exhausted
            # so a stale value()/update() raises instead of silently
            # reusing the last eval batch
            data.head, data.tail = False, True
        return ret

    def _bucket_cache(self):
        """The shape-bucketed compiled-predict cache for raw-array
        inference (``serve/cache.py``): odd request sizes pad to
        power-of-two buckets, so repeated mixed-size calls reuse a
        handful of warm XLA programs instead of re-jitting per size.
        Self-invalidates when the trainer rebuilds its net
        (init_model / load_model)."""
        from .serve.cache import ShapeBucketCache

        if (self._predict_cache is None
                or self._predict_cache.trainer is not self._trainer):
            self._predict_cache = ShapeBucketCache(
                self._trainer, self._trainer.batch_size or 64
            )
        return self._predict_cache

    def _bucketed_ok(self, arr: np.ndarray) -> bool:
        """Raw arrays route through the bucket cache for single-process
        runs (multi-process predict needs the trainer's global-array
        assembly) on nets without extra_data side inputs."""
        import jax

        return (arr.ndim >= 2 and jax.process_count() == 1
                and self._trainer.graph is not None
                and not self._trainer.graph.extra_data_num)

    def predict(self, data: Union[DataIter, np.ndarray]) -> np.ndarray:
        """Prediction for the current batch (iter) or the given array.

        Raw arrays return exactly ``data.shape[0]`` rows — internal
        bucket/shard padding is always trimmed — and hit the bucketed
        compile cache, so request sizes like 3, 7, 100 stop compiling
        fresh XLA programs per size."""
        if isinstance(data, DataIter):
            batch = data.value()
            n = batch.batch_size - batch.num_batch_padd
            return self._trainer.predict(batch)[:n]
        arr = np.ascontiguousarray(np.asarray(data), np.float32)
        if self._bucketed_ok(arr):
            return self._bucket_cache().predict(arr)
        return self._trainer.predict(_as_batch(arr, None))

    def extract(self, data: Union[DataIter, np.ndarray], name: str) -> np.ndarray:
        """Feature extraction by node name or ``top[-k]`` (raw arrays:
        trimmed to the input row count, bucket-cached like predict)."""
        if isinstance(data, DataIter):
            batch = data.value()
            n = batch.batch_size - batch.num_batch_padd
            return self._trainer.extract_feature(batch, name)[:n]
        arr = np.ascontiguousarray(np.asarray(data), np.float32)
        if self._bucketed_ok(arr):
            return self._bucket_cache().extract(arr, name)
        return self._trainer.extract_feature(_as_batch(arr, None), name)

    def generate(self, prompt: str = "", gen_len: int = 256,
                 temp: float = 0.0, cache: bool = True,
                 seed: Optional[int] = None, topk: int = 0,
                 topp: float = 0.0) -> str:
        """Continue ``prompt`` from a trained byte-level language model
        (new scope; no reference analog).  KV-cache incremental decoding
        by default, sliding-window fallback — ``nnet/generate.py``."""
        from .nnet.generate import generate

        return generate(self._trainer, prompt, gen_len, temp,
                        cache=cache, seed=seed, topk=topk, topp=topp)

    def set_weight(self, weight: np.ndarray, layer_name: str, tag: str) -> None:
        self._trainer.set_weight(np.asarray(weight, np.float32), layer_name, tag)

    def get_weight(self, layer_name: str, tag: str) -> Optional[np.ndarray]:
        w = self._trainer.get_weight(layer_name, tag)
        return None if w.size == 0 else w


def train(
    cfg: str,
    data: Union[DataIter, np.ndarray],
    num_round: int,
    param: ParamSpec,
    eval_data: Optional[DataIter] = None,
    label: Optional[np.ndarray] = None,
    dev: str = "tpu",
    print_step: int = 100,
) -> Net:
    """Config-in, trained-``Net``-out loop (reference ``train``, :281-307)."""
    net = Net(dev=dev, cfg=cfg)
    items = param.items() if isinstance(param, dict) else param
    for k, v in items:
        net.set_param(k, v)
    net.init_model()
    if isinstance(data, DataIter):
        for r in range(num_round):
            net.start_round(r)
            data.before_first()
            scounter = 0
            while data.next():
                net.update(data)
                scounter += 1
                if print_step and scounter % print_step == 0:
                    print(f"[{r}] {scounter} batch passed")
            if eval_data is not None:
                seval = net.evaluate(eval_data, "eval")
                sys.stderr.write(seval + "\n")
        return net
    for r in range(num_round):
        net.start_round(r)
        net.update(data=data, label=label)
        if eval_data is not None:
            sys.stderr.write(net.evaluate(eval_data, "eval") + "\n")
    return net
