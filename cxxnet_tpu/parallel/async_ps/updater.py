"""Push / PullReq / PullWait: the bounded-staleness async updater.

The reference's ``IAsyncUpdater`` contract (``updater.h`` /
``async_updater-inl.hpp``): after a layer's backward, ``Push`` hands its
gradient to the parameter server, ``PullReq`` requests the updated
weights, and ``PullWait`` — called only right before the NEXT forward
needs that layer — blocks until they arrived.  Everything between Push
and PullWait overlaps with the backward of the remaining layers.

This module re-expresses that contract per gradient-exchange *group*
on the SPMD mesh, wrapping the existing updater registry
(``cxxnet_tpu/updater``) instead of a server process:

* :meth:`AsyncUpdater.push` — enqueue a group's REDUCED (cross-replica
  folded) gradient into the group's aggregate buffer, stamped with its
  origin step and the current membership *generation*;
* :meth:`AsyncUpdater.pull_req` — dispatch the updater apply for the
  oldest buffered aggregate **once more than ``staleness`` aggregates
  are pending**: with ``staleness = 0`` every push applies immediately
  (synchronous semantics, bitwise — the parity suite pins it); with
  ``staleness = k`` the apply consumes the k-step-old aggregate, so a
  replica whose step-t reduction is still in flight keeps training on
  weights that lag at most k applied updates instead of stalling the
  pod;
* :meth:`AsyncUpdater.pull_wait` — block until a group's weights are
  resident (the fence before anything reads them on host);
* :meth:`AsyncUpdater.drain` — the hard re-sync barrier: apply every
  pending aggregate in push order (the trainer runs it every
  ``async_resync_period`` rounds and before serializing a checkpoint,
  so checkpoints are always fully-applied synchronous states).

Staleness accounting per group is exported as
``async_staleness_steps{group}``; every push bumps
``async_pushes_total{group}``.

**Generation stamping** (elastic pods, doc/parallel.md): each buffered
aggregate carries the membership generation it was reduced under.  An
elastic rebuild calls :meth:`reset_staleness`, which discards every
pending aggregate and bumps the generation — and the apply path
independently re-checks the stamp, so an aggregate reduced by a dead
generation's collectives can NEVER be applied to the rebuilt mesh's
weights (``async_stale_dropped_total{reason}`` counts both paths).
"""

from __future__ import annotations

import collections
from typing import Deque, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ...obs import events as obs_events
from ...obs.registry import registry as obs_registry
from .groups import GroupKey, subtree, write_back


class _Aggregate(NamedTuple):
    grads: dict      # {key: {tag: reduced grad}} — replicated leaves
    epoch: int       # origin step (the updater schedule position)
    generation: int  # membership generation the reduction ran under


def _staleness_gauge():
    return obs_registry().gauge(
        "async_staleness_steps",
        "Pending (not yet applied) gradient aggregates per exchange "
        "group — the staleness the next apply will carry.",
        labelnames=("group",),
    )


def _pushes_counter():
    return obs_registry().counter(
        "async_pushes_total",
        "Gradient aggregates pushed into the async exchange buffers.",
        labelnames=("group",),
    )


def _dropped_counter():
    return obs_registry().counter(
        "async_stale_dropped_total",
        "Buffered aggregates discarded instead of applied.",
        labelnames=("reason",),
    )


class AsyncUpdater:
    """Bounded-staleness aggregate buffers over the trainer's updaters.

    One instance per trainer; ``apply_fn(gid)`` must return the jitted
    per-group apply program ``(params_sub, ustates_sub, grads_sub,
    epoch) -> (new_params_sub, new_ustates_sub)`` (built by the
    stepper, which owns program construction)."""

    def __init__(self, trainer, groups: List[List[GroupKey]],
                 staleness: int = 0, apply_fn=None) -> None:
        self.trainer = trainer
        self.groups = groups
        self.staleness = max(0, int(staleness))
        self.generation = 0
        self._apply_fn = apply_fn
        self._pending: List[Deque[_Aggregate]] = [
            collections.deque() for _ in groups
        ]
        self.pushes = 0
        self.applies = 0
        self.dropped = 0

    # ------------------------------------------------------------------
    def pending_depth(self, gid: int) -> int:
        return len(self._pending[gid])

    def push(self, gid: int, grads: dict, epoch: int) -> None:
        """Enqueue one group's reduced gradient aggregate (generation-
        stamped); the dispatch that produced ``grads`` may still be in
        flight — nothing here blocks."""
        self._pending[gid].append(
            _Aggregate(grads, int(epoch), self.generation))
        self.pushes += 1
        try:
            _pushes_counter().labels(group=str(gid)).inc()
            _staleness_gauge().labels(group=str(gid)).set(
                len(self._pending[gid]))
        except Exception:  # noqa: BLE001 - telemetry never aborts a step
            pass

    def pull_req(self, gid: int) -> int:
        """Dispatch applies until at most ``staleness`` aggregates stay
        pending.  Returns the number of applies dispatched (0 while the
        pipeline is still filling; stale-generation discards do not
        count — they never reach the weights)."""
        n = 0
        while len(self._pending[gid]) > self.staleness:
            if self._apply_oldest(gid):
                n += 1
        return n

    def pull_wait(self, gid: int) -> None:
        """Block until this group's weights are resident — the fence a
        host-side reader needs before touching them (device-side
        consumers just get dependency-ordered behind the apply)."""
        for key, tag in self.groups[gid]:
            jax.block_until_ready(self.trainer.params[key][tag])

    # ------------------------------------------------------------------
    def _apply_oldest(self, gid: int) -> bool:
        """Pop + apply one aggregate; returns False when the stamp
        check discarded it instead."""
        agg = self._pending[gid].popleft()
        try:
            _staleness_gauge().labels(group=str(gid)).set(
                len(self._pending[gid]))
        except Exception:  # noqa: BLE001
            pass
        if agg.generation != self.generation:
            # an aggregate reduced under a dead membership generation:
            # its collective may have folded contributions from a
            # replica that no longer exists — never apply it
            self.dropped += 1
            try:
                _dropped_counter().labels(reason="generation").inc()
            except Exception:  # noqa: BLE001
                pass
            obs_events.emit("async.stale_generation_dropped", group=gid,
                            epoch=agg.epoch, aggregate_gen=agg.generation,
                            current_gen=self.generation)
            return False
        tr = self.trainer
        psub = subtree(tr.params, self.groups[gid])
        usub = subtree(tr.ustates, self.groups[gid])
        new_p, new_u = self._apply_fn(gid)(
            psub, usub, agg.grads, jnp.asarray(agg.epoch, jnp.int32))
        write_back(tr.params, self.groups[gid], new_p)
        write_back(tr.ustates, self.groups[gid], new_u)
        self.applies += 1
        return True

    # ------------------------------------------------------------------
    def drain(self) -> int:
        """Apply every pending aggregate in push order (stale-generation
        entries are discarded, not applied — and not counted) — the hard
        re-sync barrier's first half; the caller fences afterwards."""
        n = 0
        for gid in range(len(self.groups)):
            while self._pending[gid]:
                if self._apply_oldest(gid):
                    n += 1
        return n

    def reset_staleness(self, generation: Optional[int] = None,
                        reason: str = "rebuild") -> int:
        """Elastic rebuild hook: discard EVERY pending aggregate and
        move to a new membership generation.  ``generation`` pins the
        new stamp (the elastic member's); default bumps by one.
        Returns how many aggregates were dropped."""
        dropped = 0
        for gid, dq in enumerate(self._pending):
            dropped += len(dq)
            dq.clear()
            try:
                _staleness_gauge().labels(group=str(gid)).set(0)
            except Exception:  # noqa: BLE001
                pass
        if dropped:
            self.dropped += dropped
            try:
                _dropped_counter().labels(reason=reason).inc(dropped)
            except Exception:  # noqa: BLE001
                pass
        old = self.generation
        self.generation = (old + 1 if generation is None
                           else int(generation))
        obs_events.emit("async.reset_staleness", reason=reason,
                        dropped=dropped, old_generation=old,
                        generation=self.generation)
        return dropped

    def snapshot(self) -> Dict[str, object]:
        return {
            "groups": len(self.groups),
            "staleness": self.staleness,
            "generation": self.generation,
            "pending": [len(dq) for dq in self._pending],
            "pushes": self.pushes,
            "applies": self.applies,
            "dropped": self.dropped,
        }
