"""Gradient-exchange groups: partition the parameter tensors.

The reference exchanged gradients per LAYER (mshadow-ps assigned each
layer its own Push/PullReq keys, ``async_updater-inl.hpp``); one
collective per tensor is the other extreme and drowns a modern mesh in
launch overhead.  The middle ground — what resource-aware placement
(arXiv 1901.05803) argues for — is a small number of *groups* sized by
parameter count: each group's cross-replica reduction is one dispatch,
large enough to amortize collective latency, small enough that the
first groups' exchange can overlap the remaining groups' work.

``partition_groups`` is the default policy: tensors keep the net's
layer order (the order backward produces them, reversed at dispatch
time by the caller when that matters) and are greedily bucketed so
every group carries roughly ``total_params / n_groups`` parameters.
``async_groups = 0`` (auto) picks ``min(4, n_tensors)`` groups.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

GroupKey = Tuple[str, str]  # (param key, tag), e.g. ("l0_fc1", "wmat")

DEFAULT_MAX_GROUPS = 4


def tensor_sizes(params: Dict[str, dict]) -> List[Tuple[str, str, int]]:
    """``[(key, tag, n_elements)]`` in the params pytree's layer order
    (dict insertion order IS the graph's layer order)."""
    out: List[Tuple[str, str, int]] = []
    for key, tags in params.items():
        for tag, w in tags.items():
            out.append((key, tag, int(np.size(w))))
    return out


def partition_groups(params: Dict[str, dict],
                     n_groups: int = 0) -> List[List[GroupKey]]:
    """Contiguous, parameter-count-balanced partition of the tensors.

    ``n_groups <= 0`` = auto (``min(4, n_tensors)``); an explicit count
    is clamped to the tensor count so every group is non-empty.  The
    greedy rule closes a group once its cumulative size reaches the
    proportional target, while always leaving at least one tensor for
    each remaining group.
    """
    tensors = tensor_sizes(params)
    if not tensors:
        return []
    n = len(tensors)
    g = min(DEFAULT_MAX_GROUPS, n) if n_groups <= 0 else min(int(n_groups), n)
    total = max(1, sum(s for _, _, s in tensors))
    out: List[List[GroupKey]] = []
    cur: List[GroupKey] = []
    cum = 0
    for idx, (key, tag, size) in enumerate(tensors):
        cur.append((key, tag))
        cum += size
        remaining = n - idx - 1        # tensors after this one
        still_open = g - len(out) - 1  # groups after the current one
        if len(out) < g - 1 and (
                cum * g >= total * (len(out) + 1)
                or remaining <= still_open):
            out.append(cur)
            cur = []
    if cur:
        out.append(cur)
    assert len(out) == g and all(out), (
        f"partition bug: {len(out)} groups for g={g}")
    return out


def subtree(tree: Dict[str, dict], group: List[GroupKey]) -> Dict[str, dict]:
    """The ``{key: {tag: leaf}}`` sub-pytree holding one group's leaves
    (same nesting shape the trainer's ``_apply_updates`` walks)."""
    out: Dict[str, dict] = {}
    for key, tag in group:
        out.setdefault(key, {})[tag] = tree[key][tag]
    return out


def write_back(tree: Dict[str, dict], group: List[GroupKey],
               sub: Dict[str, dict]) -> None:
    """Fold one group's updated leaves back into the full pytree."""
    for key, tag in group:
        tree[key][tag] = sub[key][tag]


def group_param_counts(params: Dict[str, dict],
                       groups: List[List[GroupKey]]) -> List[int]:
    sizes = {(k, t): s for k, t, s in tensor_sizes(params)}
    return [sum(sizes[kt] for kt in grp) for grp in groups]
