"""Async data-parallel: the parameter-server heritage on the mesh.

cxxnet's signature scaling trick was asynchrony — mshadow-ps
``IAsyncUpdater`` hid each layer's gradient exchange behind the
backward of the layers below it and tolerated bounded staleness across
workers (PAPER.md; the relaxed-consistency case of arXiv 1605.08695).
The port's mesh trainer was fully synchronous; this subsystem
resurrects the model (ROADMAP item 5, ``async_overlap = 1``):

* :mod:`~cxxnet_tpu.parallel.async_ps.groups` — partition the tensors
  into gradient-exchange groups (``async_groups``, parameter-count
  buckets by default);
* :mod:`~cxxnet_tpu.parallel.async_ps.step` — the overlapped step:
  per-shard backward with NO monolithic all-reduce, then one
  dispatch-ordered async collective (all-gather + ordered fold) per
  group, the apply of group k overlapping the exchange of group k+1;
* :mod:`~cxxnet_tpu.parallel.async_ps.updater` — the
  Push/PullReq/PullWait-shaped bounded-staleness buffers
  (``staleness = k``) over the existing updater registry, with hard
  re-sync barriers every ``async_resync_period`` rounds and
  generation-stamped aggregates so an elastic rebuild can never apply
  a dead generation's gradient.

Correctness contract (doc/parallel.md "Async data-parallel"):
``staleness = 0`` is BITWISE equal to the synchronous ``det_reduce``
fused step (same all-gather + ordered fold, same updater math — the
parity suite and the ASYNC=1 CLI lane pin the checkpoint CRCs);
``staleness > 0`` changes the training math (delayed aggregates) and
is gated by the measured convergence A/B (``tools/async_ab.py``).
"""

from __future__ import annotations

from .groups import (
    group_param_counts,
    partition_groups,
    subtree,
    tensor_sizes,
    write_back,
)
from .step import AsyncStepper
from .updater import AsyncUpdater

__all__ = [
    "AsyncStepper",
    "AsyncUpdater",
    "group_param_counts",
    "partition_groups",
    "subtree",
    "tensor_sizes",
    "write_back",
]
