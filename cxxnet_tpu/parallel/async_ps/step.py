"""The overlapped async train step: grouped backward + per-group
dispatch-ordered gradient exchange.

The synchronous fused step is ONE program: forward, backward, the
cross-replica gradient reduction and the updater math all inside a
single dispatch — nothing overlaps with anything outside it, and one
slow replica stalls the single collective everyone sits in.  This
module re-expresses the same math as a *dispatch pipeline*
(``async_overlap = 1``):

1. **grad program** — a ``shard_map`` over the data axis computes each
   shard's summed-loss gradient and returns the PER-SHARD partials,
   stacked on a sharded leading axis.  No cross-replica collective
   runs here at all (the compiled-HLO suite asserts no ``all-reduce``
   anywhere in the pipeline);
2. **per-group reduce programs** — one per gradient-exchange group
   (``groups.partition_groups``): ``all-gather`` over the data axis +
   the trace-time-unrolled ORDERED fold (``((g0+g1)+g2)+…`` — the same
   fold, in the same order, as the ``det_reduce`` synchronous step, so
   ``staleness = 0`` is bitwise-equal to it).  Groups are dispatched in
   REVERSE layer order — the order backward materializes gradients —
   so the exchange of the net's tail groups is in flight while the
   head groups' reduce/apply still queue;
3. **per-group apply programs** — the updater registry's math over one
   group's tensors, fed through the bounded-staleness
   Push/PullReq/PullWait buffers (``updater.AsyncUpdater``).

Every dispatch is asynchronous: the host never blocks inside a step,
and the device executes group k's apply while group k+1's reduction is
still exchanging — on a real accelerator that is backprop/exchange
overlap; on the CPU test mesh it is the same dependency graph, which
is what the parity suites pin.  The only fences are
:meth:`AsyncStepper.round_end` (the round boundary; also the
``mesh.replica`` fault site, so an injected straggler delay is paid
ONCE per round instead of once per step) and the hard re-sync barrier
every ``async_resync_period`` rounds, which drains the staleness
buffers first.

``async_overlap_fraction`` reports, per round, the fraction of wall
time the host was NOT blocked in a fence — the measurable overlap win.
"""

from __future__ import annotations

import time
from typing import List, Optional

import jax
import jax.numpy as jnp

from ...obs import events as obs_events
from ...obs.registry import registry as obs_registry
from .groups import group_param_counts, partition_groups, subtree
from .updater import AsyncUpdater


def _overlap_gauge():
    return obs_registry().gauge(
        "async_overlap_fraction",
        "Per-round fraction of wall time the host was not blocked in a "
        "device fence (1.0 = fully overlapped dispatch).",
    )


class AsyncStepper:
    """Owns the async-mode programs and drives one trainer's pipeline.

    Built lazily by ``NetTrainer`` at the first async update; dropped
    whenever the net/mesh/jit cache is rebuilt (programs close over
    both).  All math-bearing configuration (group partition, staleness,
    resync period) is read from the trainer's conf keys once, here.
    """

    def __init__(self, trainer) -> None:
        self.trainer = trainer
        self.groups = partition_groups(trainer.params,
                                       trainer.async_groups)
        self.resync_period = max(1, int(trainer.async_resync_period))
        self.updater = AsyncUpdater(
            trainer, self.groups, staleness=trainer.staleness,
            apply_fn=self._apply_fn)
        self._grad_prog = None
        self._reduce_progs: List[Optional[object]] = [None] * len(self.groups)
        self._apply_progs: List[Optional[object]] = [None] * len(self.groups)
        self._round_t0: Optional[float] = None
        self._blocked_s = 0.0
        self.last_overlap_fraction = 0.0
        obs_events.emit(
            "async.armed", groups=len(self.groups),
            staleness=self.updater.staleness,
            resync_period=self.resync_period,
            group_params=group_param_counts(trainer.params, self.groups))

    # ------------------------------------------------------------------
    # programs
    def _grad_fn(self):
        """Per-shard summed-loss gradients, stacked ``[n_data, ...]`` on
        a sharded leading axis — backward with NO cross-replica
        collective; the exchange belongs to the per-group reduces."""
        if self._grad_prog is not None:
            return self._grad_prog
        tr = self.trainer
        plan = tr.mesh_plan
        # the backward itself is the trainer's SHARED per-shard grad
        # closure — the det_reduce step traces the identical one, which
        # is what keeps the staleness=0 bitwise-parity contract honest
        per_shard_grad = tr._shard_grad_fn()
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def per_shard(params, data, labels, mask, rng, epoch):
            g, loss, out = per_shard_grad(
                params, data, labels, mask, rng, epoch)
            gstack = jax.tree_util.tree_map(lambda x: x[None], g)
            return gstack, loss[None], out

        sm = shard_map(
            per_shard, mesh=plan.mesh,
            in_specs=(P(), P("data"), P("data"), P("data"), P(), P()),
            out_specs=(P("data"), P("data"), P("data")),
            check_rep=False,
        )
        rep, dsh, _ = tr._sh()
        psh, _ = tr._param_sh()
        self._grad_prog = tr._jit(
            sm,
            (psh, dsh, dsh, dsh, rep, rep),
            (dsh, dsh, dsh),
            kind="train_async", data_arg=1,
        )
        return self._grad_prog

    def _reduce_fn(self, gid: int):
        """One group's cross-replica exchange: ``all-gather`` over the
        data axis + the ordered fold — the det_reduce fold, scoped to
        this group's tensors, as its OWN dispatch."""
        if self._reduce_progs[gid] is not None:
            return self._reduce_progs[gid]
        tr = self.trainer
        plan = tr.mesh_plan
        n = plan.n_data
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def per_shard(gsub):
            def fold(x):
                parts = jax.lax.all_gather(x, "data")  # (n, 1, *shape)
                acc = parts[0][0]
                for i in range(1, n):
                    acc = acc + parts[i][0]
                return acc

            return jax.tree_util.tree_map(fold, gsub)

        sm = shard_map(
            per_shard, mesh=plan.mesh,
            in_specs=(P("data"),), out_specs=P(),
            check_rep=False,
        )
        rep, dsh, _ = tr._sh()
        # no donation: the sharded partial stack cannot alias the
        # replicated fold output (XLA would warn every compile); the
        # stacks are gradient-sized transients and die on their own
        self._reduce_progs[gid] = tr._jit(
            sm, (dsh,), rep,
            kind="async_reduce",
        )
        return self._reduce_progs[gid]

    def _apply_fn(self, gid: int):
        """One group's updater math (the existing registry, unchanged),
        donated so the old weight buffers die with the apply."""
        if self._apply_progs[gid] is not None:
            return self._apply_progs[gid]
        tr = self.trainer
        updaters = dict(tr.updaters)
        apply_updates = tr._apply_updates

        def f(psub, usub, gsub, epoch):
            return apply_updates(updaters, psub, usub, gsub, epoch,
                                 gspec=None)

        rep = tr._sh()[0]
        self._apply_progs[gid] = tr._jit(
            f, (rep, rep, rep, rep), (rep, rep),
            donate_argnums=(0, 1),
            kind="async_apply",
        )
        return self._apply_progs[gid]

    # ------------------------------------------------------------------
    def step(self, data, labels, mask, rng, epoch):
        """One async train step: dispatch backward, then each group's
        reduce → push → pull_req, reverse layer order.  Returns
        ``(per_shard_losses, out_rows)`` — both still device-async."""
        if self._round_t0 is None:
            self._round_t0 = time.perf_counter()
            self._blocked_s = 0.0
        tr = self.trainer
        gstack, losses, out = self._grad_fn()(
            tr.params, data, labels, mask, rng,
            jnp.asarray(epoch, jnp.int32))
        ep = int(epoch)
        # reverse layer order: backward materializes the tail groups'
        # gradients first, so their exchange dispatches first and is in
        # flight while the earlier groups' reduce/apply still queue
        for gid in range(len(self.groups) - 1, -1, -1):
            reduced = self._reduce_fn(gid)(
                subtree(gstack, self.groups[gid]))
            self.updater.push(gid, reduced, ep)
            self.updater.pull_req(gid)
        return losses, out

    def add_blocked(self, dt: float) -> None:
        """Host-blocking time spent OUTSIDE the stepper — the trainer's
        opt-in per-step fetches (divergence guard, train metrics) fence
        the pipeline too, and must count against the round's overlap
        fraction or the gauge would report ~1.0 for an effectively
        synchronous run."""
        if self._round_t0 is not None:
            self._blocked_s += dt

    def round_end(self, round_: int) -> bool:
        """Round-boundary fence; every ``async_resync_period`` rounds it
        is the HARD re-sync barrier (staleness buffers drained first,
        so weights catch up to every pushed gradient).  Returns True
        when this boundary resynced.  The fence goes through
        ``NetTrainer.sync`` — the ``mesh.replica`` fault site — so an
        injected straggler delay lands once per round here, not once
        per step."""
        resync = (round_ % self.resync_period) == 0
        drained = self.updater.drain() if resync else 0
        t0 = time.perf_counter()
        self.trainer.sync()
        self._blocked_s += time.perf_counter() - t0
        now = time.perf_counter()
        wall = (now - self._round_t0) if self._round_t0 else 0.0
        frac = max(0.0, 1.0 - self._blocked_s / wall) if wall > 0 else 0.0
        self.last_overlap_fraction = frac
        try:
            _overlap_gauge().set(frac)
        except Exception:  # noqa: BLE001 - telemetry never aborts
            pass
        if resync:
            obs_events.emit("async.resync", round=round_,
                            drained=drained,
                            overlap_fraction=round(frac, 4))
        self._round_t0 = None
        return resync

    def snapshot(self) -> dict:
        d = self.updater.snapshot()
        d["overlap_fraction"] = round(self.last_overlap_fraction, 4)
        d["resync_period"] = self.resync_period
        return d
