"""Elastic pod: survive replica loss and resize the mesh mid-run.

The cxxnet lineage kept training through worker churn via its parameter
server (PAPER.md); the TensorFlow systems paper (arXiv 1605.08695 §4.2)
treats worker failure-and-recovery as a first-class design axis.  This
module is the membership/liveness half of that story for the SPMD mesh
trainer; the rebuild half (checkpoint-and-reload onto the surviving
process set) lives in ``cli.py::_elastic_rebuild`` on top of
``distributed.shutdown_distributed`` and the PR-1 round-consensus
machinery.

Pieces:

* :class:`ElasticCoordinator` — a tiny stdlib TCP JSON-lines service
  hosted INSIDE the rank-0 process (one request per connection).  It
  tracks member heartbeats, classifies "replica slow" (missed a couple
  of beats → ``mesh.replica_slow`` event) distinctly from "replica
  gone" (silent past ``elastic_timeout_s`` → ``mesh.replica_lost`` and
  a new membership *generation*), admits waiting processes for mesh
  growth, and allocates the fresh ``jax.distributed`` coordinator port
  every generation re-initializes onto.
* :class:`ElasticMember` — the per-process client: a heartbeat thread,
  a ``lost_event`` the collective deadline polls, and the blocking
  plan/ack calls the rebuild rendezvous uses.
* :class:`ReplicaLossError` — the typed error a dead peer surfaces as,
  instead of an indefinite hang inside a collective.
* :func:`guarded_call` — the collective deadline: runs a blocking op on
  a worker thread and raises :class:`ReplicaLossError` in bounded time
  (``collective_timeout_s``) once the monitor confirms (or, past the
  deadline, suspects) a lost peer.  A merely *slow* peer only emits a
  ``mesh.collective_slow`` event — the wait continues.
* :func:`rebuild_in_progress` — process-wide flag a serve-colocated
  front-end reads to degrade ``/healthz`` while the trainer rebuilds.

Known limitation (documented in doc/parallel.md): rank 0 hosts both
coordinators, so losing rank 0 ends the job — place rank 0 on durable
capacity.  Survivor re-ranking keeps relative order, so rank 0 stays
rank 0 across every generation.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import socketserver
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs import emit as obs_emit
from ..obs.registry import registry as obs_registry

ConfigEntry = Tuple[str, str]

__all__ = [
    "ReplicaLossError",
    "ElasticOptions",
    "ElasticCoordinator",
    "ElasticMember",
    "GenerationPlan",
    "guarded_call",
    "classify_failure",
    "rebuild_in_progress",
    "set_rebuilding",
]


class ReplicaLossError(RuntimeError):
    """A mesh peer is gone (confirmed by the liveness monitor, or
    presumed after the collective deadline with missed heartbeats).

    ``fatal=True`` means the job cannot continue (survivors below
    ``elastic_min_replicas``, or the coordinator itself is unreachable)
    — the driver re-raises instead of rebuilding."""

    def __init__(self, message: str, lost: Sequence[int] = (),
                 generation: int = 0, presumed: bool = False,
                 fatal: bool = False) -> None:
        super().__init__(message)
        self.lost = list(lost)
        self.generation = int(generation)
        self.presumed = bool(presumed)
        self.fatal = bool(fatal)


# ----------------------------------------------------------------------
# /healthz degrade flag (read by serve/engine.py)
_REBUILDING = threading.Event()


def rebuild_in_progress() -> bool:
    """True while any trainer in this process is mid mesh-rebuild."""
    return _REBUILDING.is_set()


def set_rebuilding(active: bool) -> None:
    if active:
        _REBUILDING.set()
    else:
        _REBUILDING.clear()


# ----------------------------------------------------------------------
@dataclasses.dataclass
class ElasticOptions:
    """The ``elastic_*`` config surface (doc/conf.md)."""

    elastic: bool = False
    min_replicas: int = 1
    rejoin_s: float = 120.0       # joiner admission-wait budget
    heartbeat_s: float = 0.5
    timeout_s: float = 5.0        # silent this long => replica LOST
    collective_timeout_s: float = 30.0
    coordinator: str = ""         # host:port; default dist port + 1
    drop_at: int = 0              # planned shrink boundary (0 = off)
    join: bool = False            # this process is a waiting joiner
    join_at: int = 0              # pin the grow boundary (0 = next)

    @classmethod
    def from_cfg(cls, cfg: Sequence[ConfigEntry]) -> "ElasticOptions":
        o = cls()
        for name, val in cfg:
            if name == "elastic":
                o.elastic = bool(int(val))
            elif name == "elastic_min_replicas":
                o.min_replicas = int(val)
            elif name == "elastic_rejoin_s":
                o.rejoin_s = float(val)
            elif name == "elastic_heartbeat_s":
                o.heartbeat_s = float(val)
            elif name == "elastic_timeout_s":
                o.timeout_s = float(val)
            elif name == "collective_timeout_s":
                o.collective_timeout_s = float(val)
            elif name == "elastic_coordinator":
                o.coordinator = val
            elif name == "elastic_drop_at":
                o.drop_at = int(val)
            elif name == "elastic_join":
                o.join = bool(int(val))
            elif name == "elastic_join_at":
                o.join_at = int(val)
        if o.min_replicas < 1:
            raise ValueError("elastic_min_replicas must be >= 1")
        return o

    def resolve_coordinator(self, dist_coordinator: str) -> str:
        """Elastic coordinator address: explicit key, else the jax
        coordinator's host at port+1 (same machine as rank 0)."""
        if self.coordinator:
            return self.coordinator
        host, port = dist_coordinator.rsplit(":", 1)
        return f"{host}:{int(port) + 1}"


@dataclasses.dataclass
class GenerationPlan:
    """One membership transition, as seen by one member."""

    generation: int
    reason: str                  # replica_lost | planned_shrink | grow
    num: int
    rank: Optional[int]          # None: this member is dropped/leaving
    jax_coordinator: str
    at_round: Optional[int]      # None: effective immediately (loss)
    lost_ranks: List[int] = dataclasses.field(default_factory=list)
    abort: str = ""

    @classmethod
    def from_wire(cls, d: dict) -> "GenerationPlan":
        return cls(
            generation=int(d["gen"]), reason=str(d["reason"]),
            num=int(d["num"]), rank=d.get("rank"),
            jax_coordinator=str(d.get("jax_coordinator", "")),
            at_round=d.get("at_round"),
            lost_ranks=list(d.get("lost_ranks", ())),
            abort=str(d.get("abort", "")),
        )


def free_port() -> int:
    """OS-assigned free TCP port (bind-0-close; the usual TOCTOU race
    applies — callers bind promptly).  The one shared copy: the
    coordinator's per-generation jax ports and the lane tools all use
    this."""
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _replica_gauge(state: str, value: float) -> None:
    try:
        obs_registry().gauge(
            "mesh_replicas",
            "Elastic-mesh replica counts by state.",
            labelnames=("state",),
        ).labels(state=state).set(float(value))
    except Exception:  # noqa: BLE001 - telemetry must never raise
        pass


# ----------------------------------------------------------------------
class _MemberInfo:
    __slots__ = ("mid", "rank", "last_beat", "round", "gen", "suspect")

    def __init__(self, mid: str, rank: int) -> None:
        self.mid = mid
        self.rank = rank
        self.last_beat = time.monotonic()
        self.round = -1
        self.gen = 1
        self.suspect = False


class ElasticCoordinator:
    """The membership brain, hosted inside the rank-0 process.

    Protocol: one TCP connection per request, one JSON line each way.
    Ops: ``hello`` (register), ``beat`` (liveness + generation poll),
    ``join`` (waiter poll), ``plan_shrink`` / ``plan_grow`` (boundary
    rendezvous; idempotent per ``(kind, round)``), ``ack`` (member
    finished rebuilding onto a generation), ``status`` (diagnostics).
    """

    def __init__(self, bind: str, jax_host: str, num: int,
                 opts: ElasticOptions) -> None:
        self.opts = opts
        self.jax_host = jax_host
        self._lock = threading.Lock()
        self._members: Dict[str, _MemberInfo] = {}
        # mid -> {"join_at": int (0 = next), "last": monotonic} — the
        # join poll doubles as waiter liveness: a joiner that died or
        # gave up while waiting must NOT be admitted (the grow
        # rendezvous would block on a process that never arrives)
        self._waiters: Dict[str, dict] = {}
        self._gen = 1
        self._expected = num
        self._plans: Dict[int, dict] = {}    # gen -> wire plan + members
        self._plan_keys: Dict[tuple, int] = {}  # (kind, round) -> gen
        self._grow_at: Optional[int] = None
        self._abort = ""
        self._lost_total = 0
        self._rejoined_total = 0
        self._stop = threading.Event()
        host, port = bind.rsplit(":", 1)

        coord = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:  # noqa: D401
                try:
                    line = self.rfile.readline(1 << 16)
                    req = json.loads(line.decode("utf-8"))
                    resp = coord._dispatch(req)
                except Exception as e:  # noqa: BLE001 - reply, don't die
                    resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                try:
                    self.wfile.write(
                        (json.dumps(resp, separators=(",", ":")) + "\n")
                        .encode("utf-8"))
                except OSError:
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host or "", int(port)), Handler)
        self.address = (
            f"{host or 'localhost'}:{self._server.server_address[1]}")
        self._threads = [
            threading.Thread(target=self._server.serve_forever,
                             name="cxxnet-elastic-coord", daemon=True),
            threading.Thread(target=self._monitor,
                             name="cxxnet-elastic-monitor", daemon=True),
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------
    def close(self) -> None:
        self._stop.set()
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass

    # ------------------------------------------------------------------
    def _monitor(self) -> None:
        """Classify slow vs lost.  Slow (missed ~2 beats) emits one
        ``mesh.replica_slow`` event per episode; lost (silent past
        ``elastic_timeout_s``) triggers a shrink generation."""
        hb = self.opts.heartbeat_s
        while not self._stop.wait(hb):
            now = time.monotonic()
            lost: List[_MemberInfo] = []
            with self._lock:
                self._prune_waiters_locked(now)
                for m in list(self._members.values()):
                    silent = now - m.last_beat
                    if silent > self.opts.timeout_s:
                        lost.append(m)
                    elif silent > 2.5 * hb:
                        if not m.suspect:
                            m.suspect = True
                            obs_emit("mesh.replica_slow", rank=m.rank,
                                     member=m.mid, silent_s=round(silent, 3))
                    else:
                        m.suspect = False
            for m in lost:
                self._on_lost(m)

    def _prune_waiters_locked(self, now: float) -> None:
        """Drop waiters whose join polls stopped; unschedule the grow
        when none remain."""
        stale = [mid for mid, w in self._waiters.items()
                 if now - w["last"] > self.opts.timeout_s]
        for mid in stale:
            del self._waiters[mid]
            obs_emit("mesh.rejoin_abandoned", member=mid)
        if stale and not self._waiters:
            self._grow_at = None

    def _on_lost(self, m: _MemberInfo) -> None:
        with self._lock:
            if m.mid not in self._members:
                return  # raced with another trigger
            del self._members[m.mid]
            self._lost_total += 1
            obs_emit("mesh.replica_lost", rank=m.rank, member=m.mid,
                     generation=self._gen)
            self._bump_generation_locked(
                reason="replica_lost", at_round=None, lost_ranks=[m.rank])
        _replica_gauge("lost", self._lost_total)

    # ------------------------------------------------------------------
    def _bump_generation_locked(self, reason: str,
                                at_round: Optional[int],
                                lost_ranks: Sequence[int] = (),
                                drop_ranks: Sequence[int] = (),
                                admit_waiters: bool = False) -> dict:
        """Compute the next membership generation (caller holds lock).

        Survivors keep relative rank order (rank 0 stays 0); dropped
        ranks leave with ``rank=None``; admitted waiters append at the
        tail.  Every plan carries a FRESH jax coordinator port — an
        abandoned coordination service may still hold the old one."""
        survivors = sorted(self._members.values(), key=lambda m: m.rank)
        dropped = [m for m in survivors if m.rank in set(drop_ranks)]
        survivors = [m for m in survivors if m.rank not in set(drop_ranks)]
        admitted: List[str] = []
        if admit_waiters:
            self._prune_waiters_locked(time.monotonic())
            admitted = sorted(self._waiters)
            self._waiters.clear()
        num = len(survivors) + len(admitted)
        self._gen += 1
        gen = self._gen
        abort = ""
        if num < self.opts.min_replicas:
            abort = (f"{num} survivor(s) below elastic_min_replicas="
                     f"{self.opts.min_replicas}")
            self._abort = abort
        assignments: Dict[str, Optional[int]] = {}
        for i, m in enumerate(survivors):
            assignments[m.mid] = i
            # m.gen stays at the member's last ACKED generation — the
            # beat channel delivers this plan precisely while m.gen
            # lags the coordinator's
            m.rank = i
        for j, mid in enumerate(admitted):
            rank = len(survivors) + j
            assignments[mid] = rank
            info = _MemberInfo(mid, rank)
            info.gen = gen
            self._members[mid] = info
            self._rejoined_total += 1
        for m in dropped:
            assignments[m.mid] = None
            del self._members[m.mid]
        plan = {
            "gen": gen, "reason": reason, "num": num,
            "jax_coordinator": f"{self.jax_host}:{free_port()}",
            "at_round": at_round,
            "lost_ranks": list(lost_ranks),
            "abort": abort,
            "assignments": assignments,
        }
        self._plans[gen] = plan
        old_grow = self._grow_at
        self._grow_at = None
        if self._waiters:
            # a shrink must not orphan pending joiners: reschedule the
            # grow boundary past the transition we just planned
            rounds = [m.round for m in self._members.values()]
            base = (max(rounds) if rounds else 0) + 2
            self._grow_at = max(
                base, old_grow or 0,
                max((w["join_at"] for w in self._waiters.values()),
                    default=0))
        obs_emit("mesh.shrink" if reason != "grow" else "mesh.grow",
                 generation=gen, reason=reason, num=num,
                 at_round=at_round, lost_ranks=list(lost_ranks))
        _replica_gauge("alive", len(self._members))
        _replica_gauge("rejoined", self._rejoined_total)
        return plan

    def _plan_for(self, plan: dict, mid: str) -> dict:
        out = {k: v for k, v in plan.items() if k != "assignments"}
        out["rank"] = plan["assignments"].get(mid)
        return out

    # ------------------------------------------------------------------
    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        mid = str(req.get("member", ""))
        if op == "hello":
            with self._lock:
                info = _MemberInfo(mid, int(req["rank"]))
                info.gen = self._gen
                self._members[mid] = info
                alive = len(self._members)
            _replica_gauge("alive", alive)
            return {"ok": True, "gen": self._gen}
        if op == "beat":
            with self._lock:
                m = self._members.get(mid)
                if m is None:
                    # a member the monitor already declared lost is back:
                    # too late — it must rejoin as a waiter
                    return {"ok": True, "gen": self._gen, "evicted": True,
                            "abort": self._abort}
                m.last_beat = time.monotonic()
                m.round = int(req.get("round", m.round))
                change = None
                if m.gen < self._gen:
                    change = self._plan_for(self._plans[self._gen], mid)
                return {"ok": True, "gen": self._gen,
                        "grow_at": self._grow_at,
                        "suspects": [x.rank for x in self._members.values()
                                     if x.suspect],
                        "change": change, "abort": self._abort}
        if op == "join":
            join_at = int(req.get("join_at", 0) or 0)
            with self._lock:
                # already admitted by a fired grow plan?  The poll also
                # counts as liveness — the joiner's beat thread only
                # starts once it learns its rank, and the rendezvous it
                # then enters can outlast elastic_timeout_s
                mem = self._members.get(mid)
                if mem is not None:
                    mem.last_beat = time.monotonic()
                for gen in sorted(self._plans, reverse=True):
                    plan = self._plans[gen]
                    if plan["assignments"].get(mid) is not None:
                        return {"ok": True,
                                "admitted": self._plan_for(plan, mid)}
                first = mid not in self._waiters
                self._waiters[mid] = {"join_at": join_at,
                                      "last": time.monotonic()}
                if self._grow_at is None:
                    rounds = [m.round for m in self._members.values()]
                    nxt = (max(rounds) if rounds else 0) + 2
                    self._grow_at = max(join_at, nxt)
                if first:  # one announcement, not one per poll
                    obs_emit("mesh.rejoin_waiting", member=mid,
                             grow_at=self._grow_at)
                return {"ok": True, "admitted": None,
                        "grow_at": self._grow_at}
        if op in ("plan_shrink", "plan_grow"):
            round_ = int(req["round"])
            kind = "shrink" if op == "plan_shrink" else "grow"
            with self._lock:
                # a member that learned of a pending transition one
                # boundary late must receive the EXISTING plan — a
                # second generation for the same transition would split
                # the rendezvous
                mem = self._members.get(mid)
                latest = self._plans.get(self._gen)
                if (latest is not None and mem is not None
                        and mem.gen < self._gen
                        and (latest["reason"] == "grow") == (kind == "grow")):
                    return {"ok": True, "plan": self._plan_for(latest, mid)}
                key = (kind, round_)
                if key not in self._plan_keys:
                    if kind == "shrink":
                        drop = max(m.rank for m in self._members.values())
                        plan = self._bump_generation_locked(
                            reason="planned_shrink", at_round=round_,
                            drop_ranks=[drop])
                    else:
                        self._prune_waiters_locked(time.monotonic())
                        if not self._waiters:
                            # every joiner died/gave up while waiting:
                            # growing to the same membership would be a
                            # pointless full rebuild — report no change
                            return {"ok": True, "plan": None}
                        plan = self._bump_generation_locked(
                            reason="grow", at_round=round_,
                            admit_waiters=True)
                    self._plan_keys[key] = plan["gen"]
                plan = self._plans[self._plan_keys[key]]
                return {"ok": True, "plan": self._plan_for(plan, mid)}
        if op == "evict":
            # integrity quarantine: every survivor reports the SAME
            # (rank, round) verdict, so the request is idempotent — the
            # first caller mints the generation, the rest receive it
            rank = int(req["rank"])
            round_ = int(req["round"])
            with self._lock:
                key = ("evict", rank, round_)
                if key not in self._plan_keys:
                    target = [m for m in self._members.values()
                              if m.rank == rank]
                    if not target:
                        # the quarantined rank already exited and the
                        # monitor (or another trigger) dropped it —
                        # hand back the current generation
                        latest = self._plans.get(self._gen)
                        if latest is None:
                            raise ValueError(
                                f"evict: rank {rank} unknown and no "
                                "generation plan exists")
                        self._plan_keys[key] = latest["gen"]
                    else:
                        obs_emit("mesh.integrity_evict", rank=rank,
                                 round=round_, generation=self._gen)
                        plan = self._bump_generation_locked(
                            reason="integrity_evict", at_round=round_,
                            drop_ranks=[rank])
                        self._plan_keys[key] = plan["gen"]
                plan = self._plans[self._plan_keys[key]]
                return {"ok": True, "plan": self._plan_for(plan, mid)}
        if op == "ack":
            with self._lock:
                m = self._members.get(mid)
                if m is not None:
                    m.gen = int(req["gen"])
                    m.last_beat = time.monotonic()
            return {"ok": True}
        if op == "status":
            with self._lock:
                return {
                    "ok": True, "gen": self._gen,
                    "members": {m.mid: {"rank": m.rank, "round": m.round,
                                        "gen": m.gen, "suspect": m.suspect}
                                for m in self._members.values()},
                    "waiters": sorted(self._waiters),
                    "grow_at": self._grow_at,
                    "lost_total": self._lost_total,
                    "rejoined_total": self._rejoined_total,
                    "abort": self._abort,
                }
        raise ValueError(f"unknown op {op!r}")


# ----------------------------------------------------------------------
class ElasticMember:
    """Per-process elastic client: heartbeats + the rebuild rendezvous.

    ``lost_event`` is set the moment a beat reply announces a
    loss-triggered generation (or the coordinator became unreachable
    past ``elastic_timeout_s``) — the collective deadline in
    :func:`guarded_call` polls it."""

    def __init__(self, coordinator_addr: str, rank: int,
                 opts: ElasticOptions,
                 host_coordinator: bool = False,
                 num: int = 0, jax_host: str = "localhost") -> None:
        self.opts = opts
        self.addr = coordinator_addr
        self.rank = rank
        self.mid = f"{socket.gethostname()}:{os.getpid()}:{uuid.uuid4().hex[:6]}"
        self.coordinator: Optional[ElasticCoordinator] = None
        if host_coordinator:
            self.coordinator = ElasticCoordinator(
                coordinator_addr, jax_host, num, opts)
            self.addr = self.coordinator.address
        self.generation = 1
        self.lost_event = threading.Event()
        self.abort_reason = ""
        self._pending: Optional[GenerationPlan] = None
        self._grow_at: Optional[int] = None
        self._suspects: List[int] = []
        self._round = -1
        self._coord_silent_since: Optional[float] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._beat_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def _rpc(self, req: dict, timeout: Optional[float] = None) -> dict:
        timeout = timeout or max(self.opts.timeout_s, 2.0)
        req = {**req, "member": self.mid}
        host, port = self.addr.rsplit(":", 1)
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as s:
            s.sendall((json.dumps(req, separators=(",", ":")) + "\n")
                      .encode("utf-8"))
            f = s.makefile("rb")
            line = f.readline(1 << 16)
        resp = json.loads(line.decode("utf-8"))
        if not resp.get("ok"):
            raise RuntimeError(
                f"elastic coordinator rejected {req.get('op')}: "
                f"{resp.get('error')}")
        return resp

    # ------------------------------------------------------------------
    def start(self) -> "ElasticMember":
        # rank 0 binds the coordinator around the same time the peers
        # say hello — retry connection refusals for a few seconds
        deadline = time.monotonic() + 10.0
        while True:
            try:
                self._rpc({"op": "hello", "rank": self.rank})
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name="cxxnet-elastic-beat", daemon=True)
        self._beat_thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=2.0)
        if self.coordinator is not None:
            self.coordinator.close()

    # ------------------------------------------------------------------
    def report_round(self, round_: int) -> None:
        self._round = int(round_)

    def poll_now(self) -> None:
        """One synchronous beat: round boundaries call this so every
        rank reads the SAME coordinator state at the same boundary
        instead of racing the heartbeat thread's cadence."""
        resp = self._rpc({"op": "beat", "round": self._round})
        self._process_beat(resp)

    def _process_beat(self, resp: dict) -> None:
        with self._lock:
            if resp.get("evicted"):
                # the coordinator declared THIS rank lost while it was
                # stalled — the surviving mesh has re-formed without
                # it.  Fail fast (fatal) rather than wait inside a
                # collective no peer will ever join; capacity re-enters
                # through the elastic_join waiter path.
                if not self.abort_reason:
                    self.abort_reason = (
                        "this rank was evicted from the mesh (declared "
                        "lost while stalled); restart with "
                        "elastic_join=1 to rejoin")
                self.lost_event.set()
                return
            self._suspects = list(resp.get("suspects", ()))
            self._grow_at = resp.get("grow_at")
            if resp.get("abort"):
                self.abort_reason = str(resp["abort"])
                self.lost_event.set()
            change = resp.get("change")
            if change is not None:
                plan = GenerationPlan.from_wire(change)
                if plan.generation > self.generation:
                    self._pending = plan
                    if plan.at_round is None:  # loss: act now
                        self.lost_event.set()

    def _beat_loop(self) -> None:
        while not self._stop.wait(self.opts.heartbeat_s):
            try:
                resp = self._rpc({"op": "beat", "round": self._round},
                                 timeout=max(self.opts.heartbeat_s * 4, 1.0))
            except (OSError, ValueError, RuntimeError) as e:
                # RuntimeError covers an ok=false coordinator reply —
                # the heartbeat daemon must survive any single bad
                # exchange, or this healthy rank gets evicted
                # coordinator unreachable: rank 0 (its host) may be gone
                now = time.monotonic()
                if self._coord_silent_since is None:
                    self._coord_silent_since = now
                elif now - self._coord_silent_since > self.opts.timeout_s:
                    with self._lock:
                        if not self.abort_reason:
                            self.abort_reason = (
                                f"elastic coordinator {self.addr} "
                                f"unreachable ({type(e).__name__}: {e}) — "
                                "rank 0 presumed lost")
                    self.lost_event.set()
                continue
            self._coord_silent_since = None
            self._process_beat(resp)

    # ------------------------------------------------------------------
    def suspects(self) -> List[int]:
        with self._lock:
            return list(self._suspects)

    def pending_plan(self) -> Optional[GenerationPlan]:
        with self._lock:
            return self._pending

    def grow_round(self) -> Optional[int]:
        with self._lock:
            return self._grow_at

    def plan_shrink(self, round_: int) -> GenerationPlan:
        resp = self._rpc({"op": "plan_shrink", "round": int(round_)})
        return GenerationPlan.from_wire(resp["plan"])

    def plan_evict(self, rank: int, round_: int) -> GenerationPlan:
        """Quarantine plan: drop ``rank`` (named corrupt by the
        integrity vote at ``round_``) from the mesh.  Idempotent — all
        survivors call this with the identical verdict and receive the
        same generation."""
        resp = self._rpc({"op": "evict", "rank": int(rank),
                          "round": int(round_)})
        return GenerationPlan.from_wire(resp["plan"])

    def plan_grow(self, round_: int) -> Optional[GenerationPlan]:
        """None when every waiter abandoned the join before the
        boundary fired — the mesh stays as it is."""
        resp = self._rpc({"op": "plan_grow", "round": int(round_)})
        if resp.get("plan") is None:
            return None
        return GenerationPlan.from_wire(resp["plan"])

    def ack_generation(self, plan: GenerationPlan,
                       rank: Optional[int] = None) -> None:
        """Adopt a generation after the rebuild rendezvous succeeded."""
        with self._lock:
            self.generation = plan.generation
            if rank is not None:
                self.rank = rank
            self._pending = None
            self.lost_event.clear()
        try:
            self._rpc({"op": "ack", "gen": plan.generation})
        except (OSError, ValueError):
            pass  # the next beat re-syncs

    def join(self, timeout_s: Optional[float] = None) -> GenerationPlan:
        """Waiter admission: poll until a grow generation assigns this
        member a rank (``elastic_rejoin_s`` bounds the wait)."""
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.opts.rejoin_s)
        join_at = self.opts.join_at
        while True:
            try:
                resp = self._rpc({"op": "join", "join_at": join_at})
            except OSError:
                # the coordinator (rank 0) may not be up yet — a waiter
                # launched alongside (or before) the job keeps polling
                if time.monotonic() > deadline:
                    raise
                time.sleep(self.opts.heartbeat_s)
                continue
            admitted = resp.get("admitted")
            if admitted is not None:
                plan = GenerationPlan.from_wire(admitted)
                self.generation = plan.generation
                self.rank = plan.rank if plan.rank is not None else -1
                return plan
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic join: not admitted within "
                    f"{self.opts.rejoin_s:g}s (grow_at="
                    f"{resp.get('grow_at')})")
            time.sleep(self.opts.heartbeat_s)


# ----------------------------------------------------------------------
def guarded_call(fn, member: Optional[ElasticMember],
                 timeout_s: float = 30.0, what: str = "collective"):
    """Run a blocking (collective-bearing) op under the deadline.

    A confirmed peer loss (``member.lost_event``) raises
    :class:`ReplicaLossError` immediately; past ``timeout_s`` a peer
    the monitor merely *suspects* (missed beats, not yet evicted) is
    presumed lost; a slow-but-alive mesh only logs
    ``mesh.collective_slow`` and keeps waiting.  The abandoned worker
    thread is daemonized — with a truly dead peer gloo errors it out
    shortly (TCP reset), and the rebuild path joins it with a grace
    before tearing the backend down."""
    if member is None:
        return fn()
    box: dict = {}
    done = threading.Event()

    def run() -> None:
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=run, name=f"cxxnet-guarded-{what}",
                         daemon=True)
    t.start()
    guarded_call.last_thread = t  # rebuild joins it with a grace
    t0 = time.monotonic()
    warned = False
    while not done.wait(0.05):
        if member.lost_event.is_set():
            # give the op a moment to surface its own (richer) error
            done.wait(0.5)
            if not done.is_set():
                plan = member.pending_plan()
                raise ReplicaLossError(
                    f"replica lost during {what}"
                    + (f" ({member.abort_reason})" if member.abort_reason
                       else ""),
                    lost=plan.lost_ranks if plan else (),
                    generation=plan.generation if plan else 0,
                    fatal=bool(member.abort_reason),
                )
        elapsed = time.monotonic() - t0
        if elapsed > timeout_s:
            suspects = member.suspects()
            if suspects:
                raise ReplicaLossError(
                    f"{what} exceeded collective_timeout_s="
                    f"{timeout_s:g}s with unresponsive replica(s) "
                    f"{suspects} — presumed lost", lost=suspects,
                    presumed=True,
                )
            if not warned:
                warned = True
                obs_emit("mesh.collective_slow", what=what,
                         elapsed_s=round(elapsed, 3),
                         timeout_s=timeout_s)
    if "error" in box:
        raise box["error"]
    return box.get("value")


guarded_call.last_thread = None


def classify_failure(exc: BaseException,
                     member: Optional[ElasticMember],
                     confirm_s: float = 5.0) -> Optional[ReplicaLossError]:
    """Translate a collective failure into :class:`ReplicaLossError`.

    A SIGKILLed peer usually surfaces as a gloo/coordination-service
    runtime error (TCP reset) before the liveness monitor evicts it —
    wait up to ``confirm_s`` for the monitor to agree, then classify.
    Returns None for errors that are NOT a replica loss (they re-raise
    at the call site)."""
    if isinstance(exc, ReplicaLossError):
        return exc
    if member is None:
        return None
    text = f"{type(exc).__name__}: {exc}"
    # deliberately NARROW: only the collective transport (gloo), the
    # coordination service, and the mesh.replica injection site read as
    # replica loss.  Generic connection errors (a down data source, an
    # HTTP dependency) must surface as themselves, not trigger an
    # endless rebuild loop.  Transport-level resets count only when the
    # error came out of the XLA runtime.
    markers = ("Gloo", "gloo", "coordination service",
               "CoordinationService",
               "mesh.replica")  # the fault-injection site (utils/faults)
    if "XlaRuntimeError" in text and any(
            m in text for m in ("Connection reset", "Connection closed",
                                "Socket closed", "DEADLINE_EXCEEDED",
                                "UNAVAILABLE")):
        markers = markers + ("XlaRuntimeError",)
    if not any(m in text for m in markers):
        return None
    confirmed = member.lost_event.wait(timeout=confirm_s)
    plan = member.pending_plan()
    return ReplicaLossError(
        f"collective failed ({text[:300]}); replica loss "
        + ("confirmed" if confirmed else "presumed"),
        lost=plan.lost_ranks if plan else (),
        generation=plan.generation if plan else 0,
        presumed=not confirmed,
        fatal=bool(member.abort_reason),
    )
