"""Multi-process (multi-host) runtime: the distributed-PS replacement.

Parity target: the reference's distributed mode — ps-lite workers/servers
launched from ``mpi.conf`` with ``param_server = dist`` and data sharded by
``PS_RANK`` (SURVEY §2.7/§2.8, ``/root/reference/src/nnet/nnet_impl-inl.hpp:
376-390``, ``iter_thread_imbin_x-inl.hpp:108-139``).

TPU-native design: there are no parameter servers.  Every process joins one
`jax.distributed` job (GRPC coordination), the device mesh spans all
processes' chips, and gradient exchange is XLA collectives over ICI within a
host/pod and DCN across hosts — the same SPMD program as single-host, just a
bigger mesh.  The reference's ``update_on_server`` maps to sharded optimizer
state (params/updater state sharded over the mesh instead of replicated).

Config keys (set on every process, e.g. by a launcher):

* ``dist_coordinator = host:port`` — process-0 address
  (``jax.distributed.initialize`` coordinator)
* ``dist_num_proc`` — number of processes in the job
* ``dist_proc_id`` — this process's rank

or the corresponding environment variables ``CXN_COORDINATOR`` /
``CXN_NUM_PROC`` / ``CXN_PROC_ID`` (the env route mirrors the reference's
``PS_RANK`` convention).  When none are present this is a no-op single-process
run.  The data iterators independently honor ``dist_num_worker`` /
``dist_worker_rank`` / ``PS_RANK`` for shard-per-worker reading; a launcher
normally sets both groups from the same rank.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import jax

ConfigEntry = Tuple[str, str]

_initialized = False
_resilient_used = False


def resilient_client_used() -> bool:
    """Did this process ever build the resilient (elastic) coordination
    client?  Its error-poll thread cannot be stopped from Python, so
    interpreter-exit destructor order can trip it into a LOG(FATAL)
    abort — the CLI hard-exits (``os._exit``) after a clean flush
    instead of running destructors when this is set."""
    return _resilient_used


def distributed_spec(
    cfg: Sequence[ConfigEntry],
) -> Optional[Tuple[str, int, int]]:
    """Extract (coordinator, num_proc, proc_id) from config or env."""
    coord = os.environ.get("CXN_COORDINATOR")
    num = os.environ.get("CXN_NUM_PROC")
    pid = os.environ.get("CXN_PROC_ID", os.environ.get("PS_RANK"))
    for name, val in cfg:
        if name == "dist_coordinator":
            coord = val
        elif name == "dist_num_proc":
            num = val
        elif name == "dist_proc_id":
            pid = val
    if coord is None and num is None:
        return None
    if coord is None or num is None or pid is None:
        raise ValueError(
            "distributed run needs all of dist_coordinator, dist_num_proc, "
            "dist_proc_id (or CXN_COORDINATOR/CXN_NUM_PROC/CXN_PROC_ID)"
        )
    return coord, int(num), int(pid)


def maybe_init_distributed(cfg: Sequence[ConfigEntry]) -> bool:
    """Join the jax.distributed job if the config asks for one.

    Idempotent; returns True when running multi-process.  Must be called
    before any other JAX API touches the backend.  ``elastic = 1`` confs
    join through the RESILIENT client (non-fatal heartbeat callbacks,
    no shutdown-on-destruction) so a peer death is an error this
    process handles instead of a ``LOG(FATAL)`` that kills it — the
    precondition for the elastic rebuild (doc/parallel.md).
    """
    global _initialized
    spec = distributed_spec(cfg)
    if spec is None:
        return False
    if _initialized:
        return True
    coord, num, pid = spec
    from .elastic import ElasticOptions

    # last-entry-wins, same as every other config key — a CLI override
    # elastic=0 must yield the stock client, not a liveness-blind one
    # with no elastic layer armed on top
    opts = ElasticOptions.from_cfg(cfg)
    init_distributed(coord, num, pid,
                     resilient=opts.elastic or opts.join)
    return True


def init_distributed(coordinator: str, num: int, pid: int,
                     resilient: bool = False,
                     init_timeout: int = 120) -> None:
    """Join (or re-join) a jax.distributed job with explicit arguments.

    ``resilient=True`` builds the coordination-service client by hand
    (same wire protocol) with the changes that make replica loss
    survivable.  The stock client LOG(FATAL)s — terminates this
    process — when the service broadcasts a peer's death, and the
    Python-level ``missed_heartbeat_callback`` escape hatch is unusable
    in this jaxlib (nanobind cannot convert the ``absl::Status``
    argument; invoking it throws ``std::bad_cast`` on whatever thread
    polls).  So the resilient client makes the coordination service
    LIVENESS-BLIND instead: heartbeats so slow that no eviction — and
    therefore no fatal broadcast — ever fires within a training run.
    Failure detection belongs entirely to the elastic layer
    (``parallel/elastic.py``: sub-second application heartbeats + the
    collective deadline) and the gloo data plane (a SIGKILLed peer
    resets its TCP pairs, erroring collectives in milliseconds).
    ``shutdown_on_destruction=False`` plus short client/service
    shutdown timeouts make teardown abandonable: the handles are
    dropped (and their poll threads die) before any late barrier
    failure can be broadcast back.  Re-init after
    :func:`shutdown_distributed` is the elastic-rebuild rendezvous:
    connect blocks until all ``num`` processes arrive."""
    global _initialized
    _enable_cpu_collectives()
    if not resilient:
        jax.distributed.initialize(
            coordinator_address=coordinator, num_processes=num,
            process_id=pid,
        )
        _initialized = True
        return
    from jax._src import distributed as jdist
    from jax._src.lib import xla_extension as xe

    from ..obs import emit as obs_emit

    gs = jdist.global_state
    if gs.client is not None or gs.service is not None:
        raise RuntimeError(
            "init_distributed: a distributed client is already live; "
            "call shutdown_distributed() first")
    if pid == 0:
        port = coordinator.rsplit(":", 1)[1]
        gs.service = xe.get_distributed_runtime_service(
            f"[::]:{port}", num, heartbeat_interval=600,
            max_missing_heartbeats=6, shutdown_timeout=8)
    gs.client = xe.get_distributed_runtime_client(
        coordinator, pid, init_timeout=init_timeout, shutdown_timeout=5,
        heartbeat_interval=600, max_missing_heartbeats=6,
        shutdown_on_destruction=False, use_compression=True)
    obs_emit("mesh.dist_init", coordinator=coordinator, num=num,
             rank=pid, resilient=True)
    gs.client.connect()
    gs.process_id = pid
    gs.num_processes = num
    gs.coordinator_address = coordinator
    _initialized = True
    global _resilient_used
    _resilient_used = True


#: coordination services deliberately kept alive after an elastic
#: teardown: stopping (or destructing) one closes its gRPC socket, and
#: every peer whose old client is still polling it would see the
#: closure as a fatal error and LOG(FATAL).  One tiny idle server per
#: mesh generation is the price of not letting teardown order kill
#: survivors.
_leaked_services: list = []


def shutdown_distributed(timeout_s: float = 10.0,
                         graceful: bool = True) -> bool:
    """Tear down the jax.distributed runtime so it is safe to
    re-initialize IN THIS PROCESS (the elastic rebuild, and the
    re-init regression test).

    ``graceful=True`` (every peer known alive — the regression test,
    planned same-membership teardowns): client disconnect and service
    stop each run on a deadline thread; a step that cannot complete is
    ABANDONED after ``timeout_s``.

    ``graceful=False`` (the elastic rebuild): NO coordination-service
    RPC is issued at all.  A shutdown RPC would start the service-side
    shutdown barrier, the dead peer can never join it, and the barrier
    failure would be broadcast to the surviving peers' still-live
    clients — which treat any poll error as fatal and terminate.  So
    the client handle is simply dropped (its destructor cancels the
    poll thread without RPC — ``shutdown_on_destruction=False``) and
    the service object is intentionally LEAKED (see
    ``_leaked_services``).

    Live backends are dropped afterwards — compiled programs and
    device buffers of the old mesh die with them — and the next
    backend use builds a fresh client against the new distributed
    state.  Returns True when every step completed cleanly."""
    import threading as _threading

    from jax._src import distributed as jdist

    from ..obs import emit as obs_emit

    global _initialized
    gs = jdist.global_state
    client, service = gs.client, gs.service
    gs.client = None
    gs.service = None
    gs.preemption_sync_manager = None
    gs.process_id, gs.num_processes = 0, 1
    gs.coordinator_address = None
    clean = True
    if not graceful:
        if service is not None:
            _leaked_services.append(service)
        if client is not None or service is not None:
            obs_emit("mesh.dist_teardown", graceful=False,
                     leaked_services=len(_leaked_services))
        del client  # destructor cancels the poll thread, no RPC
    else:
        for name, obj in (("client", client), ("service", service)):
            if obj is None:
                continue
            box: dict = {}

            def _run(o=obj, n=name) -> None:
                try:
                    o.shutdown()
                    box[n] = True
                except Exception as e:  # noqa: BLE001 - not fatal
                    box[n] = e

            t = _threading.Thread(target=_run, daemon=True,
                                  name=f"cxxnet-dist-shutdown-{name}")
            t.start()
            t.join(timeout=timeout_s)
            if t.is_alive() or box.get(name) is not True:
                clean = False
                obs_emit("mesh.dist_shutdown_abandoned", what=name,
                         error=(None if t.is_alive()
                                else str(box.get(name))),
                         timed_out=t.is_alive())
    try:
        jax.clear_caches()
    except Exception:  # noqa: BLE001 - older jax spellings
        pass
    from jax._src import api as _api

    _api.clear_backends()
    _initialized = False
    return clean


def distributed_initialized() -> bool:
    return _initialized


def _enable_cpu_collectives() -> None:
    """Arm cross-process CPU collectives (gloo) BEFORE the backend exists.

    The CPU PJRT client is built per-process with a collectives
    implementation baked in; the default (``none``) rejects any SPMD
    program whose mesh spans processes ("Multiprocess computations
    aren't implemented on the CPU backend") — which is exactly the shape
    of a multi-host mesh trainer rehearsed on CPU (a 2-process x
    2-device 2x2 data x model mesh).  Selecting the gloo TCP
    implementation here makes the CPU backend a faithful miniature of
    the TPU pod: one jit program, partitions on every process, XLA
    collectives across them.  No-op when the jax build lacks the flag or
    another platform is primary (TPU/GPU ignore it)."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - older jax: flag absent; keep going
        pass


def process_info() -> Tuple[int, int]:
    """(process_id, process_count) — (0, 1) for single-process runs."""
    try:
        return jax.process_index(), jax.process_count()
    except RuntimeError:
        return 0, 1


def is_primary() -> bool:
    """True on the process that owns checkpoint writes (rank 0)."""
    return process_info()[0] == 0


def agree_on_value(val: int, reduce: str = "min") -> int:
    """Cross-process integer agreement (allgather + min/max reduce).

    Single-process runs return ``val`` unchanged.  Used by the
    checkpoint subsystem so every process resumes from the SAME round
    (``min`` — a round every process can see) and so a preemption signal
    delivered to any one process stops the whole job (``max``)."""
    import numpy as np

    _, count = process_info()
    if count == 1:
        return int(val)
    from jax.experimental import multihost_utils

    vals = np.asarray(
        multihost_utils.process_allgather(np.asarray([val], np.int64))
    ).reshape(-1)
    return int(vals.min() if reduce == "min" else vals.max())


def agree_on_round(local_round: int) -> int:
    """Resume-round consensus: the newest round EVERY process holds a
    valid checkpoint for (-1 when any process has none)."""
    return agree_on_value(local_round, reduce="min")


def any_process_flag(flag: bool) -> bool:
    """True when the flag is set on ANY process (collective)."""
    return bool(agree_on_value(int(bool(flag)), reduce="max"))


def barrier(name: str = "cxxnet_barrier") -> None:
    """Block until every process reaches this point (no-op single-proc).

    Used after rank-0 checkpoint writes so no process races ahead and
    reads (or prunes) a checkpoint before it is fully durable."""
    _, count = process_info()
    if count == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def fetch_array(x) -> "np.ndarray":
    """Global jax.Array → full host ndarray, multi-process safe.

    Replicated arrays (params) read from the local shard; sharded arrays
    are allgathered across processes first.
    """
    import numpy as np

    if not hasattr(x, "sharding") or jax.process_count() == 1:
        return np.asarray(x)
    if x.sharding.is_fully_replicated:
        return np.asarray(x.addressable_shards[0].data)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def fetch_local_rows(x, axis: int = 0) -> "np.ndarray":
    """Global array → this process's rows along ``axis`` (device order).

    ``axis=0`` for batch-major arrays; ``axis=1`` for ``[K, B, ...]``
    scan step-stacks sharded over the batch axis."""
    import numpy as np

    if not hasattr(x, "sharding") or jax.process_count() == 1:
        return np.asarray(x)
    # one shard per row range: replication (e.g. over the model axis) puts
    # identical row blocks on several local devices — keep the first each
    by_start = {}
    for s in x.addressable_shards:
        start = s.index[axis].start or 0
        if start not in by_start:
            by_start[start] = s
    return np.concatenate(
        [np.asarray(by_start[k].data) for k in sorted(by_start)], axis=axis
    )


def global_batch_parts(n: int) -> List[int]:
    """Deterministic split of a global batch over processes (equal shards)."""
    _, count = process_info()
    if n % count != 0:
        raise ValueError(
            f"global batch {n} must divide process count {count}"
        )
    return [n // count] * count
