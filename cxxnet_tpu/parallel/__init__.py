"""Device mesh + sharding: the TPU-native replacement for mshadow-ps.

The reference scales by spawning one pthread + CUDA stream per GPU and
combining gradients through a parameter server
(``/root/reference/src/nnet/nnet_impl-inl.hpp:376-390``,
``/root/reference/src/updater/async_updater-inl.hpp``).  Here the same
``dev=tpu:0-3`` config line builds a ``jax.sharding.Mesh`` and the whole
train step is ONE jitted SPMD program: the batch is sharded over the
``data`` axis, parameters are replicated (or sharded over ``model`` for
tensor parallelism), and XLA inserts the ICI all-reduce that replaces
Push/PullReq — overlapped with backprop by the latency-hiding scheduler,
which subsumes the reference's per-layer WFBP priorities
(``updater_impl-inl.hpp:82``).
"""

from .distributed import (  # noqa: F401
    distributed_initialized,
    distributed_spec,
    init_distributed,
    maybe_init_distributed,
    process_info,
    shutdown_distributed,
)
from .mesh import MeshPlan, make_mesh, parse_device  # noqa: F401
