"""Mesh construction from ``dev=`` config strings.

Grammar parity with the reference device parser
(``/root/reference/src/nnet/nnet_impl-inl.hpp:32-51``):

* ``dev=tpu`` / ``dev=gpu`` / ``dev=cpu`` — one device
* ``dev=tpu:0-3`` — devices 0..3 inclusive
* ``dev=tpu:0,2,5`` — explicit list

The platform word is advisory: confs written for the reference say
``gpu``; on a TPU host the same conf runs on TPU chips, and under the
CPU test harness on virtual CPU devices.  What is honored exactly is the
device *count and ordinals* — ``batch_size`` must divide by the data-axis
size, as in the reference (``nnet_impl-inl.hpp:146-151``).

The mesh is always 2-D ``('data', 'model')``; ``model=1`` gives pure data
parallelism (the reference's only strategy).  ``model_parallel=k`` in the
config splits the devices ``(n/k, k)`` for tensor-parallel layers.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def parse_device(dev: str) -> Tuple[str, List[int]]:
    """``"tpu:0-3"`` → ``("tpu", [0,1,2,3])``; bare platform → ``[0]``."""
    dev = dev.strip()
    if ":" not in dev:
        return dev, [0]
    plat, spec = dev.split(":", 1)
    ids: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if "-" in part:
            lo, hi = part.split("-", 1)
            if int(hi) < int(lo):
                raise ValueError(f"dev={dev!r}: reversed range {part!r}")
            ids.extend(range(int(lo), int(hi) + 1))
        elif part:
            ids.append(int(part))
    if not ids:
        raise ValueError(f"dev={dev!r}: empty device list")
    return plat, ids


@dataclasses.dataclass
class MeshPlan:
    """A resolved mesh plus the shardings the trainer needs."""

    mesh: Mesh
    n_data: int
    n_model: int

    @property
    def n_devices(self) -> int:
        return self.n_data * self.n_model

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def data_sharding(self, axis: int = 0) -> NamedSharding:
        """Batch-major arrays: shard the batch dim over the data axis.

        ``axis=1`` covers step-stacked ``[K, B, ...]`` arrays fed to the
        device-side multi-step scan (NetTrainer.update_scan)."""
        spec = [None] * axis + ["data"]
        return NamedSharding(self.mesh, P(*spec))

    def param_sharding(self, shape: Sequence[int]) -> NamedSharding:
        """Tensor-parallel weight sharding over the ``model`` axis.

        The GSPMD recipe (SURVEY §2.8 TPU mapping): annotate each weight's
        output-feature dimension as sharded and let XLA partition the
        matmuls/convs and insert the collectives.  Layout convention:

        * fullc ``(nout, nin)`` → shard ``nout`` (dim 0)
        * conv HWIO ``(kh, kw, cin_g, cout)`` → shard ``cout`` (dim 3)
        * per-channel 1-D params (bias, prelu slope, BN gamma/beta) →
          shard the channel dim

        A dim that does not divide by the model-axis size is replicated —
        correctness never depends on the annotation, only placement.
        """
        if self.n_model == 1:
            return self.replicated()
        shape = tuple(shape)
        if not shape:
            return self.replicated()
        axis = 3 if len(shape) == 4 else 0
        if shape[axis] % self.n_model == 0:
            spec = [None] * len(shape)
            spec[axis] = "model"
            return NamedSharding(self.mesh, P(*spec))
        return self.replicated()

    def state_sharding(self, shape: Sequence[int]) -> NamedSharding:
        """Optimizer-state sharding: the ``update_on_server=1`` analog.

        The reference moved the SGD step onto the parameter server so each
        worker held no optimizer state (``nnet_ps_server.cpp:83-89``); the
        TPU-native equivalent is ZeRO-1: momentum/Adam state sharded over
        the data axis, each DP rank computing its slice of the update and
        GSPMD all-gathering the result (SURVEY §5 distributed backend
        mapping).  On top of any model-axis placement, the largest
        still-unsharded dim divisible by the data-axis size is sharded.
        """
        base = self.param_sharding(shape)
        if self.n_data == 1 or not shape:
            return base
        spec = list(base.spec) + [None] * (len(shape) - len(base.spec))
        best, best_size = None, 0
        for d, size in enumerate(shape):
            if spec[d] is None and size % self.n_data == 0 and size > best_size:
                best, best_size = d, size
        if best is None:
            return base
        spec[best] = "data"
        return NamedSharding(self.mesh, P(*spec))

    def fsdp_sharding(self, shape: Sequence[int]) -> NamedSharding:
        """ZeRO-3/FSDP parameter placement: the weights THEMSELVES live
        sharded over the data axis (largest divisible dim, on top of any
        model-axis tensor parallelism).

        Under ``jit`` GSPMD then materializes each layer's full weight
        just-in-time with an all-gather in forward/backward and
        reduce-scatters the gradients — per-device parameter memory drops
        ~n_data-fold, the classic FSDP recipe expressed purely as
        sharding annotations (no wrapper modules, no manual collectives).
        Same placement algorithm as ``state_sharding`` — ZeRO-3 is ZeRO-1
        applied to the params too.
        """
        return self.state_sharding(shape)

    def describe(self, zero: int = 0) -> str:
        """One-line layout summary shared by the CLI's train-start line
        and ``task=summary`` (one formatter, so logs and dashboards
        never disagree about the mesh shape)."""
        import jax

        return (f"data={self.n_data} model={self.n_model} zero={zero} "
                f"processes={jax.process_count()}")

    def check_batch(self, batch_size: int) -> None:
        if batch_size % self.n_data != 0:
            raise ValueError(
                f"batch_size={batch_size} must be divisible by the number of "
                f"data-parallel devices ({self.n_data}), as in the reference "
                f"(nnet_impl-inl.hpp:146-151)"
            )


def make_mesh(
    dev: str = "tpu",
    model_parallel: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> MeshPlan:
    """Build the ('data','model') mesh for a ``dev=`` string.

    Ordinals index into the available device list of the matching
    platform when present, else into ``jax.devices()`` (confs written for
    ``gpu`` run unchanged on TPU).
    """
    plat, ids = parse_device(dev)
    if devices is None:
        try:
            pool = jax.devices(plat)
        except RuntimeError:
            pool = jax.devices()
        if ":" not in dev.strip() and jax.process_count() > 1:
            # multi-process job, bare platform word: the mesh spans ALL
            # global devices (each process contributes its local chips —
            # the multi-host semantic; explicit ordinals remain global
            # indices for expert layouts)
            devices = list(pool)
        else:
            try:
                devices = [pool[i] for i in ids]
            except IndexError:
                raise ValueError(
                    f"dev={dev!r} requests device ordinals {ids} but only "
                    f"{len(pool)} devices are available"
                ) from None
    devices = list(devices)
    n = len(devices)
    if model_parallel < 1 or n % model_parallel != 0:
        raise ValueError(
            f"model_parallel={model_parallel} must divide the device count {n}"
        )
    n_model = model_parallel
    n_data = n // n_model
    arr = np.asarray(devices, dtype=object).reshape(n_data, n_model)
    return MeshPlan(mesh=Mesh(arr, ("data", "model")), n_data=n_data, n_model=n_model)
