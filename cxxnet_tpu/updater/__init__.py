"""Updaters: sgd / nag / adam as pure per-tensor update rules.

Each updater is ``init_state(w) -> state`` plus ``apply(w, grad, state,
epoch) -> (new_w, new_state)``, both jit-traceable; the trainer maps them
over the parameter pytree.  This replaces the reference's per-tensor
``IUpdater`` objects (``/root/reference/src/updater/updater.h:22-66``) and
the AsyncUpdater push/pull engine — on TPU the gradients arrive already
all-reduced by the compiler, so the update is just math.

Shard-local contract (the ZeRO weight-update sharding, ROADMAP item 1):
under ``shard_weight_update``/``zero`` the trainer hands ``apply`` a
weight, gradient and state that live SHARDED over the mesh's data axis
— each replica holds (and updates) only its 1/N slice.  Every rule here
is elementwise in (w, g, state), so the math partitions with zero
communication; the lr/momentum schedules are scalars of the traced
``epoch``.  The two exceptions are LARS/LAMB, whose trust ratios need
the layer-global ``||w||``/``||g||`` — those ``jnp.sum`` reductions
become one tiny all-reduce per tensor under GSPMD, inserted by the
partitioner (correct by construction, and still ~1/N memory).  Keep new
updaters elementwise-plus-full-tensor-reductions and sharding keeps
working without edits here.

Update rules (exact parity, including quirks):
* sgd (``sgd_updater-inl.hpp:72-84``): ``m = mom*m - lr*(clip(g) + wd*w);
  w += m`` where ``clip`` also zeroes NaNs, applied only when
  ``clip_gradient != 0`` (the built-in NaN guard, SURVEY §4.5).
* nag (``nag_updater-inl.hpp:62-70``): ``m' = mom*m - lr*(g + wd*w);
  w += (1 + mom)*m' - mom*m``.
* adam (``adam_updater-inl.hpp:13-84``): decay1=0.1, decay2=0.001;
  **wd is subtracted** (``grad -= wd*w``) — reference quirk kept;
  ``lr_t = lr * sqrt(fix2)/fix1`` with ``fix_i = 1-(1-decay_i)^(t+1)``.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp

from .param import UpdaterParam

State = Dict[str, jnp.ndarray]


def _nan_clip(g: jnp.ndarray, bound: float) -> jnp.ndarray:
    g = jnp.where(jnp.isnan(g), 0.0, g)
    return jnp.clip(g, -bound, bound)


class Updater:
    """Base: one instance per weight tensor, carrying its UpdaterParam."""

    type_name = ""

    def __init__(self, tag: str) -> None:
        self.param = UpdaterParam(tag)

    def set_param(self, name: str, val: str) -> None:
        self.param.set_param(name, val)

    def init_state(self, w: jnp.ndarray) -> State:
        raise NotImplementedError

    def apply(
        self, w: jnp.ndarray, g: jnp.ndarray, state: State, epoch: jnp.ndarray
    ) -> Tuple[jnp.ndarray, State]:
        raise NotImplementedError


class SGDUpdater(Updater):
    type_name = "sgd"

    def init_state(self, w):
        return {"m": jnp.zeros_like(w)}

    def apply(self, w, g, state, epoch):
        p = self.param
        lr = p.learning_rate(epoch).astype(w.dtype)
        mom = p.momentum_at(epoch).astype(w.dtype)
        if p.clip_gradient != 0.0:
            g = _nan_clip(g, p.clip_gradient)
        m = mom * state["m"] - lr * (g + p.wd * w)
        return w + m, {"m": m}


class NAGUpdater(Updater):
    type_name = "nag"

    def init_state(self, w):
        return {"m": jnp.zeros_like(w)}

    def apply(self, w, g, state, epoch):
        p = self.param
        lr = p.learning_rate(epoch).astype(w.dtype)
        mom = p.momentum_at(epoch).astype(w.dtype)
        old_m = state["m"]
        m = mom * old_m - lr * (g + p.wd * w)
        return w + (1.0 + mom) * m - mom * old_m, {"m": m}


class AdamUpdater(Updater):
    type_name = "adam"

    def __init__(self, tag: str) -> None:
        super().__init__(tag)
        self.decay1 = 0.1
        self.decay2 = 0.001

    def set_param(self, name: str, val: str) -> None:
        # parity (adam_updater-inl.hpp:56-57): the reference's beta1/beta2
        # ARE the decay rates (beta1=0.1 ≙ conventional beta1=0.9)
        if name == "beta1":
            self.decay1 = float(val)
        elif name == "beta2":
            self.decay2 = float(val)
        else:
            super().set_param(name, val)

    def init_state(self, w):
        return {"m1": jnp.zeros_like(w), "m2": jnp.zeros_like(w)}

    def apply(self, w, g, state, epoch):
        p = self.param
        if p.wd > 0.0:
            g = g - p.wd * w  # reference quirk: wd *subtracted* (adam:77)
        t = jnp.asarray(epoch, jnp.float32)
        fix1 = 1.0 - jnp.power(1.0 - self.decay1, t + 1.0)
        fix2 = 1.0 - jnp.power(1.0 - self.decay2, t + 1.0)
        lr_t = (p.base_lr * jnp.sqrt(fix2) / fix1).astype(w.dtype)
        m1 = state["m1"] + self.decay1 * (g - state["m1"])
        m2 = state["m2"] + self.decay2 * (g * g - state["m2"])
        w = w - lr_t * (m1 / (jnp.sqrt(m2) + 1e-8))
        return w, {"m1": m1, "m2": m2}


class RMSPropUpdater(Updater):
    """RMSProp (Tieleman & Hinton): ``E[g^2] <- rho E[g^2] + (1-rho) g^2;
    w -= lr * g / (sqrt(E[g^2]) + eps)``.

    New scope — the reference ships only sgd/nag/adam (SURVEY §2.3); this
    follows the framework's own conventions: the lr schedule, per-tag
    overrides, NaN-zeroing clip, and ``wd`` added to the gradient all
    behave as in ``sgd``.
    """

    type_name = "rmsprop"

    def __init__(self, tag: str) -> None:
        super().__init__(tag)
        self.rho = 0.95
        self.eps = 1e-8

    def set_param(self, name: str, val: str) -> None:
        if name == "rho":
            self.rho = float(val)
        elif name == "eps":
            self.eps = float(val)
        else:
            super().set_param(name, val)

    def init_state(self, w):
        return {"v": jnp.zeros_like(w)}

    def apply(self, w, g, state, epoch):
        p = self.param
        lr = p.learning_rate(epoch).astype(w.dtype)
        if p.clip_gradient != 0.0:
            g = _nan_clip(g, p.clip_gradient)
        g = g + p.wd * w
        v = self.rho * state["v"] + (1.0 - self.rho) * g * g
        return w - lr * g / (jnp.sqrt(v) + self.eps), {"v": v}


class AdagradUpdater(Updater):
    """Adagrad (Duchi et al.): ``G <- G + g^2; w -= lr g / (sqrt(G) + eps)``.

    New scope (see RMSPropUpdater); same clip/wd/schedule conventions.
    """

    type_name = "adagrad"

    def __init__(self, tag: str) -> None:
        super().__init__(tag)
        self.eps = 1e-8

    def set_param(self, name: str, val: str) -> None:
        if name == "eps":
            self.eps = float(val)
        else:
            super().set_param(name, val)

    def init_state(self, w):
        return {"v": jnp.zeros_like(w)}

    def apply(self, w, g, state, epoch):
        p = self.param
        lr = p.learning_rate(epoch).astype(w.dtype)
        if p.clip_gradient != 0.0:
            g = _nan_clip(g, p.clip_gradient)
        g = g + p.wd * w
        v = state["v"] + g * g
        return w - lr * g / (jnp.sqrt(v) + self.eps), {"v": v}


class LARSUpdater(Updater):
    """LARS (You et al. 2017): momentum SGD with a layer-wise trust
    ratio ``trust_coeff * ||w|| / (||g + wd*w|| + eps)`` scaling the
    learning rate (the wd-folded form doc/updater.md documents).

    New scope for large-batch data-parallel training (the natural
    companion of ``update_period`` gradient accumulation and big
    meshes); same clip/wd/schedule conventions as ``sgd``.
    """

    type_name = "lars"

    def __init__(self, tag: str) -> None:
        super().__init__(tag)
        self.trust_coeff = 0.001
        self.eps = 1e-9

    def set_param(self, name: str, val: str) -> None:
        if name == "trust_coeff":
            self.trust_coeff = float(val)
        elif name == "eps":
            self.eps = float(val)
        else:
            super().set_param(name, val)

    def init_state(self, w):
        return {"m": jnp.zeros_like(w)}

    def apply(self, w, g, state, epoch):
        p = self.param
        lr = p.learning_rate(epoch).astype(w.dtype)
        mom = p.momentum_at(epoch).astype(w.dtype)
        if p.clip_gradient != 0.0:
            g = _nan_clip(g, p.clip_gradient)
        g = g + p.wd * w
        wn = jnp.sqrt(jnp.sum(w.astype(jnp.float32) ** 2))
        gn = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        trust = jnp.where(
            (wn > 0) & (gn > 0),
            self.trust_coeff * wn / (gn + self.eps),
            1.0,
        ).astype(w.dtype)
        m = mom * state["m"] - lr * trust * g
        return w + m, {"m": m}


class LAMBUpdater(Updater):
    """LAMB (You et al. 2019): Adam statistics with a per-layer trust
    ratio — the large-batch optimizer for transformer stacks.

    Conventional ``beta1/beta2`` (0.9 / 0.999 defaults — NOT the
    reference-adam decay spelling); ``wd`` is decoupled (AdamW-style,
    added to the normalized update, not the gradient).
    """

    type_name = "lamb"

    def __init__(self, tag: str) -> None:
        super().__init__(tag)
        self.beta1 = 0.9
        self.beta2 = 0.999
        self.eps = 1e-6

    def set_param(self, name: str, val: str) -> None:
        if name == "beta1":
            self.beta1 = float(val)
        elif name == "beta2":
            self.beta2 = float(val)
        elif name == "eps":
            self.eps = float(val)
        else:
            super().set_param(name, val)

    def init_state(self, w):
        return {"m1": jnp.zeros_like(w), "m2": jnp.zeros_like(w)}

    def apply(self, w, g, state, epoch):
        p = self.param
        lr = p.learning_rate(epoch).astype(jnp.float32)
        if p.clip_gradient != 0.0:
            g = _nan_clip(g, p.clip_gradient)
        gf = g.astype(jnp.float32)
        t = jnp.asarray(epoch, jnp.float32) + 1.0
        m1 = self.beta1 * state["m1"] + (1.0 - self.beta1) * gf
        m2 = self.beta2 * state["m2"] + (1.0 - self.beta2) * gf * gf
        u = (m1 / (1.0 - self.beta1 ** t)) / (
            jnp.sqrt(m2 / (1.0 - self.beta2 ** t)) + self.eps
        )
        u = u + p.wd * w.astype(jnp.float32)
        wn = jnp.sqrt(jnp.sum(w.astype(jnp.float32) ** 2))
        un = jnp.sqrt(jnp.sum(u ** 2))
        trust = jnp.where((wn > 0) & (un > 0), wn / un, 1.0)
        w = w - (lr * trust * u).astype(w.dtype)
        return w, {"m1": m1, "m2": m2}


_UPDATERS = {"sgd": SGDUpdater, "nag": NAGUpdater, "adam": AdamUpdater,
             "rmsprop": RMSPropUpdater, "adagrad": AdagradUpdater,
             "lars": LARSUpdater, "lamb": LAMBUpdater}


def create_updater(type_name: str, tag: str) -> Updater:
    """Factory (parity: ``updater_impl-inl.hpp:18-31``)."""
    if type_name not in _UPDATERS:
        raise ValueError(f"unknown updater type: {type_name!r}")
    return _UPDATERS[type_name](tag)
