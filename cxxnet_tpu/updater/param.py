"""Updater hyper-parameters: learning-rate & momentum schedules, per-tag scoping.

Parity: ``/root/reference/src/updater/param.h``.

* ``epoch`` is the number of mini-batch updates so far
  (``/root/reference/src/updater/updater.h:48-50``), NOT the round.
* lr schedules (``ScheduleEpoch``, param.h:117-137)::

    constant :  lr = base_lr
    expdecay :  lr = base_lr * gamma ** (epoch / step)          (continuous)
    polydecay:  lr = base_lr * (1 + (epoch // step) * gamma) ** -alpha
    factor   :  lr = base_lr * factor ** (epoch // step)

  clamped below by ``minimum_lr``; before ``start_epoch`` lr = base_lr.
* momentum saturation: the reference's in-place ``momentum += (final -
  base)/saturation * epoch + base`` accumulates across calls and is clamped
  at ``final_momentum`` (param.h:130-133); the *intent* — and what is
  implemented here, as a pure function — is a linear ramp from
  ``base_momentum`` to ``final_momentum`` over ``saturation_epoch`` updates.
* per-tag scoping (param.h:146-150): a key ``wmat:lr`` applies only to
  updaters whose tag is ``wmat``; the tag prefix is stripped and the rest
  re-parsed.  ``lr:...``/``eta:...`` prefixes configure the schedule.

All schedule evaluation is a pure function of a traced ``epoch`` scalar so
the whole update rule lives inside one ``jit``.
"""

from __future__ import annotations

import jax.numpy as jnp


class UpdaterParam:
    def __init__(self, tag: str = "") -> None:
        self.tag = tag
        self.base_lr = 0.01
        self.wd = 0.0
        self.momentum = 0.9
        self.lr_schedule = 0  # 0 const, 1 expdecay, 2 polydecay, 3 factor
        self.momentum_schedule = 0
        self.lr_step = 1
        self.lr_gamma = 0.5
        self.lr_alpha = 0.5
        self.lr_factor = 0.1
        self.lr_minimum = 0.00001
        self.start_epoch = 0
        self.base_momentum = 0.5
        self.final_momentum = 0.90
        self.saturation_epoch = 0
        self.clip_gradient = 0.0
        self.silent = 0

    def set_param(self, name: str, val: str) -> None:
        # tag-scoped override: "wmat:lr" applies only when tag == "wmat"
        if self.tag and name.startswith(self.tag) and len(name) > len(self.tag) \
                and name[len(self.tag)] == ":":
            name = name[len(self.tag) + 1:]
        if name in ("lr", "eta"):
            self.base_lr = float(val)
        elif name == "wd":
            self.wd = float(val)
        elif name == "momentum":
            self.momentum = float(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "momentum_schedule":
            self.momentum_schedule = int(val)
        elif name == "clip_gradient":
            self.clip_gradient = float(val)
        elif name == "final_momentum":
            self.final_momentum = float(val)
        elif name == "base_momentum":
            self.base_momentum = float(val)
        elif name == "saturation_epoch":
            self.saturation_epoch = int(val)
        elif name.startswith("lr:") or name.startswith("eta:"):
            sub = name.split(":", 1)[1]
            if sub == "schedule":
                table = {"constant": 0, "expdecay": 1, "polydecay": 2, "factor": 3}
                if val in table:
                    self.lr_schedule = table[val]
            elif sub == "gamma":
                self.lr_gamma = float(val)
            elif sub == "alpha":
                self.lr_alpha = float(val)
            elif sub == "step":
                self.lr_step = int(val)
            elif sub == "factor":
                self.lr_factor = float(val)
            elif sub == "minimum_lr":
                self.lr_minimum = float(val)
            elif sub == "start_epoch":
                self.start_epoch = int(val)

    # --- pure schedule evaluation (jit-safe) ---------------------------
    def learning_rate(self, epoch: jnp.ndarray) -> jnp.ndarray:
        e = jnp.asarray(epoch, jnp.float32)
        if self.lr_schedule == 0:
            lr = jnp.full_like(e, self.base_lr)
        elif self.lr_schedule == 1:
            lr = self.base_lr * jnp.power(self.lr_gamma, e / self.lr_step)
        elif self.lr_schedule == 2:
            lr = self.base_lr * jnp.power(
                1.0 + jnp.floor(e / self.lr_step) * self.lr_gamma, -self.lr_alpha
            )
        elif self.lr_schedule == 3:
            lr = self.base_lr * jnp.power(self.lr_factor, jnp.floor(e / self.lr_step))
        else:
            raise ValueError("unknown lr schedule")
        lr = jnp.maximum(lr, self.lr_minimum)
        if self.start_epoch > 0:
            lr = jnp.where(e < self.start_epoch, self.base_lr, lr)
        return lr

    def momentum_at(self, epoch: jnp.ndarray) -> jnp.ndarray:
        e = jnp.asarray(epoch, jnp.float32)
        if self.momentum_schedule and self.saturation_epoch > 0:
            ramp = self.base_momentum + (
                self.final_momentum - self.base_momentum
            ) * e / self.saturation_epoch
            return jnp.minimum(ramp, self.final_momentum)
        return jnp.full_like(e, self.momentum)
