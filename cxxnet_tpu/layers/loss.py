"""Loss layers: softmax, l2_loss, multi_logistic.

These are self-loop layers in reference configs (``layer[+0] = softmax``).
Each defines ``transform`` (the prediction-time output) and ``loss`` (a
summed scalar) such that ``d loss / d input`` equals the gradient the
reference injects in ``SetGradCPU``:

* softmax — probs; grad ``p - onehot(y)``
  (``loss/softmax_layer-inl.hpp:23-31``)  → loss = Σ cross-entropy
* l2_loss — identity; grad ``x - y``
  (``loss/l2_loss_layer-inl.hpp:22-32``)  → loss = ½ Σ (x-y)²
* multi_logistic — sigmoid; grad ``σ(x) - y``
  (``loss/multi_logistic_layer-inl.hpp``) → loss = Σ BCE-with-logits

The trainer multiplies each loss by ``grad_scale / (batch_size *
update_period)`` (``loss/loss_layer_base-inl.hpp:60-63``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LossLayer, register


@register
class SoftmaxLayer(LossLayer):
    type_name = "softmax"

    def transform(self, x):
        return jax.nn.softmax(x, axis=-1)

    def loss(self, x, labels):
        # labels: integer class ids over x's leading dims — (N,)/(N,1)
        # for classifiers, (N, T) for per-position sequence losses
        # (language models), or (T,) for a single row under the
        # loss_masked vmap
        lab = labels.reshape(x.shape[:-1]).astype(jnp.int32)
        logp = jax.nn.log_softmax(x, axis=-1)
        return -jnp.sum(jnp.take_along_axis(logp, lab[..., None], axis=-1))


@register
class L2LossLayer(LossLayer):
    type_name = "l2_loss"

    def loss(self, x, labels):
        lab = labels.reshape(x.shape).astype(x.dtype)
        return 0.5 * jnp.sum((x - lab) ** 2)


@register
class MultiLogisticLayer(LossLayer):
    type_name = "multi_logistic"

    def transform(self, x):
        return jax.nn.sigmoid(x)

    def loss(self, x, labels):
        lab = labels.reshape(x.shape).astype(x.dtype)
        # BCE with logits; gradient wrt x is sigmoid(x) - lab
        return jnp.sum(
            jnp.maximum(x, 0) - x * lab + jnp.log1p(jnp.exp(-jnp.abs(x)))
        )
