"""PairTest layer: differential testing of two layer implementations.

Parity: ``/root/reference/src/layer/pairtest_layer-inl.hpp`` — config name
``pairtest-<master>-<slave>`` runs both implementations on the same input
with synchronized weights and compares outputs (rel-err 1e-5).  In the
reference this is a runtime harness; here it doubles as a real test
utility: ``compare`` returns the max relative error between master and
slave outputs, and the graph forwards the master's output.
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from .base import Layer, Params, Shape


class PairTestLayer(Layer):
    type_name = "pairtest"

    def __init__(self, master: Layer, slave: Layer) -> None:
        super().__init__()
        self.master = master
        self.slave = slave
        self.is_loss = master.is_loss

    def set_param(self, name, val):
        self.master.set_param(name, val)
        self.slave.set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        out_m = self.master.infer_shape(in_shapes)
        out_s = self.slave.infer_shape(in_shapes)
        if out_m != out_s:
            raise ValueError(
                f"pairtest: master/slave shape mismatch {out_m} vs {out_s}"
            )
        return out_m

    def init_params(self, key, in_shapes) -> Params:
        # master's params are shared with the slave (weight sync at init,
        # pairtest_layer-inl.hpp:40-55)
        return self.master.init_params(key, in_shapes)

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return self.master.apply(params, inputs, train=train, rng=rng, step=step)

    def compare(self, params, inputs, *, rtol_floor: float = 1e-8) -> jnp.ndarray:
        """Max relative error between master and slave outputs (eval mode)."""
        out_m = self.master.apply(params, inputs, train=False)
        out_s = self.slave.apply(params, inputs, train=False)
        errs = []
        for m, s in zip(out_m, out_s):
            denom = jnp.maximum(jnp.abs(m), rtol_floor)
            errs.append(jnp.max(jnp.abs(m - s) / denom))
        return jnp.stack(errs).max()
