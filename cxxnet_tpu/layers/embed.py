"""Token embedding layer for sequence models.

New TPU-first scope — the reference is a CNN framework with no discrete
inputs (SURVEY §5); this follows the framework's own conventions
(config-driven params, per-tag hyperparameter scoping).

``embedding`` config keys:

* ``nvocab`` — vocabulary size (required)
* ``nhidden`` — embedding dimension (required)
* ``pos = none|learned|sin`` — positional encoding added to the token
  embedding: a trained ``(T, D)`` table (tag ``pos``, so ``pos:lr``
  scoping works) or fixed sinusoidal (Vaswani et al. 2017)

Input is a flat ``(N, T)`` node of token ids (the text iterator emits
ids as float32 — exact for any realistic vocab); output is the
``(N, T, D)`` sequence node the attention stack consumes.  The layer
sets ``integer_input`` so the net skips the bf16 compute-dtype cast on
the raw ids (bf16 would corrupt ids above 256).
"""

from __future__ import annotations

import math
from typing import List, Sequence

import jax
import jax.numpy as jnp

from .base import Layer, Params, Shape, register


def sin_pos_table(t: int, d: int) -> jnp.ndarray:
    """Sinusoidal positional encodings, (T, D) f32."""
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    half = (d + 1) // 2
    freq = jnp.exp(
        -math.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half
    )
    ang = pos * freq[None, :]
    table = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
    return table[:, :d]


@register
class EmbeddingLayer(Layer):
    type_name = "embedding"

    #: the net must NOT cast this layer's input to the compute dtype —
    #: token ids above 256 are not exactly representable in bf16
    integer_input = True

    def __init__(self) -> None:
        super().__init__()
        self.nvocab = 0
        self.pos = "none"
        self.decode = 0
        self.decode_window = 0

    def set_param(self, name, val):
        if name == "nvocab":
            self.nvocab = int(val)
        elif name == "pos":
            if val not in ("none", "learned", "sin"):
                raise ValueError(
                    f"embedding: pos must be none|learned|sin, got {val!r}"
                )
            self.pos = val
        elif name == "decode":
            # incremental decoding: positions are absolute (the loop's
            # ``step``), and the learned table spans decode_window so
            # its shape matches the training checkpoint's (T, D)
            self.decode = int(val)
        elif name == "decode_window":
            self.decode_window = int(val)
        else:
            super().set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) != 2:
            raise ValueError(
                "embedding: input must be a flat (N, T) id node "
                f"(input_shape = 1,1,T), got {shape}"
            )
        if self.nvocab <= 0 or self.param.num_hidden <= 0:
            raise ValueError("embedding: set nvocab and nhidden")
        n, t = shape
        return [(n, t, self.param.num_hidden)]

    def _table_len(self, t: int) -> int:
        if self.decode:
            if self.decode_window <= 0:
                raise ValueError(
                    "embedding: decode=1 needs decode_window (the "
                    "training T, so the pos table matches the checkpoint)"
                )
            return self.decode_window
        return t

    def init_params(self, key, in_shapes) -> Params:
        d = self.param.num_hidden
        t = self._table_len(in_shapes[0][1])
        k1, k2 = jax.random.split(key)
        sigma = self.param.init_sigma
        p = {
            "wmat": jax.random.normal(k1, (self.nvocab, d), jnp.float32)
            * sigma
        }
        if self.pos == "learned":
            p["pos"] = jax.random.normal(k2, (t, d), jnp.float32) * sigma
        return p

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        from jax import lax

        x = inputs[0]
        ids = jnp.clip(
            jnp.round(x).astype(jnp.int32), 0, self.nvocab - 1
        )
        table = params["wmat"]
        out = jnp.take(table, ids, axis=0)
        t, d = out.shape[1], out.shape[2]
        if self.decode:
            # absolute positions step..step+t-1 (the decode loop's clock)
            start = jnp.asarray(0 if step is None else step, jnp.int32)
            if self.pos == "learned":
                sl = lax.dynamic_slice(
                    params["pos"].astype(out.dtype), (start, 0), (t, d)
                )
                out = out + sl[None]
            elif self.pos == "sin":
                full = sin_pos_table(self._table_len(t), d)
                sl = lax.dynamic_slice(full, (start, 0), (t, d))
                out = out + sl.astype(out.dtype)[None]
            return [out]
        if self.pos == "learned":
            out = out + params["pos"].astype(out.dtype)[None, :t]
        elif self.pos == "sin":
            out = out + sin_pos_table(t, d).astype(out.dtype)
        return [out]
