"""Dense layers: fullc, fixconn, flatten.

Parity sources:
* fullc — ``/root/reference/src/layer/fullc_layer-inl.hpp`` (``out =
  dot(in, W^T) + bias``; W stored ``(nhidden, nin)``; init fan_in =
  W.shape[1], fan_out = W.shape[0])
* fixconn — ``/root/reference/src/layer/fixconn_layer-inl.hpp`` (frozen
  sparse weight loaded from a ``nrow ncol nnz`` + ``row col val`` text
  file; never updated)
* flatten — ``/root/reference/src/layer/flatten_layer-inl.hpp``
  (image → flat matrix node; here NHWC-ravel instead of NCHW-ravel)
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from .base import Layer, Params, Shape, register


@register
class FullConnectLayer(Layer):
    type_name = "fullc"

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) not in (2, 3):
            raise ValueError(
                "FullcLayer: input needs to be a matrix or sequence node"
            )
        if self.param.num_hidden <= 0:
            raise ValueError("FullcLayer: must set nhidden correctly")
        nin = shape[-1]
        if self.param.num_input_node == 0:
            self.param.num_input_node = nin
        elif self.param.num_input_node != nin:
            raise ValueError("FullcLayer: input hidden nodes inconsistent")
        # sequence nodes (N, T, D) project per position
        return [tuple(shape[:-1]) + (self.param.num_hidden,)]

    def init_params(self, key, in_shapes) -> Params:
        p = self.param
        nin, nout = in_shapes[0][-1], p.num_hidden
        out: Params = {"wmat": p.rand_init_weight(key, (nout, nin), nin, nout)}
        if p.no_bias == 0:
            out["bias"] = jnp.full((nout,), p.init_bias, jnp.float32)
        return out

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        y = x @ params["wmat"].astype(x.dtype).T
        if "bias" in params:
            y = y + params["bias"].astype(x.dtype)
        return [y]


@register
class FixConnectLayer(Layer):
    """fullc with a frozen sparse weight matrix read from a text file."""

    type_name = "fixconn"

    def __init__(self) -> None:
        super().__init__()
        self.fname_weight = "NULL"
        self._wmat: np.ndarray | None = None

    def set_param(self, name, val):
        if name == "fixconn_weight":
            self.fname_weight = val
        super().set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) != 2:
            raise ValueError("FixConnLayer: input needs to be a matrix node")
        if self.param.num_hidden <= 0:
            raise ValueError("FixConnLayer: must set nhidden correctly")
        if self.fname_weight == "NULL":
            raise ValueError("FixConnLayer: must specify fixconn_weight")
        self._wmat = self._load_sparse(self.fname_weight, self.param.num_hidden, shape[1])
        return [(shape[0], self.param.num_hidden)]

    @staticmethod
    def _load_sparse(fname: str, nrow_want: int, ncol_want: int) -> np.ndarray:
        # format parity: fixconn_layer-inl.hpp:40-55
        with open(fname, "r", encoding="utf-8") as f:
            toks = f.read().split()
        nrow, ncol, nnz = int(toks[0]), int(toks[1]), int(toks[2])
        if nrow != nrow_want or ncol != ncol_want:
            raise ValueError("FixConnLayer: fixconn_weight shape does not match architecture")
        w = np.zeros((nrow, ncol), np.float32)
        vals = toks[3:]
        if len(vals) != 3 * nnz:
            raise ValueError("FixConnLayer: fixconn_weight invalid sparse matrix format")
        for k in range(nnz):
            x, y, v = int(vals[3 * k]), int(vals[3 * k + 1]), float(vals[3 * k + 2])
            if not (0 <= x < nrow and 0 <= y < ncol):
                raise ValueError("FixConnLayer: fixconn_weight index exceeds matrix shape")
            w[x, y] = v
        return w

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        assert self._wmat is not None, "infer_shape must run before apply"
        x = inputs[0]
        w = jnp.asarray(self._wmat, x.dtype)
        return [x @ w.T]


@register
class FlattenLayer(Layer):
    type_name = "flatten"

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        n = 1
        for d in shape[1:]:
            n *= d
        return [(shape[0], n)]

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        return [x.reshape(x.shape[0], -1)]
