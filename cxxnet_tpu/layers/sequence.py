"""Sequence layers: multi-head attention, layer norm, sequence pooling.

New TPU-first scope — the reference is a CNN framework with no sequence
axis (SURVEY §5), so these layers have no parity source; they follow the
framework's own conventions (config-driven params, ``(nout, nin)`` weight
layout, NHWC-style batch-major nodes).  Sequence nodes are ``(N, T, D)``
(``input_layout = seq`` with ``input_shape = 1,T,D``).

``attention`` config keys:

* ``nhead`` — number of attention heads (D % nhead == 0)
* ``causal`` — 1 for autoregressive masking
* ``seq_parallel`` — 1 to run **ring attention** over the mesh's
  ``model`` axis (sequence sharded, kv blocks rotating over ICI —
  ``ops/attention.py``); requires T % model_axis == 0. Off the mesh (or
  model axis 1) it falls back to plain attention.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from .base import Layer, Params, Shape, register


@register
class AttentionLayer(Layer):
    type_name = "attention"

    def __init__(self) -> None:
        super().__init__()
        self.nhead = 1
        self.causal = 0
        self.seq_parallel = 0
        self.mesh_plan = None  # bound by the trainer (bind_mesh)

    def set_param(self, name, val):
        if name == "nhead":
            self.nhead = int(val)
        elif name == "causal":
            self.causal = int(val)
        elif name == "seq_parallel":
            self.seq_parallel = int(val)
        else:
            super().set_param(name, val)

    def bind_mesh(self, plan) -> None:
        self.mesh_plan = plan

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) != 3:
            raise ValueError(
                "attention: input must be a sequence node (N, T, D); set "
                "input_layout = seq"
            )
        n, t, d = shape
        if self.nhead <= 0 or d % self.nhead != 0:
            raise ValueError(
                f"attention: nhead={self.nhead} must divide model dim {d}"
            )
        if self.seq_parallel and self.mesh_plan is not None:
            nm = self.mesh_plan.n_model
            if nm > 1 and t % nm != 0:
                raise ValueError(
                    f"attention: seq_parallel needs T={t} divisible by the "
                    f"model axis ({nm})"
                )
        return [tuple(shape)]

    def init_params(self, key, in_shapes) -> Params:
        d = in_shapes[0][2]
        p = self.param
        k1, k2 = jax.random.split(key)
        sigma = p.init_sigma  # framework default 0.01; set via init_sigma
        return {
            # framework (nout, nin) layout: fused qkv then output proj
            "wmat": jax.random.normal(k1, (3 * d, d), jnp.float32) * sigma,
            "bias": jnp.zeros((3 * d,), jnp.float32),
            "wproj": jax.random.normal(k2, (d, d), jnp.float32) * sigma,
            "bproj": jnp.zeros((d,), jnp.float32),
        }

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        from ..ops.attention import mha, ring_self_attention

        x = inputs[0]
        n, t, d = x.shape
        h = self.nhead
        dh = d // h
        qkv = x @ params["wmat"].astype(x.dtype).T + params["bias"].astype(
            x.dtype
        )
        qkv = qkv.reshape(n, t, 3, h, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        plan = self.mesh_plan
        if self.seq_parallel and plan is not None and plan.n_model > 1:
            o = ring_self_attention(
                q, k, v, plan.mesh, "model", causal=bool(self.causal)
            )
        else:
            o = mha(q, k, v, causal=bool(self.causal))
        o = o.reshape(n, t, d)
        return [
            o @ params["wproj"].astype(x.dtype).T
            + params["bproj"].astype(x.dtype)
        ]


@register
class LayerNormLayer(Layer):
    type_name = "layer_norm"

    def __init__(self) -> None:
        super().__init__()
        self.eps = 1e-6

    def set_param(self, name, val):
        if name == "eps":
            self.eps = float(val)
        else:
            super().set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        return [tuple(in_shapes[0])]

    def init_params(self, key, in_shapes) -> Params:
        d = in_shapes[0][-1]
        return {
            "wmat": jnp.ones((d,), jnp.float32),
            "bias": jnp.zeros((d,), jnp.float32),
        }

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        xf = x.astype(jnp.float32)  # stats in f32 under mixed precision
        mu = xf.mean(axis=-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + jnp.float32(self.eps))
        y = y * params["wmat"].astype(jnp.float32) + params["bias"].astype(
            jnp.float32
        )
        return [y.astype(x.dtype)]


@register
class SeqPoolLayer(Layer):
    """Mean-pool the time axis: (N, T, D) -> (N, D) classification head."""

    type_name = "seq_pool"

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) != 3:
            raise ValueError("seq_pool: input must be a sequence node")
        return [(shape[0], shape[2])]

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [inputs[0].mean(axis=1)]
