"""Sequence layers: multi-head attention, layer norm, sequence pooling.

New TPU-first scope — the reference is a CNN framework with no sequence
axis (SURVEY §5), so these layers have no parity source; they follow the
framework's own conventions (config-driven params, ``(nout, nin)`` weight
layout, NHWC-style batch-major nodes).  Sequence nodes are ``(N, T, D)``
(``input_layout = seq`` with ``input_shape = 1,T,D``).

``attention`` config keys:

* ``nhead`` — number of attention heads (D % nhead == 0)
* ``causal`` — 1 for autoregressive masking
* ``seq_parallel`` — sequence/context parallelism over the mesh's
  ``model`` axis (``ops/attention.py``; off the mesh, or with a model
  axis of 1, both fall back to plain attention):
  * ``1`` / ``ring`` — **ring attention**: sequence sharded, kv blocks
    rotate over ICI with a streaming-softmax merge; needs
    T % model_axis == 0.  Scales to any T (never materializes full-T
    scores) and any head count.
  * ``2`` / ``alltoall`` — **Ulysses all-to-all**: two all_to_alls swap
    the sequence sharding for a head sharding, full-sequence attention
    per head subset; needs T % model_axis == 0 AND
    nhead % model_axis == 0.  Two activation collectives vs the ring's
    n kv hops — usually cheaper when heads divide the axis.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from .base import Layer, Params, Shape, register


def _layer_norm(x, w, b, eps: float):
    """Shared layer-norm math: statistics in f32 under mixed precision."""
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + jnp.float32(eps))
    return (
        y * w.astype(jnp.float32) + b.astype(jnp.float32)
    ).astype(x.dtype)


_FLASH_OK: dict = {}


def _flash_works(t: int, tk: int, dh: int, dtype, causal: bool,
                 ring: bool = False) -> bool:
    """Compile probe so ``attn_impl`` can never take down a run (the
    pool/LRN probe discipline, layers/conv.py): keyed on the static
    attention geometry, probing fwd AND bwd of the real (T, Dh).
    ``ring=True`` probes the dynamic-offset lse kernel the flash ring
    uses (per-shard shapes)."""
    key = (t, tk, dh, jnp.dtype(dtype).name, causal, ring)
    if key not in _FLASH_OK:
        from .conv import _run_probe_untraced
        from ..ops.flash import flash_mha, flash_mha_lse

        def probe():
            q = jnp.ones((1, t, 1, dh), dtype)
            k = jnp.ones((1, tk, 1, dh), dtype)
            if ring:
                def f(a):
                    o, lse = flash_mha_lse(
                        a, k, k, jnp.int32(0), jnp.int32(0), causal,
                        512, 512, False,
                    )
                    return o.astype(jnp.float32).sum() + lse.sum() * 1e-3
            else:
                def f(a):
                    return flash_mha(
                        a, k, k, causal, 512, 512, False
                    ).astype(jnp.float32).sum()
            jax.grad(f)(q).block_until_ready()

        _FLASH_OK[key] = _run_probe_untraced(probe)
    return _FLASH_OK[key]


@register
class AttentionLayer(Layer):
    type_name = "attention"

    def __init__(self) -> None:
        super().__init__()
        self.nhead = 1
        self.causal = 0
        self.seq_parallel = 0
        self.attn_impl = "auto"
        self.decode = 0
        self.decode_window = 0
        self.mesh_plan = None  # bound by the trainer (bind_mesh)

    _SP_MODES = {"0": 0, "1": 1, "2": 2, "off": 0, "ring": 1,
                 "alltoall": 2, "a2a": 2}

    def set_param(self, name, val):
        if name == "nhead":
            self.nhead = int(val)
        elif name == "causal":
            self.causal = int(val)
        elif name == "attn_impl":
            if val not in ("auto", "pallas", "xla"):
                raise ValueError(
                    f"attn_impl must be auto|pallas|xla, got {val!r}"
                )
            self.attn_impl = val
        elif name == "seq_parallel":
            if val not in self._SP_MODES:
                raise ValueError(
                    f"seq_parallel must be one of {sorted(self._SP_MODES)},"
                    f" got {val!r}"
                )
            self.seq_parallel = self._SP_MODES[val]
        elif name == "decode":
            # KV-cache incremental decoding (generation): keys/values
            # accumulate in aux state; the loop's ``step`` is the
            # absolute position of this call's first token
            self.decode = int(val)
        elif name == "decode_window":
            self.decode_window = int(val)
        else:
            super().set_param(name, val)

    # XLA mha materializes (B,H,T,T) scores in HBM; past this T the
    # flash kernel's O(T) memory is the difference between running and
    # OOM, and its fused VMEM pipeline wins on step time too.
    _AUTO_FLASH_MIN_T = 1024

    def _local_attn(self, causal_override=None):
        """Per-device full-sequence attention fn ``(q,k,v,causal)->o``.

        ``attn_impl = pallas`` is a hard opt-in (raises if the kernel
        probe fails on this backend); ``auto`` switches to the flash
        kernel for long sequences where the XLA path's full score
        matrix is the memory ceiling; ``xla`` always takes the
        reference path.  On CPU the identical kernel runs in interpret
        mode (tests).
        """
        from ..ops.attention import mha

        def xla_attn(q, k, v, causal=bool(self.causal)):
            return mha(q, k, v, causal=causal)

        if self.attn_impl == "xla":
            return xla_attn

        def flash_attn(q, k, v, causal=bool(self.causal)):
            from ..ops.flash import flash_mha

            interp = jax.default_backend() != "tpu"
            return flash_mha(q, k, v, causal, 512, 512, interp)

        def dispatch(q, k, v, causal=bool(self.causal)):
            from ..ops.flash import _pick_block

            t, tk, dh = q.shape[1], k.shape[1], q.shape[3]
            on_tpu = jax.default_backend() == "tpu"
            if self.attn_impl == "auto":
                # auto never takes the interpret-mode emulation (a silent
                # orders-of-magnitude slowdown off-TPU), and falls back
                # to mha when an odd T would shrink blocks into scalar
                # territory (block 1 kernels compile forever / run slow)
                if (
                    not on_tpu
                    or t < self._AUTO_FLASH_MIN_T
                    or _pick_block(t, 512) < 128
                    or _pick_block(tk, 512) < 128
                ):
                    return xla_attn(q, k, v, causal)
            if on_tpu and not _flash_works(t, tk, dh, q.dtype, causal):
                if self.attn_impl == "pallas":
                    raise RuntimeError(
                        "attention: attn_impl=pallas requested but the "
                        f"flash kernel probe failed for T={t}, Dh={dh}, "
                        f"{q.dtype} on this backend"
                    )
                return xla_attn(q, k, v, causal)
            return flash_attn(q, k, v, causal)

        return dispatch

    def bind_mesh(self, plan) -> None:
        self.mesh_plan = plan

    def init_aux(self, in_shapes):
        """KV cache state for ``decode = 1``: keys/values for all past
        positions, written at the loop's ``step`` offset."""
        if not self.decode:
            return {}
        if self.seq_parallel:
            raise ValueError(
                "attention: decode=1 (single-token KV caching) does not "
                "compose with seq_parallel"
            )
        if not self.causal:
            raise ValueError(
                "attention: decode=1 requires causal=1 — incremental "
                "decoding cannot reproduce bidirectional attention"
            )
        if self.decode_window <= 0:
            raise ValueError(
                "attention: decode=1 needs decode_window (max positions "
                "the cache holds — the training T)"
            )
        n, t, d = in_shapes[0]
        h, dh = self.nhead, d // self.nhead
        w = self.decode_window
        return {
            "kcache": jnp.zeros((n, w, h, dh), jnp.float32),
            "vcache": jnp.zeros((n, w, h, dh), jnp.float32),
        }

    def apply_stateful(self, params, aux, inputs, *, train=False, rng=None,
                       step=None):
        """Incremental attention: write this call's k/v into the cache
        at positions ``step..step+t-1`` and attend q against everything
        up to its own position (the causal rule against the cache)."""
        from jax import lax

        x = inputs[0]
        n, t, d = x.shape
        h, dh = self.nhead, d // self.nhead
        qkv = x @ params["wmat"].astype(x.dtype).T + params["bias"].astype(
            x.dtype
        )
        qkv = qkv.reshape(n, t, 3, h, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        start = jnp.asarray(0 if step is None else step, jnp.int32)
        kc = lax.dynamic_update_slice(
            aux["kcache"], k.astype(jnp.float32), (0, start, 0, 0)
        )
        vc = lax.dynamic_update_slice(
            aux["vcache"], v.astype(jnp.float32), (0, start, 0, 0)
        )
        w = kc.shape[1]
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), kc,
            preferred_element_type=jnp.float32,
        ) * (1.0 / (dh ** 0.5))
        q_pos = start + lax.broadcasted_iota(jnp.int32, (t, w), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (t, w), 1)
        s = jnp.where((k_pos <= q_pos)[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum(
            "bhqk,bkhd->bqhd", p, vc, preferred_element_type=jnp.float32
        ).astype(x.dtype).reshape(n, t, d)
        out = o @ params["wproj"].astype(x.dtype).T + params["bproj"].astype(
            x.dtype
        )
        return [out], {"kcache": kc, "vcache": vc}

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) != 3:
            raise ValueError(
                "attention: input must be a sequence node (N, T, D); set "
                "input_layout = seq"
            )
        n, t, d = shape
        if self.nhead <= 0 or d % self.nhead != 0:
            raise ValueError(
                f"attention: nhead={self.nhead} must divide model dim {d}"
            )
        if self.seq_parallel and self.mesh_plan is not None:
            nm = self.mesh_plan.n_model
            if nm > 1 and t % nm != 0:
                raise ValueError(
                    f"attention: seq_parallel needs T={t} divisible by the "
                    f"model axis ({nm})"
                )
            if nm > 1 and self.seq_parallel == 2 and self.nhead % nm != 0:
                raise ValueError(
                    f"attention: seq_parallel=alltoall needs "
                    f"nhead={self.nhead} divisible by the model axis ({nm})"
                )
        return [tuple(shape)]

    def init_params(self, key, in_shapes) -> Params:
        d = in_shapes[0][2]
        p = self.param
        k1, k2 = jax.random.split(key)
        sigma = p.init_sigma  # framework default 0.01; set via init_sigma
        return {
            # framework (nout, nin) layout: fused qkv then output proj
            "wmat": jax.random.normal(k1, (3 * d, d), jnp.float32) * sigma,
            "bias": jnp.zeros((3 * d,), jnp.float32),
            "wproj": jax.random.normal(k2, (d, d), jnp.float32) * sigma,
            "bproj": jnp.zeros((d,), jnp.float32),
        }

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        from ..ops.attention import mha, ring_self_attention

        x = inputs[0]
        n, t, d = x.shape
        h = self.nhead
        dh = d // h
        qkv = x @ params["wmat"].astype(x.dtype).T + params["bias"].astype(
            x.dtype
        )
        qkv = qkv.reshape(n, t, 3, h, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        plan = self.mesh_plan
        if self.seq_parallel and plan is not None and plan.n_model > 1:
            if self.seq_parallel == 2:
                from ..ops.attention import a2a_self_attention

                o = a2a_self_attention(
                    q, k, v, plan.mesh, "model", causal=bool(self.causal),
                    attn_fn=self._local_attn(),
                )
            elif self.attn_impl == "pallas":
                # flash ring: per-hop (o, lse) pairs from the fused
                # kernel, merged in log space (ops/attention).  Same
                # opt-in discipline as the local pallas path: tiny
                # per-shard blocks and probe failures raise clearly
                # instead of surfacing as Mosaic errors mid-training.
                from ..ops.flash import _pick_block
                from ..ops.attention import ring_self_attention_flash

                ts = t // plan.n_model  # per-shard sequence length
                dh = d // h
                if jax.default_backend() == "tpu":
                    if _pick_block(ts, 512) < 128:
                        raise ValueError(
                            f"attention: seq_parallel=ring "
                            f"attn_impl=pallas needs per-shard T={ts} "
                            f"with a block >= 128; use attn_impl=xla "
                            f"for short shards"
                        )
                    if not _flash_works(
                        ts, ts, dh, q.dtype, bool(self.causal), ring=True
                    ):
                        raise RuntimeError(
                            "attention: attn_impl=pallas requested but "
                            f"the flash ring kernel probe failed for "
                            f"T={ts}, Dh={dh}, {q.dtype} on this backend"
                        )
                o = ring_self_attention_flash(
                    q, k, v, plan.mesh, "model", causal=bool(self.causal),
                    interpret=jax.default_backend() != "tpu",
                )
            else:
                o = ring_self_attention(
                    q, k, v, plan.mesh, "model", causal=bool(self.causal)
                )
        else:
            o = self._local_attn()(q, k, v)
        o = o.reshape(n, t, d)
        return [
            o @ params["wproj"].astype(x.dtype).T
            + params["bproj"].astype(x.dtype)
        ]


@register
class LayerNormLayer(Layer):
    type_name = "layer_norm"

    def __init__(self) -> None:
        super().__init__()
        self.eps = 1e-6

    def set_param(self, name, val):
        if name == "eps":
            self.eps = float(val)
        else:
            super().set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        return [tuple(in_shapes[0])]

    def init_params(self, key, in_shapes) -> Params:
        d = in_shapes[0][-1]
        return {
            "wmat": jnp.ones((d,), jnp.float32),
            "bias": jnp.zeros((d,), jnp.float32),
        }

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        return [_layer_norm(x, params["wmat"], params["bias"], self.eps)]


@register
class SeqPoolLayer(Layer):
    """Mean-pool the time axis: (N, T, D) -> (N, D) classification head."""

    type_name = "seq_pool"

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) != 3:
            raise ValueError("seq_pool: input must be a sequence node")
        return [(shape[0], shape[2])]

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [inputs[0].mean(axis=1)]


@register
class MoELayer(Layer):
    """Mixture-of-experts projection with expert parallelism.

    New TPU-first scope (no reference analog).  ``nexpert`` expert
    projections ``(nhidden, D)`` live in one ``(E, nhidden, D)`` tensor
    whose expert dim is sharded over the mesh ``model`` axis
    (``MeshPlan.param_sharding`` 3-D rule) — GSPMD partitions the expert
    einsums across devices and inserts the combine reduction, which IS
    expert parallelism.  Routing is a softmax gate, optionally top-k
    masked (``topk = 0`` keeps the dense soft mixture).

    Works on flat ``(N, D)`` and sequence ``(N, T, D)`` nodes.
    """

    type_name = "moe"

    def __init__(self) -> None:
        super().__init__()
        self.nexpert = 4
        self.topk = 0

    def set_param(self, name, val):
        if name == "nexpert":
            self.nexpert = int(val)
        elif name == "topk":
            self.topk = int(val)
        else:
            super().set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) not in (2, 3):
            raise ValueError("moe: input must be a matrix or sequence node")
        if self.param.num_hidden <= 0:
            raise ValueError("moe: must set nhidden correctly")
        if self.nexpert < 1 or not (0 <= self.topk <= self.nexpert):
            raise ValueError("moe: need nexpert >= 1 and 0 <= topk <= nexpert")
        return [tuple(shape[:-1]) + (self.param.num_hidden,)]

    def init_params(self, key, in_shapes) -> Params:
        d = in_shapes[0][-1]
        nh = self.param.num_hidden
        e = self.nexpert
        k1, k2 = jax.random.split(key)
        sigma = self.param.init_sigma
        return {
            "wgate": jax.random.normal(k1, (e, d), jnp.float32) * sigma,
            "wmat": jax.random.normal(k2, (e, nh, d), jnp.float32) * sigma,
            "bias": jnp.zeros((e, nh), jnp.float32),
        }

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        wg = params["wgate"].astype(x.dtype)
        wm = params["wmat"].astype(x.dtype)
        b = params["bias"].astype(x.dtype)
        logits = jnp.einsum("...d,ed->...e", x, wg).astype(jnp.float32)
        gate = jax.nn.softmax(logits, axis=-1)
        if self.topk:
            # keep exactly top-k gates (by index, so ties at the threshold
            # never admit extra experts), renormalize; the masked experts'
            # outputs are zero-weighted (FLOPs still run — dense dispatch)
            _, idx = jax.lax.top_k(gate, self.topk)
            mask = jax.nn.one_hot(idx, self.nexpert, dtype=gate.dtype).sum(
                axis=-2
            )
            gate = gate * mask
            gate = gate / jnp.maximum(
                gate.sum(axis=-1, keepdims=True), 1e-30
            )
        gate = gate.astype(x.dtype)
        h = jnp.einsum("...d,eod->...eo", x, wm) + b
        return [jnp.einsum("...e,...eo->...o", gate, h)]


class _PipelineStackLayer(Layer):
    """Shared plumbing for homogeneous block-stack layers that can run as
    a GPipe pipeline over the mesh model axis: the
    ``pipeline_parallel`` / ``n_microbatch`` config keys, mesh binding,
    stage/microbatch divisibility checks, and the
    pipeline-vs-scanned-stack dispatch.  Subclasses define ``nblock``,
    ``_block(p, x)``, and their params stack."""

    def __init__(self) -> None:
        super().__init__()
        self.nblock = 2
        self.pipeline_parallel = 0
        self.n_microbatch = 4
        self.mesh_plan = None

    def set_param(self, name, val):
        if name == "nblock":
            self.nblock = int(val)
        elif name == "pipeline_parallel":
            self.pipeline_parallel = int(val)
        elif name == "n_microbatch":
            self.n_microbatch = int(val)
        else:
            super().set_param(name, val)

    def bind_mesh(self, plan) -> None:
        self.mesh_plan = plan

    def _check_pipeline_shape(self, batch: int) -> None:
        if self.pipeline_parallel and self.mesh_plan is not None:
            nm = self.mesh_plan.n_model
            if nm > 1 and self.nblock % nm != 0:
                raise ValueError(
                    f"{self.type_name}: nblock={self.nblock} must divide "
                    f"over the model axis ({nm} stages)"
                )
            if nm > 1 and batch % self.n_microbatch != 0:
                raise ValueError(
                    f"{self.type_name}: batch {batch} must divide into "
                    f"{self.n_microbatch} microbatches"
                )

    def _apply_stack(self, stack, x):
        """Run the block stack pipelined (when configured on a >1 model
        axis) or as a plain lax.scan — identical math either way."""
        plan = self.mesh_plan
        if self.pipeline_parallel and plan is not None and plan.n_model > 1:
            from ..ops.pipeline import pipeline_apply

            return pipeline_apply(
                self._block, stack, x, plan.mesh,
                n_microbatch=self.n_microbatch, stage_axis="model",
            )

        def body(h, p):
            return self._block(p, h), None

        y, _ = jax.lax.scan(body, x, stack)
        return y


@register
class PipeTransformerLayer(_PipelineStackLayer):
    """A stack of ``nblock`` identical pre-LN transformer blocks runnable
    as a GPipe pipeline (``ops/pipeline.py``) over the mesh model axis.

    Pipeline parallelism over REAL model blocks: each block is
    layer_norm -> multi-head attention -> residual -> layer_norm ->
    gelu-MLP -> residual, exactly the ``transformer_conf`` block
    structure, with all ``nblock`` blocks' parameters living in stacked
    ``(L, ...)`` tensors.  With ``pipeline_parallel = 1`` the stack is
    sharded one-stage-per-device and microbatches stream through the
    gpipe schedule; with 0 the same blocks run as a plain ``lax.scan``
    (identical math — the parity fixture in tests/test_pipeline.py).

    SPMD pipelining requires homogeneous stages (every device runs the
    same program), hence a block *stack* rather than arbitrary layer
    ranges — the same constraint praxis/GSPMD pipelining has.
    """

    type_name = "pipe_transformer"
    f32_tags = frozenset({"ln1_w", "ln1_b", "ln2_w", "ln2_b"})

    def __init__(self) -> None:
        super().__init__()
        self.nhead = 1
        self.causal = 0
        self.ffn_hidden = 0  # default 4*D
        self.eps = 1e-6

    def set_param(self, name, val):
        if name == "nhead":
            self.nhead = int(val)
        elif name == "causal":
            self.causal = int(val)
        elif name == "ffn_hidden":
            self.ffn_hidden = int(val)
        elif name == "eps":
            self.eps = float(val)
        else:
            super().set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) != 3:
            raise ValueError(
                "pipe_transformer: input must be a sequence node (N, T, D)"
            )
        n, t, d = shape
        if self.nhead <= 0 or d % self.nhead != 0:
            raise ValueError(
                f"pipe_transformer: nhead={self.nhead} must divide dim {d}"
            )
        self._check_pipeline_shape(n)
        return [tuple(shape)]

    def init_params(self, key, in_shapes) -> Params:
        d = in_shapes[0][2]
        h = self.ffn_hidden or 4 * d
        l = self.nblock
        sigma = self.param.init_sigma
        k1, k2, k3, k4 = jax.random.split(key, 4)
        return {
            "ln1_w": jnp.ones((l, d), jnp.float32),
            "ln1_b": jnp.zeros((l, d), jnp.float32),
            "ln2_w": jnp.ones((l, d), jnp.float32),
            "ln2_b": jnp.zeros((l, d), jnp.float32),
            "wqkv": jax.random.normal(k1, (l, 3 * d, d), jnp.float32) * sigma,
            "bqkv": jnp.zeros((l, 3 * d), jnp.float32),
            "wproj": jax.random.normal(k2, (l, d, d), jnp.float32) * sigma,
            "bproj": jnp.zeros((l, d), jnp.float32),
            "wff1": jax.random.normal(k3, (l, h, d), jnp.float32) * sigma,
            "bff1": jnp.zeros((l, h), jnp.float32),
            "wff2": jax.random.normal(k4, (l, d, h), jnp.float32) * sigma,
            "bff2": jnp.zeros((l, d), jnp.float32),
        }

    def _block(self, p, x):
        from ..ops.attention import mha

        n, t, d = x.shape
        nh = self.nhead
        h = _layer_norm(x, p["ln1_w"], p["ln1_b"], self.eps)
        qkv = h @ p["wqkv"].T + p["bqkv"]
        qkv = qkv.reshape(n, t, 3, nh, d // nh)
        o = mha(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                causal=bool(self.causal))
        x = x + o.reshape(n, t, d) @ p["wproj"].T + p["bproj"]
        h2 = _layer_norm(x, p["ln2_w"], p["ln2_b"], self.eps)
        f = (jax.nn.gelu(h2 @ p["wff1"].T + p["bff1"])
             @ p["wff2"].T + p["bff2"])
        return x + f

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        stack = {
            k: (v if k in self.f32_tags else v.astype(x.dtype))
            for k, v in params.items()
        }
        return [self._apply_stack(stack, x)]


@register
class PipeMLPLayer(_PipelineStackLayer):
    """A stack of ``nblock`` identical relu-MLP blocks runnable as a
    GPipe pipeline (``ops/pipeline.py``) over the mesh model axis.

    The minimal pipeline-parallel layer: blocks are homogeneous
    (``y = relu(x W_i + b_i)``, width = input dim), their params live in
    one ``(L, D, D)`` stack sharded one-stage-per-device when
    ``pipeline_parallel = 1``, and microbatches stream through the
    stages with activations hopping a ppermute ring.  For pipelining
    real model blocks use ``pipe_transformer``.
    """

    type_name = "pipe_mlp"

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) != 2:
            raise ValueError("pipe_mlp: input must be a matrix node")
        self._check_pipeline_shape(shape[0])
        return [tuple(shape)]

    def init_params(self, key, in_shapes) -> Params:
        d = in_shapes[0][1]
        sigma = self.param.init_sigma
        return {
            "wmat": jax.random.normal(
                key, (self.nblock, d, d), jnp.float32
            ) * sigma,
            "bias": jnp.zeros((self.nblock, d), jnp.float32),
        }

    @staticmethod
    def _block(p, x):
        return jax.nn.relu(x @ p["wmat"] + p["bias"])

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        stack = {
            "wmat": params["wmat"].astype(x.dtype),
            "bias": params["bias"].astype(x.dtype),
        }
        return [self._apply_stack(stack, x)]
