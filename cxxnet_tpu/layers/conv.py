"""Spatial layers: conv, pooling family, LRN, batch-norm.

All operate on NHWC arrays and lower TPU-shaped: conv via
``lax.conv_general_dilated`` (MXU); pooling as shifted-slice max/add trees
(VPU — avoiding reduce_window's select-and-scatter backward); LRN via a
Pallas kernel on TPU.  No im2col-GEMM / mshadow ``pool`` expressions.

Parity sources:
* conv — ``/root/reference/src/layer/convolution_layer-inl.hpp``
  (grouped im2col GEMM; output shape ``(in + 2p - k) // s + 1``; weights
  init with fan_in = Cin/g*kh*kw, fan_out = Cout/g)
* pooling — ``/root/reference/src/layer/pooling_layer-inl.hpp`` (max /
  sum / avg / relu+max; **ceil** output shape
  ``min(in - k + s - 1, in - 1) // s + 1`` with partial edge windows;
  avg always divides by k*k regardless of window truncation)
* insanity_max_pooling — ``/root/reference/src/layer/
  insanity_pooling_layer-inl.hpp`` (train: each source pixel is replaced,
  with prob (1-keep)/4 each, by its up/down/left/right neighbour before a
  normal ceil max-pool; eval: plain max-pool)
* lrn — ``/root/reference/src/layer/lrn_layer-inl.hpp`` (cross-channel:
  ``out = x * (knorm + alpha/n * sum_win(x^2))^-beta``)
* batch_norm — ``/root/reference/src/layer/batch_norm_layer-inl.hpp``
  (per-channel batch stats; **eval also uses current-minibatch stats** —
  a documented reference quirk, doc/layer.md:235-240 — kept for parity)
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .base import Layer, Params, Shape, register


def _ceil_pool_shape(in_size: int, k: int, s: int, p: int = 0) -> int:
    """Reference pooling output size (pooling_layer-inl.hpp:100-104).

    ``p=0`` is the exact reference formula (it has no pooling pad).  With
    ``p>0`` (a framework extension needed for inception-style same-size
    pool branches) the shape follows the caffe convention the reference's
    formula derives from: ceil((in+2p-k)/s)+1, clipped so the last window
    starts inside the (left-padded) input.
    """
    if p == 0:
        return min(in_size - k + s - 1, in_size - 1) // s + 1
    out = (in_size + 2 * p - k + s - 1) // s + 1
    if (out - 1) * s >= in_size + p:
        out -= 1
    return out


def _pool_pad(in_size: int, k: int, s: int, p: int = 0) -> Tuple[int, int]:
    """(left, right) padding so VALID windows realize the ceil shape."""
    out = _ceil_pool_shape(in_size, k, s, p)
    return p, max(0, (out - 1) * s + k - in_size - p)


def _conv_s2d(x, w, s: int, py: int, px: int):
    """Strided conv as space-to-depth + stride-1 conv — mathematically
    exact (MLPerf-style stem-conv rewrite, generalized to any stride).

    A strided conv on a low-channel high-resolution input (GoogLeNet/
    ResNet 7x7 s2, AlexNet 11x11 s4 stems: C_in=3, 224px+) im2cols to a
    GEMM whose K = k·k·3 rows are read at stride s — poor MXU feeding.
    Decomposing tap index dy = s·t + a turns it into a stride-1 conv on
    the s×s space-to-depth input (1/s resolution, s²C channels) with
    the kernel taps regrouped the same way (k not divisible by s
    zero-pads the tail tap rows/cols; input extents not divisible by s
    zero-pad on the right and the junk tail outputs are sliced off):

        y[oy] = Σ_dy x̃[s·oy+dy]·W[dy] = Σ_{t,a} xs_a[oy+t]·W[s·t+a]

    Weights stay (kh, kw, C, O) — checkpoints, updaters, and visitors
    untouched; the regroup is a reshape/transpose autodiff reverses
    exactly.
    """
    kh, kw, c, o = w.shape
    n, h, wd = x.shape[0], x.shape[1], x.shape[2]
    oh = (h + 2 * py - kh) // s + 1
    ow = (wd + 2 * px - kw) // s + 1
    hp, wp = h + 2 * py, wd + 2 * px
    eh, ew = (-hp) % s, (-wp) % s
    xp = jnp.pad(x, ((0, 0), (py, py + eh), (px, px + ew), (0, 0)))
    hq, wq = (hp + eh) // s, (wp + ew) // s
    xs = (
        xp.reshape(n, hq, s, wq, s, c)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(n, hq, wq, s * s * c)
    )
    k2h, k2w = -(-kh // s), -(-kw // s)
    wpad = jnp.pad(w, ((0, k2h * s - kh), (0, k2w * s - kw), (0, 0),
                       (0, 0)))
    ws = (
        wpad.reshape(k2h, s, k2w, s, c, o)
        .transpose(0, 2, 1, 3, 4, 5)
        .reshape(k2h, k2w, s * s * c, o)
    )
    assert hq - k2h + 1 >= oh and wq - k2w + 1 >= ow
    y = lax.conv_general_dilated(
        xs,
        ws,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y[:, :oh, :ow, :]


# --- Winograd F(m x m, 3x3) (Lavin & Gray 2015) -------------------------
#
# Two tile sizes, selected by ``conv_wino``:
#
# * 1 -> F(4x4): 36 taps per 16 outputs = 2.25 MACs/output vs direct's
#   9 (the max FLOP win), transform constants up to |8| — bf16 GEMM
#   operands cost ~1e-2 relative error (the known fp16-Winograd
#   tradeoff; cuDNN's fp16 winograd has the same profile);
# * 2 -> F(2x2): 16 taps per 4 outputs = 4 MACs/output (a 2.25x
#   reduction), transform constants in {0, +-1, 1/2} — error within
#   ~3x of the direct bf16 conv (the tested bound).  The numerics
#   escape hatch.
#
# B^T/A^T products are bf16-exact or near-exact; G carries fractions,
# so U = GwG^T is computed in f32 and cast once.

_WG_F4 = (
    4,
    np.array(
        [
            [4, 0, -5, 0, 1, 0],
            [0, -4, -4, 1, 1, 0],
            [0, 4, -4, -1, 1, 0],
            [0, -2, -1, 2, 1, 0],
            [0, 2, -1, -2, 1, 0],
            [0, 4, 0, -5, 0, 1],
        ],
        np.float32,
    ),
    np.array(
        [
            [1 / 4, 0, 0],
            [-1 / 6, -1 / 6, -1 / 6],
            [-1 / 6, 1 / 6, -1 / 6],
            [1 / 24, 1 / 12, 1 / 6],
            [1 / 24, -1 / 12, 1 / 6],
            [0, 0, 1],
        ],
        np.float32,
    ),
    np.array(
        [
            [1, 1, 1, 1, 1, 0],
            [0, 1, -1, 2, -2, 0],
            [0, 1, 1, 4, 4, 0],
            [0, 1, -1, 8, -8, 1],
        ],
        np.float32,
    ),
)
_WG_F2 = (
    2,
    np.array(
        [
            [1, 0, -1, 0],
            [0, 1, 1, 0],
            [0, -1, 1, 0],
            [0, 1, 0, -1],
        ],
        np.float32,
    ),
    np.array(
        [
            [1, 0, 0],
            [1 / 2, 1 / 2, 1 / 2],
            [1 / 2, -1 / 2, 1 / 2],
            [0, 0, 1],
        ],
        np.float32,
    ),
    np.array(
        [
            [1, 1, 1, 0],
            [0, 1, -1, -1],
        ],
        np.float32,
    ),
)


def _conv_winograd3(x, w, py: int, px: int, variant: int = 1):
    """3x3 stride-1 conv via Winograd F(mxm, 3x3) — fewer MACs per
    output than the 9-tap im2col GEMM XLA:TPU lowers to (no Winograd
    rewrite in XLA; the cuDNN fast path the reference gets for free,
    ``cudnn_convolution_layer-inl.hpp``, re-derived as pure XLA ops).

    Everything is jnp — tile extraction as strided slices, the two
    small (m+2)x(m+2) transforms as f32 einsums (VPU work, fused by
    XLA), and the one heavy contraction as an (m+2)²-way batched GEMM
    in the input dtype with f32 accumulation — so XLA keeps fusing
    around it; no custom-call fence (the round-3 Pallas-pool lesson,
    doc/performance.md "Isolated-kernel wins do not survive fusion").

    Numerics: input/inverse transforms in f32, GEMM operands cast back
    to ``x.dtype`` (see the tile-size tradeoff at the matrices above).
    Autodiff reverses the whole pipeline, so the backward is Winograd
    too (the transposed transforms).
    """
    m, bt, g, at = _WG_F2 if variant == 2 else _WG_F4
    a = m + 2  # input tile edge
    n, h, wd, c = x.shape
    o = w.shape[3]
    oh, ow = h + 2 * py - 2, wd + 2 * px - 2
    th, tw = -(-oh // m), -(-ow // m)
    # padded extent must cover the last tile: m*(t-1) + a
    xp = jnp.pad(
        x,
        ((0, 0), (py, m * th + 2 - h - py), (px, m * tw + 2 - wd - px),
         (0, 0)),
    )
    # d[n, t, u, c, i, j] = xp[n, m*t+i, m*u+j, c]: a*a strided slices
    d = jnp.stack(
        [
            jnp.stack(
                [xp[:, i:i + m * th:m, j:j + m * tw:m, :] for j in range(a)],
                axis=-1,
            )
            for i in range(a)
        ],
        axis=-2,
    )  # (N, th, tw, C, a_i, a_j)
    v = jnp.einsum(
        "ai,ntucij,bj->abntuc",
        bt, d.astype(jnp.float32), bt,
    ).astype(x.dtype)
    u = jnp.einsum(
        "ak,klco,bl->abco",
        g, w.astype(jnp.float32), g,
    ).astype(x.dtype)
    # the MXU part: a² batched (N*th*tw, C) x (C, O) GEMMs
    mm = jnp.einsum(
        "abntuc,abco->abntuo", v, u,
        preferred_element_type=jnp.float32,
    )
    y = jnp.einsum("pa,abntuo,qb->ntupqo", at, mm, at)
    y = y.transpose(0, 1, 3, 2, 4, 5).reshape(n, m * th, m * tw, o)
    return y[:, :oh, :ow, :].astype(x.dtype)


@register
class ConvolutionLayer(Layer):
    type_name = "conv"

    def __init__(self) -> None:
        super().__init__()
        self.conv_s2d = 0  # opt-in space-to-depth rewrite (any stride>1)
        # opt-in Winograd for 3x3 s1 convs: 1 = F(4x4), 2 = F(2x2)
        self.conv_wino = 0

    def set_param(self, name, val):
        if name == "conv_s2d":
            self.conv_s2d = int(val)
        elif name == "conv_wino":
            if val not in ("0", "1", "2"):
                raise ValueError(
                    f"conv_wino must be 0 (off), 1 (F4x4) or 2 (F2x2), "
                    f"got {val!r}"
                )
            self.conv_wino = int(val)
        else:
            super().set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) != 4:
            raise ValueError("ConvolutionLayer: input must be an NHWC image node")
        p = self.param
        n, h, w, c = shape
        if c % p.num_group != 0:
            raise ValueError("input channels must divide group size")
        if p.num_channel <= 0 or p.num_channel % p.num_group != 0:
            raise ValueError("must set nchannel correctly (divisible by ngroup)")
        if p.kernel_height <= 0 or p.kernel_width <= 0:
            raise ValueError("must set kernel_size correctly")
        if p.kernel_width > w + 2 * p.pad_x or p.kernel_height > h + 2 * p.pad_y:
            raise ValueError("kernel size exceeds input")
        if p.num_input_channel == 0:
            p.num_input_channel = c
        elif p.num_input_channel != c:
            raise ValueError("ConvolutionLayer: inconsistent input channels")
        oh = (h + 2 * p.pad_y - p.kernel_height) // p.stride + 1
        ow = (w + 2 * p.pad_x - p.kernel_width) // p.stride + 1
        return [(n, oh, ow, p.num_channel)]

    def init_params(self, key, in_shapes) -> Params:
        p = self.param
        cin_g = in_shapes[0][3] // p.num_group
        # HWIO layout, O grouped in ngroup blocks (XLA feature_group_count)
        shape = (p.kernel_height, p.kernel_width, cin_g, p.num_channel)
        in_num = cin_g * p.kernel_height * p.kernel_width
        out_num = p.num_channel // p.num_group
        out: Params = {"wmat": p.rand_init_weight(key, shape, in_num, out_num)}
        if p.no_bias == 0:
            out["bias"] = jnp.full((p.num_channel,), p.init_bias, jnp.float32)
        return out

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        p = self.param
        x = inputs[0]
        if (self.conv_wino and p.stride == 1 and p.num_group == 1
                and p.kernel_height == 3 and p.kernel_width == 3
                and x.shape[3] >= 8):
            # cin < 8 (e.g. a VGG conv1_1 RGB input) keeps the direct
            # path: the Winograd GEMM contracts over K = cin, and K=3
            # starves the MXU worse than the 9-tap im2col's K=27
            y = _conv_winograd3(x, params["wmat"], p.pad_y, p.pad_x,
                                variant=self.conv_wino)
        elif self.conv_s2d and p.stride > 1 and p.num_group == 1:
            y = _conv_s2d(x, params["wmat"].astype(x.dtype), p.stride,
                          p.pad_y, p.pad_x)
        else:
            y = lax.conv_general_dilated(
                x,
                params["wmat"].astype(x.dtype),
                window_strides=(p.stride, p.stride),
                padding=((p.pad_y, p.pad_y), (p.pad_x, p.pad_x)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=p.num_group,
            )
        if "bias" in params:
            y = y + params["bias"].astype(x.dtype)
        return [y]


def _pool_geometry(h: int, w: int, kh: int, kw: int, s: int, py: int,
                   px: int):
    """((plh, prh), (plw, prw), oh, ow) for the ceil-shape pooling."""
    return (
        _pool_pad(h, kh, s, py),
        _pool_pad(w, kw, s, px),
        _ceil_pool_shape(h, kh, s, py),
        _ceil_pool_shape(w, kw, s, px),
    )


def _pad_for_pool(x, kh, kw, s, py, px, init_val):
    """(padded x, geometry): the common front of every pooling path."""
    geo = _pool_geometry(x.shape[1], x.shape[2], kh, kw, s, py, px)
    (plh, prh), (plw, prw), _, _ = geo
    xp = jnp.pad(
        x,
        ((0, 0), (plh, prh), (plw, prw), (0, 0)),
        constant_values=x.dtype.type(init_val),
    )
    return xp, geo


def _shifted_slices(xp, kh, kw, s, oh, ow):
    """Yield ((dy, dx), window-element slice) over the k*k offsets: the
    strided-slice tree shared by pooling forward and backward."""
    for dy in range(kh):
        for dx in range(kw):
            yield (dy, dx), xp[
                :,
                dy : dy + (oh - 1) * s + 1 : s,
                dx : dx + (ow - 1) * s + 1 : s,
                :,
            ]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _maxpool_eq(x, kh: int, kw: int, s: int, py: int, px: int):
    """Ceil-shape max pooling whose backward is the reference's unpool.

    Forward: max tree over the k*k statically-shifted strided slices
    (see _PoolBase._pool).  Backward (custom VJP): the mshadow
    ``unpool`` rule the reference's pooling layer uses
    (``pooling_layer-inl.hpp:66-75``) — every input position equal to
    its window's max receives that window's gradient:
    ``dx_i = sum_w [x_i == y_w] * g_w``.

    Two reasons to override autodiff here (measured on v5e, GoogLeNet
    b128, doc/performance.md): the max tree's autodiff backward is an
    8-deep select chain that materializes pred masks between fusions
    (~29ms/step across the 13 pools — 40% of the whole train step), and
    its single-winner tie handling differs from the reference.  The
    equality rule is k*k fused compare-multiplies expanded back onto
    the input grid with interior padding (the transpose of the strided
    slice), the same pad+add shape XLA already lowers well for the sum
    pool's backward.
    """
    xp, (_, _, oh, ow) = _pad_for_pool(x, kh, kw, s, py, px, -jnp.inf)
    acc = None
    for _, sl in _shifted_slices(xp, kh, kw, s, oh, ow):
        acc = sl if acc is None else lax.max(acc, sl)
    return acc


def _maxpool_eq_fwd(x, kh, kw, s, py, px):
    y = _maxpool_eq(x, kh, kw, s, py, px)
    return y, (x, y)


def _maxpool_eq_bwd(kh, kw, s, py, px, res, g):
    x, y = res
    h, w = x.shape[1], x.shape[2]
    xp, ((plh, _), (plw, _), oh, ow) = _pad_for_pool(
        x, kh, kw, s, py, px, -jnp.inf
    )
    hp, wp = xp.shape[1], xp.shape[2]
    zero = jnp.zeros((), g.dtype)
    if s > 1:
        dx_p = _unpool_strided(xp, y, g, kh, kw, s, oh, ow)
        dx_ = dx_p[:, plh : plh + h, plw : plw + w, :]
        return (dx_.astype(x.dtype),)
    # note: a gather-style s==1 formulation (read y/g at k*k shifts, one
    # pass at input resolution) measured SLOWER on v5e than this
    # pad-and-add form (2044 vs 2128 img/s GoogLeNet b128) — the pads
    # below fuse better than the 2k²+1-operand compare fusion
    total = None
    for (dy, dx), xw in _shifted_slices(xp, kh, kw, s, oh, ow):
        contrib = jnp.where(xw == y, g, zero)
        # transpose of the strided slice: interior-pad back onto the
        # padded-input grid, then the contributions just add
        exp = lax.pad(
            contrib,
            zero,
            (
                (0, 0, 0),
                (dy, hp - (dy + (oh - 1) * s + 1), s - 1),
                (dx, wp - (dx + (ow - 1) * s + 1), s - 1),
                (0, 0, 0),
            ),
        )
        total = exp if total is None else total + exp
    dx_ = total[:, plh : plh + h, plw : plw + w, :]
    return (dx_.astype(x.dtype),)


def _unpool_strided(xp, y, g, kh, kw, s, oh, ow):
    """The unpool-equality backward for s > 1 as a parity decomposition
    — scatter-free, one write per input position.

    The s == 1 pad-and-add form above interior-pads every one of the
    k*k window contributions back onto the FULL padded-input grid (for
    s=2 each dilated tensor is 3/4 zeros) and adds k*k of them: ~k*k
    full-resolution HBM writes.  Measured on the ResNet-50 stem pool
    (k3 s2 on 112x112x64, b128) that single pool's backward cost
    ~9 ms/step (doc/performance.md bisection).

    Strided pooling makes the transpose cheap instead: input row
    p = s*m + r (parity r = p mod s) collects contributions only from
    window elements dy ≡ r (mod s), shifted by t = (dy-r)/s in window
    index: ``sub_r[m] = sum_t c[r+s*t][m-t]``.  So build the s*s parity
    subgrids at window resolution (each 1/s² of the input area, at most
    ceil(k/s)² terms), then interleave them with one reshape.  Total
    traffic ~ k² window-size reads + one input-size write, vs k²
    input-size writes.
    """
    zero = jnp.zeros((), g.dtype)
    hp, wp = xp.shape[1], xp.shape[2]
    ohp = -(-hp // s)  # ceil: parity subgrids must cover every p < hp
    owp = -(-wp // s)
    contrib = {
        off: jnp.where(xw == y, g, zero)
        for off, xw in _shifted_slices(xp, kh, kw, s, oh, ow)
    }
    n, c = g.shape[0], g.shape[3]
    rows = []
    for ry in range(s):
        cols = []
        for rx in range(s):
            acc = None
            for dy in range(ry, kh, s):
                for dx in range(rx, kw, s):
                    t, u = (dy - ry) // s, (dx - rx) // s
                    # c[dy,dx][m-t, n-u] → pad t/u zeros in front, out to
                    # (ohp, owp) behind (window-resolution tensors: cheap)
                    term = lax.pad(
                        contrib[(dy, dx)],
                        zero,
                        (
                            (0, 0, 0),
                            (t, ohp - oh - t, 0),
                            (u, owp - ow - u, 0),
                            (0, 0, 0),
                        ),
                    )
                    acc = term if acc is None else acc + term
            cols.append(
                acc
                if acc is not None
                else jnp.zeros((n, ohp, owp, c), g.dtype)
            )
        rows.append(jnp.stack(cols, axis=3))  # (N, ohp, owp, s, C)
    big = jnp.stack(rows, axis=2)  # (N, ohp, s, owp, s, C)
    # interleave: p = s*m + ry, q = s*n + rx
    big = big.reshape(n, ohp * s, owp * s, c)
    return big[:, :hp, :wp, :]


_maxpool_eq.defvjp(_maxpool_eq_fwd, _maxpool_eq_bwd)


_PALLAS_PBWD_OK: dict = {}


def _pallas_pool_bwd_works(k: int, pad: int, nchannel: int, dtype) -> bool:
    """Compile probe for the stride-1 one-pass backward kernel."""
    key = (k, pad, int(nchannel), jnp.dtype(dtype).name)
    if key not in _PALLAS_PBWD_OK:
        from ..ops.maxpool import maxpool_bwd_s1

        def probe():
            v0 = jnp.ones((2, k + 2, k + 2, key[2]), dtype)
            y0 = _maxpool_eq(v0, k, k, 1, pad, pad)
            maxpool_bwd_s1(v0, y0, y0, k, pad).block_until_ready()

        _PALLAS_PBWD_OK[key] = _run_probe_untraced(probe)
    return _PALLAS_PBWD_OK[key]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _maxpool_eq_pb(x, k: int, pad: int, interpret: bool):
    """Stride-1 max pooling: XLA forward tree (cheap, fuses well) with
    the one-pass Pallas backward (``ops/maxpool.maxpool_bwd_s1``) —
    ``pool_impl = pallas_bwd``.  Same unpool-equality semantics as
    ``_maxpool_eq``; that path is the pairtest golden."""
    return _maxpool_eq(x, k, k, 1, pad, pad)


def _maxpool_eq_pb_fwd(x, k, pad, interpret):
    y = _maxpool_eq(x, k, k, 1, pad, pad)
    return y, (x, y)


def _maxpool_eq_pb_bwd(k, pad, interpret, res, g):
    from ..ops.maxpool import maxpool_bwd_s1

    x, y = res
    return (maxpool_bwd_s1(x, y, g.astype(x.dtype), k, pad, interpret),)


_maxpool_eq_pb.defvjp(_maxpool_eq_pb_fwd, _maxpool_eq_pb_bwd)


_PALLAS_POOL_OK: dict = {}


def _run_probe_untraced(fn) -> bool:
    """Run a compile probe on a worker thread.

    Probes fire while the net is being jit-traced (layer ``apply`` is
    where the impl choice lives); JAX trace contexts are thread-local,
    so a worker thread executes the probe eagerly — really compiling
    and running the kernel — instead of tracing junk into the outer
    program and failing spuriously (``block_until_ready`` on a tracer),
    which would silently disable every Pallas kernel inside real nets.
    """
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(1) as ex:
        try:
            ex.submit(fn).result(timeout=300)
            return True
        except Exception:  # pragma: no cover - backend-specific
            return False


def _pallas_pool_works(kh, kw, s, py, px, nchannel, dtype) -> bool:
    """Compile probe so ``pool_impl=auto`` can never take down a run
    (same discipline as the LRN kernel's probe): keyed on the full
    static config + channel count + dtype, probing fwd AND bwd."""
    key = (kh, kw, s, py, px, int(nchannel), jnp.dtype(dtype).name)
    if key not in _PALLAS_POOL_OK:
        from ..ops.maxpool import maxpool_fused

        def probe():
            v0 = jnp.ones((2, kh + s, kw + s, key[5]), dtype)
            jax.grad(
                lambda v: maxpool_fused(v, kh, kw, s, py, px)
                .astype(jnp.float32).sum()
            )(v0).block_until_ready()

        _PALLAS_POOL_OK[key] = _run_probe_untraced(probe)
    return _PALLAS_POOL_OK[key]


class _PoolBase(Layer):
    """Shared ceil-shape pooling over NHWC (shifted-slice tree, see _pool)."""

    def __init__(self) -> None:
        super().__init__()
        self.pool_impl = "auto"  # auto = XLA; pallas is explicit opt-in

    def set_param(self, name, val):
        if name == "pool_impl":
            if val not in ("auto", "pallas", "pallas_bwd", "xla"):
                raise ValueError(
                    f"pool_impl must be auto|pallas|pallas_bwd|xla, "
                    f"got {val!r}"
                )
            self.pool_impl = val
        else:
            super().set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        (shape,) = in_shapes
        if len(shape) != 4:
            raise ValueError(f"{self.type_name}: input must be an NHWC image node")
        p = self.param
        if p.kernel_height <= 0 or p.kernel_width <= 0:
            raise ValueError("must set kernel_size correctly")
        n, h, w, c = shape
        if p.kernel_width > w + 2 * p.pad_x or p.kernel_height > h + 2 * p.pad_y:
            raise ValueError("kernel size exceeds input")
        return [
            (
                n,
                _ceil_pool_shape(h, p.kernel_height, p.stride, p.pad_y),
                _ceil_pool_shape(w, p.kernel_width, p.stride, p.pad_x),
                c,
            )
        ]

    def _pool(self, x: jnp.ndarray, reducer, init_val) -> jnp.ndarray:
        """Pooling as a max/add tree over k*k statically-shifted strided
        slices — NOT ``lax.reduce_window``.

        TPU-shaped on purpose: the backward of ``reduce_window(max)`` is
        select-and-scatter, which XLA lowers poorly on TPU (orders of
        magnitude slower than the forward for overlapping windows, e.g.
        the stride-1 3x3 pools in every inception block).  A shifted
        max/add tree autodiffs to pad + select chains: pure VPU work,
        and XLA fuses the whole tree.
        """
        p = self.param
        kh, kw, s = p.kernel_height, p.kernel_width, p.stride
        xp, (_, _, oh, ow) = _pad_for_pool(
            x, kh, kw, s, p.pad_y, p.pad_x, init_val
        )
        acc = None
        for _, sl in _shifted_slices(xp, kh, kw, s, oh, ow):
            acc = sl if acc is None else reducer(acc, sl)
        return acc

    def _use_pallas(self, nchannel: int, dtype) -> bool:
        """``pool_impl = pallas`` is explicit opt-in; ``auto`` never
        chooses the kernel: it wins isolated microbenchmarks (2.39 vs
        3.26 ms for the b128 inception pool, fwd+bwd) but embedding 9
        pool kernels in the scanned train step regressed XLA compile
        time pathologically on the v5e AOT runtime, and stride>1 needs
        a strided slice Mosaic lowers as an unsupported gather
        (doc/performance.md).  Opt-in still goes through the compile
        probe on TPU so a bad geometry degrades to the XLA path with a
        warning instead of taking down the run."""
        if self.pool_impl != "pallas":
            return False
        if jax.default_backend() != "tpu":
            return True  # interpret mode, works on any backend
        p = self.param
        if _pallas_pool_works(p.kernel_height, p.kernel_width, p.stride,
                              p.pad_y, p.pad_x, nchannel, dtype):
            return True
        import warnings

        warnings.warn(
            f"{self.type_name}: pool_impl=pallas requested but the kernel "
            f"probe failed for k=({p.kernel_height},{p.kernel_width}) "
            f"s={p.stride} C={nchannel}; using the XLA path"
        )
        return False

    def _max_pool(self, x: jnp.ndarray) -> jnp.ndarray:
        """Max pooling with the unpool-equality backward: the XLA
        expression (``_maxpool_eq``) by default, the fused Pallas
        kernel (``ops/maxpool.py``) under ``pool_impl = pallas``, or
        XLA forward + the one-pass Pallas backward for stride-1 pools
        under ``pool_impl = pallas_bwd`` — identical semantics,
        pair-tested."""
        p = self.param
        if self.pool_impl == "pallas_bwd":
            eligible = (
                p.stride == 1
                and p.kernel_height == p.kernel_width
                and p.pad_y == p.pad_x
                and p.pad_y * 2 == p.kernel_height - 1  # same-size only
            )
            if eligible:
                interp = jax.default_backend() != "tpu"
                if interp or _pallas_pool_bwd_works(
                    p.kernel_height, p.pad_y, x.shape[-1], x.dtype
                ):
                    return _maxpool_eq_pb(
                        x, p.kernel_height, p.pad_y, interp
                    )
            import warnings

            warnings.warn(
                f"{self.type_name}: pool_impl=pallas_bwd "
                + ("probe failed"
                   if eligible else
                   "needs a same-size stride-1 pool (odd k, pad=(k-1)/2)")
                + f" for k=({p.kernel_height},{p.kernel_width}) "
                f"s={p.stride} pad=({p.pad_y},{p.pad_x}) "
                f"C={x.shape[-1]}; using the XLA path"
            )
        if self._use_pallas(x.shape[-1], x.dtype):
            from ..ops.maxpool import maxpool_fused

            interp = jax.default_backend() != "tpu"  # forced-on off-TPU
            return maxpool_fused(
                x, p.kernel_height, p.kernel_width, p.stride, p.pad_y,
                p.pad_x, interp,
            )
        return _maxpool_eq(
            x, p.kernel_height, p.kernel_width, p.stride, p.pad_y, p.pad_x
        )


@register
class MaxPoolingLayer(_PoolBase):
    type_name = "max_pooling"

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [self._max_pool(inputs[0])]


@register
class SumPoolingLayer(_PoolBase):
    type_name = "sum_pooling"

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [self._pool(inputs[0], lax.add, 0.0)]


@register
class AvgPoolingLayer(_PoolBase):
    type_name = "avg_pooling"

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        p = self.param
        # parity: divide by full k*k even for truncated edge windows
        scale = 1.0 / (p.kernel_height * p.kernel_width)
        return [self._pool(inputs[0], lax.add, 0.0) * scale]


@register
class ReluMaxPoolingLayer(_PoolBase):
    type_name = "relu_max_pooling"

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [self._max_pool(jax.nn.relu(inputs[0]))]


@register
class InsanityPoolingLayer(_PoolBase):
    type_name = "insanity_max_pooling"

    def __init__(self) -> None:
        super().__init__()
        self.p_keep = 1.0

    def set_param(self, name, val):
        if name == "keep":
            self.p_keep = float(val)
        else:
            super().set_param(name, val)

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        if train and rng is not None and self.p_keep < 1.0:
            # jitter each source pixel to a neighbour with prob (1-keep)/4
            # per direction, border-clamped (insanity_pooling:70-100)
            flag = jax.random.uniform(rng, x.shape, x.dtype)
            up = jnp.concatenate([x[:, :1], x[:, :-1]], axis=1)
            down = jnp.concatenate([x[:, 1:], x[:, -1:]], axis=1)
            left = jnp.concatenate([x[:, :, :1], x[:, :, :-1]], axis=2)
            right = jnp.concatenate([x[:, :, 1:], x[:, :, -1:]], axis=2)
            d = (1.0 - self.p_keep) / 4.0
            x = jnp.where(
                flag < self.p_keep,
                x,
                jnp.where(
                    flag < self.p_keep + d,
                    up,
                    jnp.where(
                        flag < self.p_keep + 2 * d,
                        down,
                        jnp.where(flag < self.p_keep + 3 * d, left, right),
                    ),
                ),
            )
        return [self._max_pool(x)]


_PALLAS_LRN_OK: dict = {}


def _pallas_lrn_works(nchannel: int, dtype) -> bool:
    """Compile probe so ``lrn_impl=auto`` can never take down a run on a
    backend whose Pallas lowering is broken/unavailable.

    Keyed on ``(channel count, dtype)`` and probed at the layer's real
    channel width: a backend that compiles the aligned 128-lane case can
    still reject the 64- or 192-lane blocks GoogLeNet actually runs.
    """
    key = (int(nchannel), jnp.dtype(dtype).name)
    if key not in _PALLAS_LRN_OK:
        from ..ops.lrn import lrn

        def probe():
            lrn(jnp.ones((8, key[0]), dtype), 5, 1e-4, 0.75, 1.0
                ).block_until_ready()

        _PALLAS_LRN_OK[key] = _run_probe_untraced(probe)
    return _PALLAS_LRN_OK[key]


@register
class LRNLayer(Layer):
    type_name = "lrn"

    def __init__(self) -> None:
        super().__init__()
        self.nsize = 3
        self.alpha = 0.001
        self.beta = 0.75
        self.knorm = 1.0
        self.impl = "auto"  # auto = XLA; pallas is explicit opt-in

    def set_param(self, name, val):
        if name == "local_size":
            self.nsize = int(val)
        elif name == "alpha":
            self.alpha = float(val)
        elif name == "beta":
            self.beta = float(val)
        elif name == "knorm":
            self.knorm = float(val)
        elif name == "lrn_impl":
            if val not in ("auto", "pallas", "xla", "matmul"):
                raise ValueError(
                    f"lrn_impl must be auto|pallas|xla|matmul, got {val!r}"
                )
            self.impl = val
        else:
            super().set_param(name, val)

    def _use_pallas(self, nchannel: int, dtype) -> bool:
        """``lrn_impl = pallas`` is explicit opt-in.  ``auto`` stays on
        the XLA path: embedding the kernel in the scanned GoogLeNet
        train step regressed XLA compile from ~47s to >25min on the
        v5e AOT runtime (same pathology as the pool kernel,
        doc/performance.md), and the measured step-time difference
        vs lrn_xla was ~0 — LRN is ~3.5ms of a 60ms step.  Opt-in
        still goes through the compile probe on TPU so an unsupported
        shape degrades to lrn_xla with a warning, not a crash."""
        if self.impl != "pallas":
            return False
        if jax.default_backend() != "tpu":
            return True  # interpret mode, works on any backend
        if _pallas_lrn_works(nchannel, dtype):
            return True
        import warnings

        warnings.warn(
            f"lrn: lrn_impl=pallas requested but the kernel probe failed "
            f"for C={nchannel} {jnp.dtype(dtype).name}; using lrn_xla"
        )
        return False

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        return [tuple(in_shapes[0])]

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        from ..ops.lrn import lrn, lrn_matmul, lrn_xla

        x = inputs[0]
        if self._use_pallas(x.shape[-1], x.dtype):
            interp = jax.default_backend() != "tpu"  # forced-on off-TPU
            y = lrn(x, self.nsize, self.alpha, self.beta, self.knorm, interp)
        elif self.impl == "matmul":
            y = lrn_matmul(x, self.nsize, self.alpha, self.beta, self.knorm)
        else:
            y = lrn_xla(x, self.nsize, self.alpha, self.beta, self.knorm)
        return [y]


@register
class BatchNormLayer(Layer):
    type_name = "batch_norm"

    def __init__(self) -> None:
        super().__init__()
        self.init_slope = 1.0
        self.init_bias_bn = 0.0
        self.eps = 1e-10
        self.bn_eval = "batch"  # reference parity; "running" for EMA stats
        self.bn_momentum = 0.9
        self.bn_stats = "twopass"  # "onepass": E[x^2]-E[x]^2, one read

    def set_param(self, name, val):
        if name == "init_slope":
            self.init_slope = float(val)
        elif name == "init_bias":
            self.init_bias_bn = float(val)
        elif name == "eps":
            self.eps = float(val)
        elif name == "bn_eval":
            if val not in ("batch", "running"):
                raise ValueError("bn_eval must be batch or running")
            self.bn_eval = val
        elif name == "bn_momentum":
            self.bn_momentum = float(val)
        elif name == "bn_stats":
            if val not in ("twopass", "onepass"):
                raise ValueError("bn_stats must be twopass or onepass")
            self.bn_stats = val
        else:
            super().set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        return [tuple(in_shapes[0])]

    def init_params(self, key, in_shapes) -> Params:
        ch = in_shapes[0][-1]
        return {
            "wmat": jnp.full((ch,), self.init_slope, jnp.float32),
            "bias": jnp.full((ch,), self.init_bias_bn, jnp.float32),
        }

    def init_aux(self, in_shapes):
        """EMA statistics state (only with ``bn_eval = running``).

        The reference always normalized with *current-minibatch* stats,
        even at eval (doc/layer.md:235-240 caveat) — that stays the
        default; ``bn_eval = running`` upgrades eval to the standard
        moving-average statistics carried as trainer aux state."""
        if self.bn_eval != "running":
            return {}
        ch = in_shapes[0][-1]
        return {
            "rmean": jnp.zeros((ch,), jnp.float32),
            "rvar": jnp.ones((ch,), jnp.float32),
        }

    def _normalize(self, x, mean, var, params):
        inv = lax.rsqrt(var + jnp.float32(self.eps))
        slope = params["wmat"].astype(jnp.float32)
        bias = params["bias"].astype(jnp.float32)
        return ((x.astype(jnp.float32) - mean) * inv * slope + bias).astype(
            x.dtype
        )

    def _batch_stats(self, x):
        # statistics always in f32: bf16 mean/var loses too many mantissa
        # bits over a 100k-element reduction
        axes = tuple(range(x.ndim - 1))  # all but channel
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        if self.bn_stats == "onepass":
            # one read of x: E[x^2]-E[x]^2, both reductions fuse into a
            # single pass (the two-pass form serializes: var needs mean).
            # f32 accumulation over activations in [-5,5] keeps ~7
            # significant digits — fine for BN, and each step's stats are
            # recomputed so no error accumulates across steps.
            var = jnp.maximum(jnp.mean(xf * xf, axis=axes) - mean * mean,
                              0.0)
        else:
            var = jnp.mean((xf - mean) ** 2, axis=axes)
        return mean, var

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        mean, var = self._batch_stats(x)
        return [self._normalize(x, mean, var, params)]

    def apply_stateful(self, params, aux, inputs, *, train=False, rng=None,
                       step=None):
        """(outs, new_aux): batch stats + EMA update in train, running
        stats at eval.  Only routed when init_aux returned state."""
        x = inputs[0]
        if train:
            mean, var = self._batch_stats(x)
            mom = jnp.float32(self.bn_momentum)
            new_aux = {
                "rmean": aux["rmean"] * mom + (1.0 - mom) * mean,
                "rvar": aux["rvar"] * mom + (1.0 - mom) * var,
            }
            return [self._normalize(x, mean, var, params)], new_aux
        return [
            self._normalize(x, aux["rmean"], aux["rvar"], params)
        ], aux
