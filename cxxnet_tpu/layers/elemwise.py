"""Elementwise layers: activations, leaky-ReLU family, dropout, bias.

Parity sources:
* activations — ``/root/reference/src/layer/activation_layer-inl.hpp`` +
  functor definitions in ``/root/reference/src/layer/op.h:21-103``
* xelu — ``/root/reference/src/layer/xelu_layer-inl.hpp`` (slope 1/b, b=5)
* prelu — ``/root/reference/src/layer/prelu_layer-inl.hpp`` (learnable
  per-channel slope, train-time multiplicative slope noise, slope mask
  clamped to [0, 1])
* insanity — ``/root/reference/src/layer/insanity_layer-inl.hpp``
  (randomized leaky ReLU: per-element slope 1/u, u ~ U[lb, ub] at train,
  midpoint at eval, annealed toward the midpoint over
  [calm_start, calm_end])
* dropout — ``/root/reference/src/layer/dropout_layer-inl.hpp`` (inverted
  dropout, ``threshold`` = drop probability, self-loop)
* bias — ``/root/reference/src/layer/bias_layer-inl.hpp`` (additive bias
  over flat nodes, self-loop capable)
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from .base import Layer, Params, Shape, register


class _UnaryLayer(Layer):
    """1-in/1-out shape-preserving elementwise layer."""

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        return [tuple(in_shapes[0])]


@register
class SigmoidLayer(_UnaryLayer):
    type_name = "sigmoid"

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [jax.nn.sigmoid(inputs[0])]


@register
class TanhLayer(_UnaryLayer):
    type_name = "tanh"

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [jnp.tanh(inputs[0])]


@register
class ReluLayer(_UnaryLayer):
    type_name = "relu"

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [jax.nn.relu(inputs[0])]


@register
class SoftplusLayer(_UnaryLayer):
    """``softplus`` parses in the reference (layer.h:331) but its factory
    has no case and errors out (layer_impl-inl.hpp:76); here it works."""

    type_name = "softplus"

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [jax.nn.softplus(inputs[0])]


@register
class GeluLayer(_UnaryLayer):
    """Gaussian error linear unit (transformer blocks; no reference
    analog — the reference predates it)."""

    type_name = "gelu"

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [jax.nn.gelu(inputs[0])]


@register
class XeluLayer(_UnaryLayer):
    """Leaky ReLU with negative slope ``1/b`` (xelu_layer-inl.hpp:17-45)."""

    type_name = "xelu"

    def __init__(self) -> None:
        super().__init__()
        self.b = 5.0

    def set_param(self, name, val):
        if name == "b":
            self.b = float(val)
        else:
            super().set_param(name, val)

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        return [jnp.where(x > 0, x, x / self.b)]


def _channel_axis(shape: Shape) -> int:
    """Per-channel axis: C for NHWC images, feature for flat nodes.

    Mirrors the reference's ``size(1) == 1 ? size(3) : size(1)`` dispatch
    (prelu_layer-inl.hpp:68-73) translated to NHWC/flat layouts.
    """
    return len(shape) - 1


@register
class PReluLayer(_UnaryLayer):
    type_name = "prelu"

    def __init__(self) -> None:
        super().__init__()
        self.init_slope = 0.25
        self.init_random = 0
        self.random = 0.0

    def set_param(self, name, val):
        if name == "init_slope":
            self.init_slope = float(val)
        elif name == "random_slope":
            self.init_random = int(val)
        elif name == "random":
            self.random = float(val)
        else:
            super().set_param(name, val)

    def init_params(self, key, in_shapes) -> Params:
        ch = in_shapes[0][_channel_axis(in_shapes[0])]
        if self.init_random:
            slope = self.init_slope * jax.random.uniform(key, (ch,), jnp.float32)
        else:
            slope = jnp.full((ch,), self.init_slope, jnp.float32)
        # tagged "bias" so bias:lr / bias:wd overrides apply, matching the
        # reference's ApplyVisitor tag (prelu_layer-inl.hpp:60-62)
        return {"bias": slope}

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        slope = params["bias"].astype(x.dtype)
        bshape = [1] * x.ndim
        bshape[_channel_axis(x.shape)] = -1
        mask = jnp.broadcast_to(slope.reshape(bshape), x.shape)
        if train and self.random > 0 and rng is not None:
            noise = 1.0 + (jax.random.uniform(rng, x.shape, x.dtype) * 2.0 - 1.0) * self.random
            mask = mask * noise
        mask = jnp.clip(mask, 0.0, 1.0)
        return [jnp.where(x > 0, x, x * mask)]


@register
class InsanityLayer(_UnaryLayer):
    """Randomized leaky ReLU (RReLU).

    Train: per-element slope ``1/u`` with ``u ~ U[lb, ub]``; eval: slope
    ``2/(lb+ub)``.  The reference anneals ``[lb, ub]`` toward the midpoint
    between ``calm_start`` and ``calm_end`` forward calls via an in-place
    recurrence (insanity_layer-inl.hpp:60-75); here the anneal is the
    equivalent *linear* ramp of the interval endpoints over the same step
    range, expressed as a pure function of the step counter so it can live
    inside ``jit``.
    """

    type_name = "insanity"

    def __init__(self) -> None:
        super().__init__()
        self.lb = 5.0
        self.ub = 10.0
        self.calm_start = 0
        self.calm_end = 0

    def set_param(self, name, val):
        if name == "lb":
            self.lb = float(val)
        elif name == "ub":
            self.ub = float(val)
        elif name == "calm_start":
            self.calm_start = int(val)
        elif name == "calm_end":
            self.calm_end = int(val)
        else:
            super().set_param(name, val)

    def _interval(self, step: Optional[jnp.ndarray]):
        lb, ub = self.lb, self.ub
        if self.calm_end <= self.calm_start or step is None:
            return jnp.float32(lb), jnp.float32(ub)
        mid = (lb + ub) / 2.0
        t = jnp.clip(
            (step - self.calm_start) / (self.calm_end - self.calm_start), 0.0, 1.0
        ).astype(jnp.float32)
        return lb + (mid - lb) * t, ub + (mid - ub) * t

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        lb, ub = self._interval(step)
        if train and rng is not None:
            u = jax.random.uniform(rng, x.shape, x.dtype) * (ub - lb) + lb
        else:
            u = (lb + ub) / 2.0
        return [jnp.where(x > 0, x, x / u)]


@register
class DropoutLayer(_UnaryLayer):
    type_name = "dropout"

    def __init__(self) -> None:
        super().__init__()
        self.threshold = 0.0

    def set_param(self, name, val):
        if name == "threshold":
            self.threshold = float(val)
            if not (0.0 <= self.threshold < 1.0):
                raise ValueError("DropoutLayer: invalid dropout threshold")
        else:
            super().set_param(name, val)

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        x = inputs[0]
        if not train or self.threshold <= 0.0 or rng is None:
            return [x]
        pkeep = 1.0 - self.threshold
        mask = jax.random.bernoulli(rng, pkeep, x.shape)
        return [jnp.where(mask, x / pkeep, jnp.zeros_like(x))]


@register
class BiasLayer(_UnaryLayer):
    """Additive per-feature bias over flat nodes (bias_layer-inl.hpp)."""

    type_name = "bias"

    def infer_shape(self, in_shapes):
        self._check_arity(in_shapes, 1)
        if len(in_shapes[0]) != 2:
            raise ValueError("BiasLayer: input must be a flat matrix node")
        return [tuple(in_shapes[0])]

    def init_params(self, key, in_shapes) -> Params:
        return {"bias": jnp.full((in_shapes[0][1],), self.param.init_bias, jnp.float32)}

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [inputs[0] + params["bias"].astype(inputs[0].dtype)]
